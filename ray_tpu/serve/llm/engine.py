"""Continuous-batching LLM inference engine — the Serve-on-TPU data plane.

The serving problem on TPU is a compile-boundary problem: XLA programs
are shape-specialized, so a naive server that launches one `generate`
per request (or per ad-hoc batch) either retraces constantly or decodes
in lockstep where every short request pays for the longest one
(models/llama.py::generate — the static path this engine replaces).
Podracer (arXiv:2104.06272) and RLAX (arXiv:2512.06392) both land on
the same answer: keep ONE fixed-shape compiled program fed continuously.

Design — a bounded set of compiled programs, everything else is data:

- A fixed pool of ``B = num_slots`` decode slots sharing one KV cache
  ``[L, B, S, n_kv, head_dim]``. Per-slot position/last-token/active
  state are device arrays with fixed shapes.
- ONE jitted decode tick advances all live slots together
  (models/llama.py::decode_step with the slot-active mask: dead slots
  ride through the program but their KV writes are dropped). The tick
  runs `decode_block` steps per dispatch through an internal lax.scan —
  still one compiled program — to amortize host dispatch/readback on
  tunneled TPU backends.
- Jitted prefill at a small set of padded prompt-length buckets; the
  resulting per-layer KV lands in the shared cache at a slot index via
  one `dynamic_update_slice` (insert-at-slot). One compiled program per
  bucket, so a mixed workload traces exactly
  ``len(prefill_buckets) + 1`` engine programs — `trace_count` exposes
  the number for the compile-guard test. Workloads that adopt migrated
  or tier-promoted KV add exactly ONE more (the fixed-shape adopt
  scatter, shared by disagg migration and tier promotes).
- Slot eviction/recycling is host-side bookkeeping: EOS / stop-token /
  max_tokens free the slot, the next queued request prefills into it.
  Stale KV beyond a recycled slot's new position is harmless — decode
  masks positions > pos and overwrites each position before ever
  attending to it.

Greedy decoding is token-identical to per-request
`models.llama.generate`: padding columns contribute exact zeros through
the masked softmax, so bucket-padded prefill and the shared-cache
decode reproduce the static path bit-for-bit (pinned by
tests/test_serve_llm.py::test_greedy_parity_*).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Shapes of the engine's compiled programs (all static)."""

    num_slots: int = 8              # B: concurrent sequences in flight
    max_seq_len: int = 512          # S: shared KV cache length per slot
    # Padded prompt lengths; a prompt compiles into the smallest bucket
    # that holds it. Keep this SHORT — each bucket is one XLA program.
    prefill_buckets: Tuple[int, ...] = (32, 64, 128)
    eos_id: Optional[int] = None    # config-level end-of-sequence token
    # Decode steps per tick dispatch (lax.scan inside the ONE tick
    # program). >1 amortizes host dispatch/readback — decisive on
    # tunneled TPU backends (~tens of ms per round trip) — at the cost
    # of up to K-1 speculative tokens per finished slot (computed, then
    # discarded host-side; parity is unaffected because truncation
    # happens at the same stop condition single-stepping would hit) and
    # admission latency of one block.
    decode_block: int = 1
    # KV layout. "dense": one [S] stripe per slot (the PR-1 layout).
    # "paged": a fixed pool of [kv_block_size]-row blocks shared by all
    # slots through per-slot block tables (serve/llm/kv_cache.py) —
    # short requests stop reserving max_seq rows, and the prefix cache
    # can skip prefill for shared prompt prefixes. Both layouts are
    # token-exact for greedy decoding and trace the same number of
    # programs (block tables are data, not shape).
    kv_layout: str = "dense"
    # None -> GlobalConfig.serve_kv_block_size (RAY_TPU_-overridable).
    kv_block_size: Optional[int] = None
    # Pool size; None -> num_slots * (max_seq_len / kv_block_size), the
    # dense equivalent (no memory saving, full parity). Undersize it to
    # oversubscribe HBM: admission queues on exhaustion, never crashes.
    num_kv_blocks: Optional[int] = None
    prefix_cache: bool = True       # paged only: prompt-prefix reuse
    # Speculative decoding (paged only; armed by constructing the
    # engine with draft_params/draft_config): the draft proposes
    # spec_k - 1 tokens per round, one paged verify step accepts the
    # longest target-agreeing prefix — 1..spec_k tokens per round with
    # greedy parity by construction. None -> GlobalConfig.serve_spec_k.
    spec_k: Optional[int] = None
    # Batch-lane preemption hysteresis: interactive pressure must hold
    # preempt_hold_s before a batch decode is checkpointed, and grants
    # are spaced by preempt_cooldown_s (observability/control.py gate).
    # None -> GlobalConfig.serve_preempt_{hold,cooldown}_s.
    preempt_hold_s: Optional[float] = None
    preempt_cooldown_s: Optional[float] = None
    # Tiered KV spill (kv_cache.KVTierManager): prefix-cache evictions
    # gather their HBM rows into a host-RAM tier (object-store overflow
    # when a cluster is attached) and re-admissions promote them back
    # through the adopt scatter when the PromoteCostModel favors the
    # transfer over recompute. None -> on for paged + prefix_cache
    # engines (both migration programs already exist; spill adds no
    # trace). Forced off otherwise.
    kv_spill: Optional[bool] = None
    kv_host_tier_bytes: Optional[int] = None    # None -> GlobalConfig
    # PromoteCostModel knobs, milliseconds; None -> GlobalConfig
    # serve_kv_adopt_cost_*/serve_kv_prefill_cost_per_token_ms.
    kv_adopt_cost_fixed_ms: Optional[float] = None
    kv_adopt_cost_per_block_ms: Optional[float] = None
    kv_prefill_cost_per_token_ms: Optional[float] = None

    def __post_init__(self):
        from ray_tpu._private.config import GlobalConfig

        if self.decode_block < 1:
            raise ValueError("decode_block must be >= 1")
        if not self.prefill_buckets:
            raise ValueError("need at least one prefill bucket")
        if self.spec_k is None:
            object.__setattr__(self, "spec_k",
                               int(GlobalConfig.serve_spec_k))
        if self.spec_k < 2:
            raise ValueError("spec_k must be >= 2 (one draft proposal "
                             "plus the bonus target token)")
        if self.preempt_hold_s is None:
            object.__setattr__(
                self, "preempt_hold_s",
                float(GlobalConfig.serve_preempt_hold_s))
        if self.preempt_cooldown_s is None:
            object.__setattr__(
                self, "preempt_cooldown_s",
                float(GlobalConfig.serve_preempt_cooldown_s))
        b = tuple(sorted(set(int(x) for x in self.prefill_buckets)))
        object.__setattr__(self, "prefill_buckets", b)
        if b[-1] > self.max_seq_len:
            raise ValueError(
                f"largest prefill bucket {b[-1]} exceeds max_seq_len "
                f"{self.max_seq_len}")
        if self.kv_layout not in ("dense", "paged"):
            raise ValueError(
                f"kv_layout must be 'dense' or 'paged', got "
                f"{self.kv_layout!r}")
        if self.kv_spill is None:
            object.__setattr__(
                self, "kv_spill",
                self.kv_layout == "paged" and self.prefix_cache)
        elif self.kv_spill and (self.kv_layout != "paged"
                                or not self.prefix_cache):
            raise ValueError(
                "kv_spill requires kv_layout='paged' with "
                "prefix_cache=True (the spill hook rides prefix-cache "
                "eviction)")
        if self.kv_host_tier_bytes is None:
            object.__setattr__(
                self, "kv_host_tier_bytes",
                int(GlobalConfig.serve_kv_host_tier_bytes))
        for name, knob in (
                ("kv_adopt_cost_fixed_ms",
                 GlobalConfig.serve_kv_adopt_cost_fixed_ms),
                ("kv_adopt_cost_per_block_ms",
                 GlobalConfig.serve_kv_adopt_cost_per_block_ms),
                ("kv_prefill_cost_per_token_ms",
                 GlobalConfig.serve_kv_prefill_cost_per_token_ms)):
            if getattr(self, name) is None:
                object.__setattr__(self, name, float(knob))
        if self.kv_block_size is None:
            object.__setattr__(self, "kv_block_size",
                               int(GlobalConfig.serve_kv_block_size))
        if self.kv_layout == "paged":
            bs = self.kv_block_size
            if bs < 1:
                raise ValueError("kv_block_size must be >= 1")
            if self.max_seq_len % bs:
                raise ValueError(
                    f"max_seq_len {self.max_seq_len} must be a multiple "
                    f"of kv_block_size {bs} (block tables tile the "
                    f"sequence exactly)")
            bad = [x for x in b if x % bs]
            if bad:
                raise ValueError(
                    f"prefill buckets {bad} must be multiples of "
                    f"kv_block_size {bs} (suffix KV scatters whole "
                    f"blocks)")
            if self.num_kv_blocks is not None and self.num_kv_blocks < 1:
                raise ValueError("num_kv_blocks must be >= 1")

    @property
    def max_blocks_per_slot(self) -> int:
        return self.max_seq_len // self.kv_block_size

    @property
    def pool_blocks(self) -> int:
        if self.num_kv_blocks is not None:
            return self.num_kv_blocks
        return self.num_slots * self.max_blocks_per_slot


@dataclasses.dataclass
class Request:
    """One generation request (token-id domain; tokenization is the
    caller's concern)."""

    prompt: Sequence[int]
    max_tokens: int = 64
    temperature: float = 0.0
    stop: Tuple[int, ...] = ()      # tokens that halt WITHOUT being emitted
    # Streaming hook: called as on_token(request_id, token_id) from the
    # engine loop as each token lands.
    on_token: Optional[Callable[[int, int], None]] = None
    # SLO lane: "interactive" requests are admitted first and, under
    # pressure, may preempt "batch" decodes (whose checkpoints resume
    # later — see LLMEngine.preempt).
    slo: str = "interactive"
    # Stop after prefill + the first sampled token and export the KV
    # state (handle.kv_state) instead of decoding — the disaggregated
    # prefill tier's mode (serve/llm/disagg). Paged layout only.
    prefill_only: bool = False
    # Paged + prefix-cache engines: admit prompts longer than the
    # largest bucket by prefilling bucket-sized chunks through the
    # prefix cache (each chunk's blocks are cached, the next chunk
    # prefix-hits them), one chunk per scheduler step — so interactive
    # admissions interleave instead of stalling behind one long prefill.
    chunked_prefill: bool = False
    # Cost-accounting identity: whose ledger row this request bills to
    # (observability/accounting.py). The schema is ready for the
    # multiplexing roadmap item; until then callers that don't care
    # all bill to "default".
    tenant: str = "default"


class RequestHandle:
    """Host-side view of a submitted request; completion is an Event."""

    def __init__(self, request_id: int, request: Request):
        self.request_id = request_id
        self.request = request
        self.tokens: List[int] = []
        self.submitted_at = time.monotonic()
        self.admitted_at: Optional[float] = None
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        # Wall-clock mirror of submitted_at: lifecycle spans need
        # epoch timestamps (timeline rows), latency math stays
        # monotonic.
        self.submitted_wall = time.time()
        # "eos" | "stop" | "length" | "prefill" | "cancelled"
        self.finish_reason: Optional[str] = None
        # Exported KV checkpoint (kv_cache.KVState): set by prefill_only
        # completion and by preemption; consumed by submit_adopted /
        # readmission.
        self.kv_state: Optional[Any] = None
        # Prompt positions THIS engine actually prefilled (suffix after
        # prefix-cache hits and tier promotes; summed across chunks).
        # len(prompt) - prefilled_tokens is the prefill work avoided —
        # the bench's FLOPs-avoided numerator and its pacing input.
        self.prefilled_tokens = 0
        # Request-scoped tracing: the TraceContext active on the
        # submitting thread (the replica's llm.server_call span) plus a
        # pre-allocated span id for this request's llm.request span —
        # the scheduler thread records phases with no ambient context,
        # so kv.promote / kv.migrate / phase spans all parent under the
        # same explicit id.
        self.trace: Optional[Any] = None
        self.trace_span_id: Optional[str] = None
        # Cost accounting (observability/accounting.py): attached at
        # submit when the plane is enabled, integrated by the scheduler
        # thread, finalized + folded at finish. None when disabled.
        self.meter: Optional[Any] = None
        self._done = threading.Event()
        self._engine: Optional["LLMEngine"] = None
        self._chunk_ends: List[int] = []   # chunked-prefill boundaries
        self._chunk_idx = 0
        self._adopted_submit = False   # arrived via submit_adopted

    def done(self) -> bool:
        return self._done.is_set()

    def cancel(self) -> bool:
        """Cancel the request: queued handles finish immediately with
        finish_reason "cancelled"; a handle live in a decode slot is
        torn down by the scheduler thread at its next step boundary,
        releasing the slot's paged blocks and prefix-cache refs (the
        reclaim path for client-abandoned requests). Returns False if
        the request already finished."""
        if self._done.is_set() or self._engine is None:
            return False
        return self._engine.cancel(self)

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not finished in {timeout}s")
        return self.tokens

    # Latency accounting for the bench (seconds).
    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at

    @property
    def tpot_s(self) -> Optional[float]:
        """Mean per-output-token latency after the first token."""
        if self.finished_at is None or self.first_token_at is None:
            return None
        n = len(self.tokens)
        if n <= 1:
            return 0.0
        return (self.finished_at - self.first_token_at) / (n - 1)


class _Slot:
    __slots__ = ("handle", "uses")

    def __init__(self):
        self.handle: Optional[RequestHandle] = None
        self.uses = 0


class LLMEngine:
    """Slot-based continuous-batching engine over a Llama param set.

    Host-side scheduler + two families of jitted device programs
    (`_insert` per prefill bucket, `_tick` for the decode step). Thread
    model: `submit()` is thread-safe; `step()`/`run()` must be driven by
    a single scheduler thread (serve/llm/deployment.py runs one per
    replica).
    """

    def __init__(self, params: Any, model_config: Any,
                 engine_config: Optional[EngineConfig] = None,
                 rng_seed: int = 0,
                 draft_params: Any = None,
                 draft_config: Any = None):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ray_tpu.models.llama import init_kv_cache, init_paged_kv_cache

        self.params = params
        self.model_config = model_config
        self.config = engine_config or EngineConfig()
        c = self.config
        B = c.num_slots

        # Device state (fixed shapes for the engine's whole lifetime).
        self._paged = c.kv_layout == "paged"
        if self._paged:
            from ray_tpu.serve.llm.kv_cache import (BlockAllocator,
                                                    KVTierManager,
                                                    PrefixCache,
                                                    PromoteCostModel)

            self._cache = init_paged_kv_cache(
                model_config, c.pool_blocks, c.kv_block_size)
            # HBM bytes per block (k + v rows across all layers) — the
            # byte-accounting basis for allocator/prefix/tier stats.
            block_bytes = int(
                (self._cache["k"].nbytes + self._cache["v"].nbytes)
                // self._cache["k"].shape[1])
            self._allocator = BlockAllocator(c.pool_blocks,
                                             c.kv_block_size,
                                             block_bytes=block_bytes)
            self._prefix = (PrefixCache(self._allocator)
                            if c.prefix_cache else None)
            # Per-slot block tables (host copy is the truth; the device
            # sees it as a plain [B, max_blocks] int32 argument — data,
            # not shape, so tables never retrace anything).
            self._tables = np.zeros((B, c.max_blocks_per_slot), np.int32)
            self._slot_blocks: List[List[int]] = [[] for _ in range(B)]
            self._prefix_seen = {"hits": 0, "misses": 0,
                                 "hit_tokens": 0, "evictions": 0,
                                 "spilled": 0}
            self._cost_model = PromoteCostModel(
                adopt_fixed_s=c.kv_adopt_cost_fixed_ms * 1e-3,
                adopt_per_block_s=c.kv_adopt_cost_per_block_ms * 1e-3,
                prefill_per_token_s=c.kv_prefill_cost_per_token_ms
                * 1e-3)
            self._tiers = None
            if c.kv_spill and self._prefix is not None:
                self._tiers = KVTierManager(
                    c.kv_host_tier_bytes, c.kv_block_size,
                    put_fn=_tier_store_put, get_fn=_tier_store_get)
                self._prefix.spill_fn = self._spill_evicted
        else:
            self._cache = init_kv_cache(model_config, B, c.max_seq_len)
            self._allocator = None
            self._prefix = None
            self._tiers = None
        self._tok = jnp.zeros((B,), jnp.int32)
        self._pos = jnp.zeros((B,), jnp.int32)
        self._key = jax.random.key(rng_seed)
        # Host-side mirrors fed into each program call (tiny transfers).
        self._active = np.zeros((B,), bool)
        self._temp = np.zeros((B,), np.float32)

        # Host-side scheduler state. One queue per SLO lane; admission
        # drains "interactive" before "batch" (all queue accesses under
        # _lock — submit/cancel are cross-thread).
        self._slots = [_Slot() for _ in range(B)]
        self._free: deque = deque(range(B))
        self._queues: Dict[str, deque] = {"interactive": deque(),
                                          "batch": deque()}
        self._lock = threading.Lock()
        self._work = threading.Event()
        self._ids = itertools.count()
        self._completed = 0
        self._slot_reuses = 0
        self._cancelled: set = set()    # request ids, guarded by _lock
        self._admit_blocked = False     # interactive admission starved
        self._preempted = 0
        self._migrated_blocks = 0       # KVStates adopted into this pool
        self._migrated_bytes = 0
        self._promoted_blocks = 0       # tier blocks re-adopted to HBM
        self._promote_skips = 0         # cost model chose recompute
        self._tier_seen = {t: {"hits": 0, "misses": 0, "spills": 0,
                               "promotes": 0}
                           for t in ("host", "store")}
        # Cross-thread control calls executed by step() on the
        # scheduler thread (the only thread allowed to touch device
        # state alongside the donating programs) — export_prefix from
        # a replica's Serve thread goes through here.
        self._ctrl_q: deque = deque()

        from ray_tpu.observability.control import Hysteresis

        self._preempt_gate = Hysteresis(
            up_delay_s=c.preempt_hold_s, down_delay_s=0.0,
            cooldown_s=c.preempt_cooldown_s)

        # Speculative decoding: a small draft model proposing
        # spec_k - 1 greedy tokens per round, verified in one paged
        # K-token target step (models/llama.py::verify_kv_paged). The
        # draft keeps a dense per-slot cache — it is tiny, so paging it
        # would buy nothing.
        self._draft = draft_params
        self.draft_config = draft_config
        self._spec_ok = np.zeros((B,), bool)
        self._spec_rounds = 0
        self._spec_proposed = 0
        self._spec_accepted = 0
        if draft_params is not None:
            if not self._paged:
                raise ValueError(
                    "speculative decoding requires kv_layout='paged' "
                    "(the verify step goes through block tables)")
            if draft_config is None:
                raise ValueError("draft_params given without "
                                 "draft_config")
            self._draft_cache = init_kv_cache(draft_config, B,
                                              c.max_seq_len)

        # Compile tracking through the shared telemetry plane: the
        # TrackedJit probe runs ONLY when jax traces a new program, so
        # .traces counts compiled engine programs — the compile-guard
        # test asserts trace_count <= n_buckets + 1, and the recompile
        # detector warns if either program family exceeds its budget
        # (ONE tick, one insert per prefill bucket).
        from ray_tpu.observability import serve_metrics, tracked_jit
        from ray_tpu.observability.device import ensure_sampler_registered

        if self._paged:
            self._jit_tick = tracked_jit(
                self._tick_fn_paged, name="llm_engine_tick",
                trace_budget=1, donate_argnums=(1, 3, 4))
            self._jit_insert = tracked_jit(
                self._insert_fn_paged, name="llm_engine_insert",
                trace_budget=len(c.prefill_buckets),
                donate_argnums=(1, 2, 3))
            # KV migration programs (ONE trace each: block counts are
            # data — padded ids, out-of-bounds scatters dropped).
            self._jit_export = tracked_jit(
                self._export_fn, name="llm_engine_export",
                trace_budget=1)
            self._jit_adopt = tracked_jit(
                self._adopt_fn, name="llm_engine_adopt",
                trace_budget=1, donate_argnums=(0, 1, 2))
            if self._draft is not None:
                self._jit_spec = tracked_jit(
                    self._spec_fn, name="llm_engine_spec",
                    trace_budget=1, donate_argnums=(2, 3, 5, 6))
                self._jit_draft_insert = tracked_jit(
                    self._draft_insert_fn,
                    name="llm_engine_draft_insert",
                    trace_budget=len(c.prefill_buckets),
                    donate_argnums=(1,))
        else:
            self._jit_tick = tracked_jit(
                self._tick_fn, name="llm_engine_tick", trace_budget=1,
                donate_argnums=(1, 2, 3))
            self._jit_insert = tracked_jit(
                self._insert_fn, name="llm_engine_insert",
                trace_budget=len(c.prefill_buckets),
                donate_argnums=(1, 2, 3))
        self._metrics = serve_metrics()
        ensure_sampler_registered()

        # Per-request cost accounting (observability/accounting.py).
        # The gate is latched once per engine: meters attach at submit,
        # so flipping the knob mid-flight would half-meter requests.
        from ray_tpu.observability.accounting import accounting_enabled

        self._acct = accounting_enabled()
        mc = model_config
        self._model_label = (
            f"llama_d{getattr(mc, 'dim', 0)}"
            f"_l{getattr(mc, 'n_layers', 0)}")

    # ------------------------------------------------------------ programs

    def _tick_fn(self, params, cache, tok, pos, active, temp, key):
        """`decode_block` decode steps for all B slots in one dispatch
        (lax.scan — still ONE compiled program). Inactive slots are
        computed but masked: no KV write, token/pos parked. Positions
        clamp at S-1 so a slot finishing mid-block can speculate ahead
        without ever attending past rows it wrote itself; the host
        discards post-stop tokens."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.llama import decode_step

        S = self.config.max_seq_len

        def body(carry, _):
            cache, tok, pos, key = carry
            logits, cache = decode_step(params, cache, tok, pos,
                                        self.model_config, active=active)
            key, sub = jax.random.split(key)
            nxt = _sample(logits, temp, sub)
            tok = jnp.where(active, nxt, tok)
            pos = jnp.where(active, jnp.minimum(pos + 1, S - 1), pos)
            return (cache, tok, pos, key), tok

        (cache, tok, pos, key), toks = jax.lax.scan(
            body, (cache, tok, pos, key), None,
            length=self.config.decode_block)
        return cache, tok, pos, key, toks          # toks: [K, B]

    def _insert_fn(self, params, cache, tok, pos, padded_prompt,
                   prompt_len, slot, temperature, key):
        """Prefill one bucket-padded prompt and splice its KV into the
        shared cache at `slot`; sample the first generated token from
        the logits at the last REAL prompt position. One trace per
        bucket length (the shape of `padded_prompt`)."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        from ray_tpu.models.llama import lm_head_weight, prefill_kv

        c = self.model_config
        hidden, ks, vs = prefill_kv(params, padded_prompt[None], c)
        # ks/vs: [L, 1, Pb, n_kv, hd] -> rows [0, Pb) of this slot.
        cache = {
            "k": lax.dynamic_update_slice(
                cache["k"], ks.astype(c.dtype), (0, slot, 0, 0, 0)),
            "v": lax.dynamic_update_slice(
                cache["v"], vs.astype(c.dtype), (0, slot, 0, 0, 0)),
        }
        x_last = lax.dynamic_index_in_dim(
            hidden[0], prompt_len - 1, axis=0, keepdims=False)
        logits = jax.lax.dot_general(
            x_last[None], lm_head_weight(params, c),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # [1, V]
        key, sub = jax.random.split(key)
        first = _sample(logits, temperature[None], sub)[0]
        tok = tok.at[slot].set(first)
        pos = pos.at[slot].set(prompt_len)
        return cache, tok, pos, key

    def _tick_fn_paged(self, params, pools, tables, tok, pos, active,
                       temp, key):
        """Paged twin of `_tick_fn`: same scan, same sampling, but the
        KV write/read goes through the block tables (data, so still ONE
        compiled program regardless of who owns which block)."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.llama import decode_step_paged

        S = self.config.max_seq_len

        def body(carry, _):
            pools, tok, pos, key = carry
            logits, pools = decode_step_paged(
                params, pools, tables, tok, pos, self.model_config,
                active=active)
            key, sub = jax.random.split(key)
            nxt = _sample(logits, temp, sub)
            tok = jnp.where(active, nxt, tok)
            pos = jnp.where(active, jnp.minimum(pos + 1, S - 1), pos)
            return (pools, tok, pos, key), tok

        (pools, tok, pos, key), toks = jax.lax.scan(
            body, (pools, tok, pos, key), None,
            length=self.config.decode_block)
        return pools, tok, pos, key, toks          # toks: [K, B]

    def _insert_fn_paged(self, params, pools, tok, pos, table_row,
                         hist_len, padded_suffix, suffix_len,
                         new_block_ids, slot, temperature, key):
        """Prefill the (possibly prefix-truncated) suffix of one prompt
        and scatter its KV into the slot's freshly-allocated blocks.

        The prefix-hit path IS the miss path: ``hist_len`` (dynamic
        data) tells `prefill_kv_paged` where the suffix starts; a miss
        is just hist_len = 0 over an all-zero history. One trace per
        suffix bucket — the only static shapes are ``padded_suffix``
        [Pb] and ``new_block_ids`` [Pb / block_size], both functions of
        the bucket — so compile count stays <= len(prefill_buckets).
        """
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.llama import lm_head_weight, prefill_kv_paged

        c = self.model_config
        bs = self.config.kv_block_size
        L = pools["k"].shape[0]
        n_kv, hd = pools["k"].shape[3], pools["k"].shape[4]
        S_pad = self.config.max_blocks_per_slot * bs
        Pb = padded_suffix.shape[0]
        # History view: this slot's dense [S_pad] gather. Rows at and
        # past hist_len are stale — masked inside prefill_kv_paged.
        hist_k = pools["k"][:, table_row].reshape(L, S_pad, n_kv, hd)
        hist_v = pools["v"][:, table_row].reshape(L, S_pad, n_kv, hd)
        hidden, ks, vs = prefill_kv_paged(
            params, padded_suffix[None], hist_len, hist_k, hist_v, c)
        # ks/vs: [L, 1, Pb, n_kv, hd] -> whole blocks into the pool at
        # the slot's new physical ids (padding rows ride along; decode
        # overwrites each before attending, exactly like the dense path
        # tolerates stale rows).
        kb = ks[:, 0].astype(c.dtype).reshape(L, Pb // bs, bs, n_kv, hd)
        vb = vs[:, 0].astype(c.dtype).reshape(L, Pb // bs, bs, n_kv, hd)
        pools = {
            "k": pools["k"].at[:, new_block_ids].set(kb),
            "v": pools["v"].at[:, new_block_ids].set(vb),
        }
        x_last = jax.lax.dynamic_index_in_dim(
            hidden[0], suffix_len - 1, axis=0, keepdims=False)
        logits = jax.lax.dot_general(
            x_last[None], lm_head_weight(params, c),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # [1, V]
        key, sub = jax.random.split(key)
        first = _sample(logits, temperature[None], sub)[0]
        tok = tok.at[slot].set(first)
        pos = pos.at[slot].set(hist_len + suffix_len)
        return pools, tok, pos, key

    def _export_fn(self, pools, table_row):
        """Gather one slot's blocks into dense [L, max_blocks, bs,
        n_kv, hd] arrays (the host slices the valid prefix). Read-only
        on the pool; ONE trace regardless of how many blocks are live
        (the table row is data)."""
        return pools["k"][:, table_row], pools["v"][:, table_row]

    def _adopt_fn(self, pools, tok, pos, kb, vb, scatter_ids, slot,
                  new_tok, new_pos):
        """Scatter an imported KVState's blocks into the pool at this
        engine's freshly-allocated ids and seed the slot's token /
        position. ``scatter_ids`` is padded to max_blocks with the pool
        size (out-of-bounds scatters are dropped under jit), so ONE
        compiled program serves every valid-block count."""
        pools = {
            "k": pools["k"].at[:, scatter_ids].set(kb),
            "v": pools["v"].at[:, scatter_ids].set(vb),
        }
        tok = tok.at[slot].set(new_tok)
        pos = pos.at[slot].set(new_pos)
        return pools, tok, pos

    def _draft_insert_fn(self, draft_params, dcache, padded_prompt,
                         slot):
        """Prefill the draft model's dense cache for one admitted slot
        (always the FULL padded prompt — the draft has no prefix cache;
        padding rows are stale-but-masked exactly like the dense
        insert). One trace per prompt bucket."""
        from jax import lax

        from ray_tpu.models.llama import prefill_kv

        dc = self.draft_config
        _, ks, vs = prefill_kv(draft_params, padded_prompt[None], dc)
        return {
            "k": lax.dynamic_update_slice(
                dcache["k"], ks.astype(dc.dtype), (0, slot, 0, 0, 0)),
            "v": lax.dynamic_update_slice(
                dcache["v"], vs.astype(dc.dtype), (0, slot, 0, 0, 0)),
        }

    def _spec_fn(self, params, draft_params, pools, dcache, tables,
                 tok, pos, active):
        """One speculative round (greedy lanes only): the draft
        proposes spec_k - 1 tokens from its dense cache, ONE paged
        verify step scores all spec_k inputs on the target, and the
        longest draft prefix agreeing with the target argmax is
        accepted. Every emitted token IS the target's argmax given
        correct inputs, so a round is token-identical to 1..spec_k
        plain ticks — a zero-accept round still emits the one token a
        plain tick would have. Rejected inputs leave stale rows past
        the new position in both caches; both are overwritten before
        ever being attended (the recycled-slot invariant)."""
        import jax.numpy as jnp
        from jax import lax

        from ray_tpu.models.llama import decode_step, verify_kv_paged

        c = self.config
        K = c.spec_k
        S = c.max_seq_len
        B = tok.shape[0]

        def draft_body(carry, _):
            dcache, dtok, dpos = carry
            dlogits, dcache = decode_step(
                draft_params, dcache, dtok, dpos, self.draft_config,
                active=active)
            nxt = jnp.argmax(dlogits, axis=-1).astype(jnp.int32)
            dtok = jnp.where(active, nxt, dtok)
            dpos = jnp.where(active, jnp.minimum(dpos + 1, S - 1), dpos)
            return (dcache, dtok, dpos), dtok

        (dcache, _, _), drafts = lax.scan(
            draft_body, (dcache, tok, pos), None, length=K - 1)
        # Verify inputs: the accepted stream so far ends at `tok`
        # (sampled, unconsumed); the draft continues it. [B, K]
        inputs = jnp.concatenate([tok[None], drafts], axis=0).T
        logits, pools = verify_kv_paged(
            params, pools, tables, inputs, pos, self.model_config,
            active=active)
        t = jnp.argmax(logits, axis=-1).astype(jnp.int32)    # [B, K]
        # Draft token j+1 survives iff the target's argmax after input
        # j equals it; acceptance is the leading run of agreements.
        agree = (t[:, :-1] == drafts.T).astype(jnp.int32)    # [B, K-1]
        acc = jnp.cumprod(agree, axis=1).sum(axis=1)         # 0..K-1
        n_emit = jnp.where(active, acc + 1, 0)
        new_tok = t[jnp.arange(B), jnp.maximum(n_emit, 1) - 1]
        tok = jnp.where(active, new_tok, tok)
        pos = jnp.where(active, jnp.minimum(pos + n_emit, S - 1), pos)
        return pools, dcache, tok, pos, t, n_emit

    # ----------------------------------------------------------- submission

    def submit(self, request: Request) -> RequestHandle:
        c = self.config
        P = len(request.prompt)
        top = c.prefill_buckets[-1]
        if P == 0:
            raise ValueError("empty prompt")
        if request.max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        if request.slo not in ("interactive", "batch"):
            raise ValueError(
                f"slo must be 'interactive' or 'batch', got "
                f"{request.slo!r}")
        if request.prefill_only and not self._paged:
            raise ValueError(
                "prefill_only requires kv_layout='paged' (the exported "
                "checkpoint is a set of KV blocks)")
        chunked = request.chunked_prefill and P > top
        handle = RequestHandle(next(self._ids), request)
        if chunked:
            if not (self._paged and self._prefix is not None):
                raise ValueError(
                    "chunked_prefill needs kv_layout='paged' with "
                    "prefix_cache=True (chunks hand off through the "
                    "prefix cache)")
            if P >= c.max_seq_len or -(-P // top) * top > c.max_seq_len:
                raise ValueError(
                    f"prompt length {P} cannot be chunk-prefilled: "
                    f"ceil({P}/{top}) bucket-sized chunks exceed "
                    f"max_seq_len {c.max_seq_len}")
            handle._chunk_ends = list(range(top, P, top)) + [P]
        elif P > top:
            raise ValueError(
                f"prompt length {P} exceeds largest prefill bucket "
                f"{top} (set chunked_prefill=True on a paged + "
                f"prefix-cache engine)")
        if self._paged:
            # A request the pool can never hold must fail loudly at
            # submit — queuing it would deadlock admission forever.
            worst = self._blocks_needed(P, request.max_tokens)
            worst = max(worst,
                        self._bucket_for(min(P, top))
                        // c.kv_block_size)
            if worst > c.pool_blocks:
                raise ValueError(
                    f"request needs up to {worst} KV blocks but the "
                    f"pool only has {c.pool_blocks}; raise "
                    f"num_kv_blocks or lower max_tokens")
        handle._engine = self
        self._capture_trace(handle)
        self._attach_meter(handle)
        with self._lock:
            self._queues[request.slo].append(handle)
        self._work.set()
        return handle

    def _attach_meter(self, handle: RequestHandle) -> None:
        """Attach a cost meter (after _capture_trace: the meter is
        stamped with the captured trace id)."""
        if not self._acct:
            return
        try:
            from ray_tpu.observability.accounting import RequestMeter

            req = handle.request
            handle.meter = RequestMeter(
                tenant=req.tenant, model=self._model_label,
                lane=req.slo,
                trace_id=(handle.trace.trace_id if handle.trace
                          else None),
                request_id=handle.request_id)
        except Exception:
            handle.meter = None   # accounting must never break submit

    def submit_adopted(self, request: Request, state: Any, *,
                       front: bool = False,
                       meter_snapshot: Optional[Dict[str, Any]] = None
                       ) -> RequestHandle:
        """Submit a request whose prefill already ran elsewhere: `state`
        is the kv_cache.KVState exported by the prefill tier (or by
        preemption). Admission imports the blocks into this engine's
        pool and decoding continues exactly where the checkpoint
        stopped — token-for-token what a monolithic engine would have
        produced. `front=True` queues at the lane head (resume
        semantics)."""
        from ray_tpu.serve.llm.kv_cache import KVState

        c = self.config
        if not self._paged:
            raise ValueError("submit_adopted requires kv_layout='paged'")
        if not isinstance(state, KVState):
            raise TypeError(f"expected KVState, got {type(state)!r}")
        state.validate()
        if state.block_size != c.kv_block_size:
            raise ValueError(
                f"KVState block_size {state.block_size} != engine "
                f"kv_block_size {c.kv_block_size}")
        if list(request.prompt) != list(state.prompt):
            raise ValueError(
                "request.prompt does not match the exported KVState "
                "prompt (the checkpoint is prompt-specific)")
        if request.max_tokens <= len(state.tokens):
            raise ValueError(
                f"max_tokens {request.max_tokens} already reached by "
                f"the checkpoint ({len(state.tokens)} tokens)")
        if request.slo not in ("interactive", "batch"):
            raise ValueError(
                f"slo must be 'interactive' or 'batch', got "
                f"{request.slo!r}")
        need = max(self._blocks_needed(len(request.prompt),
                                       request.max_tokens),
                   state.n_blocks)
        if need > c.pool_blocks:
            raise ValueError(
                f"adopted request needs up to {need} KV blocks but the "
                f"pool only has {c.pool_blocks}")
        handle = RequestHandle(next(self._ids), request)
        handle._engine = self
        handle._adopted_submit = True
        self._capture_trace(handle)
        self._attach_meter(handle)
        if handle.meter is not None and meter_snapshot:
            # The prefill tier's meter rides next to the KVState so the
            # migrated request lands on ONE ledger row (prefill
            # chip-seconds and all).
            handle.meter.absorb(meter_snapshot)
        handle.tokens = list(state.tokens)
        handle.kv_state = state
        with self._lock:
            q = self._queues[request.slo]
            if front:
                q.appendleft(handle)
            else:
                q.append(handle)
        self._work.set()
        return handle

    def cancel(self, handle: RequestHandle) -> bool:
        """Cancel a submitted request. Queued handles finish here;
        live handles are marked and torn down by the scheduler thread
        at its next step boundary (slot + blocks + prefix refs all
        released there, on the only thread that owns device state)."""
        with self._lock:
            if handle._done.is_set():
                return False
            for q in self._queues.values():
                if handle in q:
                    q.remove(handle)
                    break
            else:
                self._cancelled.add(handle.request_id)
                self._work.set()
                return True
        self._finish_cancelled(handle)
        return True

    def _finish_cancelled(self, handle: RequestHandle) -> None:
        handle.finish_reason = "cancelled"
        handle.finished_at = time.monotonic()
        self._completed += 1
        self._record_finished(handle)
        handle._done.set()

    def has_work(self) -> bool:
        return (any(self._queues.values()) or bool(self._active.any())
                or bool(self._cancelled) or bool(self._ctrl_q))

    # ------------------------------------------------------------ scheduling

    def _bucket_for(self, n: int) -> int:
        for b in self.config.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(n)  # pre-checked in submit()

    def _blocks_needed(self, prompt_len: int, max_tokens: int) -> int:
        """Blocks covering every position this request can ever write:
        prompt + generated tokens + up to decode_block - 1 (or
        spec_k - 1 when a draft model is wired — a verify step writes
        spec_k rows) speculative writes after the stop condition,
        capped at the sequence limit (positions clamp at S - 1)."""
        c = self.config
        over = max(c.decode_block,
                   c.spec_k if self._draft is not None else 1)
        top = min(prompt_len + max_tokens + over - 1, c.max_seq_len)
        return -(-top // c.kv_block_size)

    def _pop_next(self) -> Optional[RequestHandle]:
        """Next admissible handle, interactive lane first (strict
        priority; batch only drains when interactive is empty)."""
        with self._lock:
            for lane in ("interactive", "batch"):
                if self._queues[lane]:
                    return self._queues[lane].popleft()
        return None

    def _requeue(self, handle: RequestHandle, *,
                 front: bool = True) -> None:
        with self._lock:
            q = self._queues[handle.request.slo]
            if front:
                q.appendleft(handle)
            else:
                q.append(handle)

    def _admit(self) -> List[Tuple[int, bool]]:
        """Move queued requests into free slots (one prefill each);
        returns (slot, fresh) pairs inserted this step — `fresh` is
        False for adopted checkpoints, whose last sampled token was
        already emitted by the exporting engine. Paged layout:
        admission additionally needs blocks — on pool exhaustion the
        request goes BACK to the lane head and admission stops
        (requests queue, never crash; blocks free as running sequences
        finish). Chunked-prefill intermediates are throwaway
        admissions (KV lands in the prefix cache, the slot is reused
        immediately) rate-limited to one chunk per step so interactive
        admissions interleave with a long prefill."""
        import numpy as np

        inserted: List[Tuple[int, bool]] = []
        chunk_budget = 1
        while self._free:
            handle = self._pop_next()
            if handle is None:
                break
            if handle._done.is_set():
                continue   # cancelled while queued by a racing cancel()
            req = handle.request
            if handle._chunk_ends and \
                    handle._chunk_idx < len(handle._chunk_ends) - 1:
                # Intermediate chunk: prefill prompt[:end] through the
                # prefix cache and free the slot again. Budget of one
                # chunk per step keeps the lane responsive.
                if chunk_budget == 0:
                    self._requeue(handle)
                    break
                end = handle._chunk_ends[handle._chunk_idx]
                slot = self._free[0]
                t_chunk = time.monotonic()
                if not self._admit_paged(handle, slot, upto=end,
                                         throwaway=True):
                    self._requeue(handle)
                    if req.slo == "interactive":
                        self._admit_blocked = True
                    break
                if handle.meter is not None:
                    handle.meter.note_chip(
                        "prefill", time.monotonic() - t_chunk)
                chunk_budget -= 1
                handle._chunk_idx += 1
                self._requeue(handle)
                continue
            slot = self._free.popleft()
            fresh = handle.kv_state is None
            t_admit = time.monotonic()
            if not fresh:
                ok = self._admit_adopted(handle, slot)
            elif self._paged:
                ok = self._admit_paged(handle, slot)
            else:
                P = len(req.prompt)
                bucket = self._bucket_for(P)
                padded = np.zeros((bucket,), np.int32)
                padded[:P] = np.asarray(req.prompt, np.int32)
                self._cache, self._tok, self._pos, self._key = \
                    self._jit_insert(
                        self.params, self._cache, self._tok, self._pos,
                        padded, np.int32(P), np.int32(slot),
                        np.float32(req.temperature), self._key)
                handle.prefilled_tokens += P
                ok = True
            if not ok:
                self._free.appendleft(slot)
                if req.slo == "interactive":
                    self._admit_blocked = True
                self._requeue(handle)
                break
            if self._draft is not None and fresh:
                self._draft_admit(list(req.prompt), slot)
            if handle.meter is not None:
                # Admission dispatch (insert/adopt + draft seed) billed
                # as this request's prefill chip-time; fresh admissions
                # resume-from-preempt included — the adopt scatter is
                # real chip work this request caused.
                handle.meter.note_chip(
                    "prefill", time.monotonic() - t_admit)
            if handle.admitted_at is None:
                handle.admitted_at = time.monotonic()
                self._metrics.queue_wait.observe(
                    handle.admitted_at - handle.submitted_at)
                if handle.meter is not None:
                    handle.meter.note_queue_wait(
                        handle.admitted_at - handle.submitted_at)
            st = self._slots[slot]
            if st.uses:
                self._slot_reuses += 1
                self._metrics.slot_reuses.inc()
            st.uses += 1
            st.handle = handle
            self._active[slot] = True
            self._temp[slot] = req.temperature
            inserted.append((slot, fresh))
        return inserted

    def _admit_paged(self, handle: RequestHandle, slot: int,
                     upto: Optional[int] = None,
                     throwaway: bool = False) -> bool:
        """Block accounting + paged insert for one request. Returns
        False (nothing allocated, nothing inserted) when the pool can't
        cover it even after evicting cold prefix entries.

        `upto` prefills only prompt[:upto] (a chunked-prefill chunk);
        `throwaway` additionally keeps the slot free — the KV outlives
        the admission only through the prefix-cache refs taken at
        insert, so the next chunk (or the final admission) prefix-hits
        it. The sampled token of a throwaway insert is garbage by
        construction and never read: the slot stays inactive, so the
        tick masks it and the final admission overwrites tok/pos."""
        import numpy as np

        req = handle.request
        c = self.config
        bs = c.kv_block_size
        prompt = req.prompt if upto is None else req.prompt[:upto]
        P = len(prompt)
        if throwaway:
            # Only the chunk itself; headroom is the FINAL admission's
            # problem (these blocks are cache-owned the moment the
            # insert returns).
            need_total = -(-P // bs)
        else:
            need_total = self._blocks_needed(P, req.max_tokens)

        # Longest cached prefix, capped so the LAST prompt token is
        # always prefilled (its logits seed the first sampled token).
        hit_blocks: List[int] = []
        if self._prefix is not None:
            hit_blocks = self._prefix.match(prompt,
                                            max_blocks=(P - 1) // bs)
        if P - len(hit_blocks) * bs > c.prefill_buckets[-1]:
            # Chunked-prefill continuation whose earlier chunks were
            # evicted from the prefix cache before this admission: the
            # remaining suffix no longer fits any bucket. Rewind the
            # chunk plan to what the cache still covers and re-chunk.
            self._allocator.free(hit_blocks)
            handle._chunk_idx = (len(hit_blocks) * bs) \
                // c.prefill_buckets[-1]
            return False
        # Trim the hit so history + the padded suffix bucket still fit
        # in the slot's table (a shallow hit on a near-max prompt can
        # otherwise push the bucket's whole-block scatter past S).
        while hit_blocks:
            hl = len(hit_blocks) * bs
            if hl + self._bucket_for(P - hl) <= c.max_seq_len:
                break
            self._allocator.free([hit_blocks.pop()])
        n_hit = len(hit_blocks)
        # Tier continuation: extend the HBM hit with spilled chain
        # links, re-adopted through the adopt scatter — but only when
        # the cost model says the transfer beats recomputing those
        # positions (short suffixes recompute; the crossover is the
        # whole point of the hierarchy).
        promote: List[Any] = []
        if self._tiers is not None and self._prefix is not None:
            cap = (P - 1) // bs - n_hit
            if cap > 0:
                promote = self._tiers.lookup(prompt, bs,
                                             start_depth=n_hit,
                                             max_blocks=cap)
            # Same table-fit trim as the HBM hit above.
            while promote:
                hl = (n_hit + len(promote)) * bs
                if hl + self._bucket_for(P - hl) <= c.max_seq_len:
                    break
                promote.pop()
            if promote and not self._cost_model.should_promote(
                    len(promote), bs):
                self._promote_skips += len(promote)
                promote = []
        while True:
            n_pro = len(promote)
            hist_len = (n_hit + n_pro) * bs
            suffix_len = P - hist_len
            bucket = self._bucket_for(suffix_len)
            # Fresh blocks: the rest of the sequence, but at least the
            # promoted links plus the whole suffix bucket — the adopt
            # and insert scatters write full blocks, and every written
            # block must be owned by this slot.
            n_new = max(need_total - n_hit, n_pro + bucket // bs)
            new_blocks = self._allocator.alloc(n_new)
            if new_blocks is None and self._prefix is not None:
                self._prefix.evict(n_new - self._allocator.free_blocks)
                new_blocks = self._allocator.alloc(n_new)
            if new_blocks is not None or not promote:
                break
            # All-or-nothing promote: the pool cannot cover the full
            # run even after eviction — drop the promote entirely
            # (tier entries untouched) and retry as a plain recompute.
            promote = []
        if new_blocks is None:
            if hit_blocks:
                self._allocator.free(hit_blocks)
            return False

        blocks = hit_blocks + new_blocks
        row = np.zeros((c.max_blocks_per_slot,), np.int32)
        row[:len(blocks)] = blocks
        if not throwaway:
            self._tables[slot] = row
            self._slot_blocks[slot] = blocks
            if handle.meter is not None:
                # Block-seconds meter opens here; _release_slot closes
                # it with the same count (all blocks alloc up front).
                # Throwaway chunk admissions skip it — their KV is
                # cache-owned the moment the insert returns.
                handle.meter.blocks_acquired(len(blocks))

        if promote:
            # Land the tier links in new_blocks[:n_pro] BEFORE the
            # insert below reads them as history.
            self._promote_tier_hits(promote, new_blocks[:n_pro], slot,
                                    handle=handle)
        padded = np.zeros((bucket,), np.int32)
        padded[:suffix_len] = np.asarray(prompt[hist_len:], np.int32)
        scatter_ids = np.asarray(new_blocks[n_pro:n_pro + bucket // bs],
                                 np.int32)
        self._cache, self._tok, self._pos, self._key = \
            self._jit_insert(
                self.params, self._cache, self._tok, self._pos,
                row, np.int32(hist_len), padded, np.int32(suffix_len),
                scatter_ids, np.int32(slot),
                np.float32(req.temperature), self._key)
        handle.prefilled_tokens += suffix_len
        if self._prefix is not None:
            # Register the prompt's FULL blocks (all rows real) so the
            # next request sharing this prefix skips their prefill.
            full = P // bs
            if full:
                self._prefix.insert(prompt, blocks[:full])
        if throwaway:
            # The prefix cache now owns the chunk's full blocks (insert
            # increfed them); drop this admission's transient refs. The
            # slot was never activated, so its garbage tok/pos rows are
            # masked by the tick and overwritten at final admission.
            self._allocator.free(blocks)
        return True

    def _admit_adopted(self, handle: RequestHandle, slot: int) -> bool:
        """Import a KVState checkpoint into this engine's pool and
        resume the sequence in `slot`. All-or-nothing: either every
        block the sequence can ever need is allocated (evicting cold
        prefix entries if that closes the gap) and the scatter runs, or
        nothing changes and the request stays queued. ONE adopt trace
        serves every valid-block count — kb/vb are zero-padded to
        max_blocks_per_slot and the scatter ids of padding rows point
        one past the pool (out-of-bounds writes drop under jit)."""
        import numpy as np

        t_mig = time.time()
        req = handle.request
        st = handle.kv_state
        c = self.config
        bs = c.kv_block_size
        n_valid = st.n_blocks
        need_total = max(
            self._blocks_needed(len(req.prompt), req.max_tokens),
            n_valid)
        blocks = self._allocator.adopt(need_total, self._prefix)
        if blocks is None:
            return False
        row = np.zeros((c.max_blocks_per_slot,), np.int32)
        row[:need_total] = blocks
        self._tables[slot] = row
        self._slot_blocks[slot] = blocks
        if handle.meter is not None:
            handle.meter.blocks_acquired(len(blocks))

        nb = c.max_blocks_per_slot
        # Padding rows scatter to pool_blocks (out of bounds → dropped).
        ids = np.full((nb,), c.pool_blocks, np.int32)
        ids[:n_valid] = blocks[:n_valid]
        kb = np.zeros((st.k_blocks.shape[0], nb) + st.k_blocks.shape[2:],
                      st.k_blocks.dtype)
        vb = np.zeros_like(kb)
        kb[:, :n_valid] = st.k_blocks
        vb[:, :n_valid] = st.v_blocks
        self._cache, self._tok, self._pos = self._jit_adopt(
            self._cache, self._tok, self._pos, kb, vb, ids,
            np.int32(slot), np.int32(st.next_tok), np.int32(st.pos))
        if self._prefix is not None:
            # Shared prompts stay warm across the migration: register
            # the prompt's FULL blocks exactly like a fresh admission.
            full = len(req.prompt) // bs
            full = min(full, n_valid)
            if full:
                self._prefix.insert(req.prompt, blocks[:full])
        self._migrated_blocks += n_valid
        self._migrated_bytes += st.payload_bytes
        self._metrics.kv_migrated_blocks.inc(float(n_valid))
        self._metrics.kv_migrated_bytes.inc(float(st.payload_bytes))
        try:
            from ray_tpu.util.tracing import record_span

            record_span("kv.migrate", t_mig, time.time() - t_mig,
                        attrs={"blocks": int(n_valid),
                               "bytes": int(st.payload_bytes)},
                        trace=self._phase_trace(handle))
        except Exception:
            pass  # telemetry must never break admission
        handle.kv_state = None
        if self._draft is not None:
            # The draft cache never migrated: re-prefill it with
            # everything the sequence has consumed so far.
            self._draft_admit(
                list(req.prompt) + list(handle.tokens[:-1]), slot)
        return True

    def _draft_admit(self, consumed: List[int], slot: int) -> None:
        """Prefill the draft model's dense cache with a slot's consumed
        tokens (prompt, plus prior output for adopted sequences). A
        sequence whose consumed length exceeds the largest bucket
        cannot seed the draft in one insert — it simply decodes without
        speculation (spec_ok stays False; the plain tick handles it)."""
        import numpy as np

        n = len(consumed)
        if n > self.config.prefill_buckets[-1]:
            self._spec_ok[slot] = False
            return
        bucket = self._bucket_for(n)
        padded = np.zeros((bucket,), np.int32)
        padded[:n] = np.asarray(consumed, np.int32)
        self._draft_cache = self._jit_draft_insert(
            self._draft, self._draft_cache, padded, np.int32(slot))
        self._spec_ok[slot] = True

    def _release_slot(self, slot: int, donate: bool = False) -> None:
        """Clear a slot's scheduler state and reclaim its blocks.
        `donate=True` hands the blocks to a pending checkpoint (export
        already copied the data; `BlockAllocator.donate` asserts the
        refs are live) instead of plain freeing."""
        st = self._slots[slot]
        handle = st.handle
        st.handle = None
        self._active[slot] = False
        self._temp[slot] = 0.0
        self._spec_ok[slot] = False
        if self._paged and self._slot_blocks[slot]:
            # Drop this sequence's refs; blocks shared with the prefix
            # cache (or other sequences) stay resident.
            if handle is not None and handle.meter is not None:
                # Close the block-seconds interval symmetrically with
                # the acquisition count; preempt → resume reopens it
                # at re-admission, so occupancy stays monotone and
                # never double-counts.
                handle.meter.blocks_released(
                    len(self._slot_blocks[slot]))
            if donate:
                self._allocator.donate(self._slot_blocks[slot])
            else:
                self._allocator.free(self._slot_blocks[slot])
            self._slot_blocks[slot] = []
        self._free.append(slot)

    def _emit(self, slot: int, token: int) -> None:
        """Record one generated token for `slot`; free the slot when the
        request is finished (eos/stop halt, max_tokens bounds)."""
        st = self._slots[slot]
        handle = st.handle
        req = handle.request
        now = time.monotonic()
        reason = None
        if token in req.stop:
            reason = "stop"                      # halt, token NOT emitted
        else:
            handle.tokens.append(token)
            if handle.first_token_at is None:
                handle.first_token_at = now
            if req.on_token is not None:
                try:
                    req.on_token(handle.request_id, token)
                except Exception:
                    pass                          # streaming is best-effort
            if (self.config.eos_id is not None
                    and token == self.config.eos_id):
                reason = "eos"                   # halt, eos IS emitted
            elif len(handle.tokens) >= req.max_tokens:
                reason = "length"
        # Hard cap: a slot may never write past the shared cache. The
        # NEXT token would land at pos = prompt + len(tokens); stop while
        # it still fits.
        if reason is None and (len(req.prompt) + len(handle.tokens)
                               >= self.config.max_seq_len):
            reason = "length"
        if reason is not None:
            handle.finish_reason = reason
            handle.finished_at = now
            self._release_slot(slot)
            self._completed += 1
            self._record_finished(handle)
            handle._done.set()

    def _finish_prefill(self, slot: int, token: int) -> None:
        """Prefill-only completion: record the first sampled token,
        export the slot's KV blocks as the handle's checkpoint, and
        free the slot. A request that already terminates at its first
        token (stop/eos/length) finishes with that reason instead —
        the decode tier has nothing left to do and the router skips
        the migration hop."""
        st = self._slots[slot]
        handle = st.handle
        req = handle.request
        now = time.monotonic()
        reason = None
        if token in req.stop:
            reason = "stop"
        else:
            handle.tokens.append(token)
            handle.first_token_at = now
            if (self.config.eos_id is not None
                    and token == self.config.eos_id):
                reason = "eos"
            elif req.max_tokens <= 1 or \
                    len(req.prompt) + 1 >= self.config.max_seq_len:
                reason = "length"
        donate = False
        if reason is None:
            handle.kv_state = self._export_state(slot)
            reason = "prefill"
            donate = True
        handle.finish_reason = reason
        handle.finished_at = now
        self._release_slot(slot, donate=donate)
        self._completed += 1
        self._record_finished(handle)
        handle._done.set()

    def _export_state(self, slot: int) -> Any:
        """Snapshot a live slot's sequence as a host-side KVState:
        dense copies of its valid KV blocks + the resume bookkeeping
        (consumed position, pending sampled token). ONE gather trace
        for every block count — the table row is data; the host slices
        the valid prefix."""
        import numpy as np

        from ray_tpu.serve.llm.kv_cache import KVState

        handle = self._slots[slot].handle
        req = handle.request
        bs = self.config.kv_block_size
        pos = int(np.asarray(self._pos)[slot])
        next_tok = int(np.asarray(self._tok)[slot])
        n_valid = -(-pos // bs)
        kb, vb = self._jit_export(self._cache,
                                  self._tables[slot].copy())
        state = KVState(
            prompt=list(req.prompt),
            tokens=list(handle.tokens),
            next_tok=next_tok,
            pos=pos,
            temperature=req.temperature,
            block_size=bs,
            k_blocks=np.asarray(kb)[:, :n_valid].copy(),
            v_blocks=np.asarray(vb)[:, :n_valid].copy(),
        )
        state.validate()
        return state

    # ------------------------------------------------------- KV tiering

    def _spill_evicted(self, victims: List[Any]) -> int:
        """PrefixCache eviction hook: gather the victims' HBM rows
        (still cache-owned at this point — the free happens after we
        return) and park them in the tier manager as one single-block
        KVPrefix per chain link. Batched through the existing export
        program — the padded id row is data, so a spill adds ZERO new
        traces. Runs on the scheduler thread (eviction only happens
        there)."""
        import numpy as np

        from ray_tpu.serve.llm.kv_cache import KVPrefix

        if self._tiers is None:
            return 0
        c = self.config
        bs = c.kv_block_size
        ents = [e for e in victims if e.tokens]
        if not ents:
            return 0
        nb = c.max_blocks_per_slot
        prefixes: List[Any] = []
        for i in range(0, len(ents), nb):
            chunk = ents[i:i + nb]
            row = np.zeros((nb,), np.int32)
            row[:len(chunk)] = [e.block for e in chunk]
            kb, vb = self._jit_export(self._cache, row)
            kb, vb = np.asarray(kb), np.asarray(vb)
            for j, e in enumerate(chunk):
                prefixes.append(KVPrefix(
                    tokens=e.tokens, block_size=bs,
                    k_blocks=kb[:, j:j + 1].copy(),
                    v_blocks=vb[:, j:j + 1].copy()))
        return self._tiers.spill(prefixes)

    def _promote_tier_hits(self, hits: List[Any],
                           dst_blocks: List[int], slot: int,
                           handle: Optional[RequestHandle] = None
                           ) -> None:
        """Scatter tier-resident chain links into freshly-allocated
        pool blocks through the ONE adopt program (padding ids point
        one past the pool — dropped under jit). The tok/pos writes are
        placeholders: the insert that follows for the same slot owns
        them (and a throwaway slot is never activated). Tier entries
        are popped only after the scatter dispatched — the
        all-or-nothing contract."""
        import numpy as np

        t_pro = time.time()
        c = self.config
        nb = c.max_blocks_per_slot
        ids = np.full((nb,), c.pool_blocks, np.int32)
        ids[:len(dst_blocks)] = dst_blocks
        proto = hits[0].prefix.k_blocks
        kb = np.zeros((proto.shape[0], nb) + proto.shape[2:],
                      proto.dtype)
        vb = np.zeros_like(kb)
        for j, h in enumerate(hits):
            kb[:, j] = h.prefix.k_blocks[:, -1]
            vb[:, j] = h.prefix.v_blocks[:, -1]
        self._cache, self._tok, self._pos = self._jit_adopt(
            self._cache, self._tok, self._pos, kb, vb, ids,
            np.int32(slot), np.int32(0), np.int32(0))
        self._tiers.pop(hits)
        self._promoted_blocks += len(hits)
        if handle is not None:
            try:
                from ray_tpu.util.tracing import record_span

                record_span("kv.promote", t_pro, time.time() - t_pro,
                            attrs={"blocks": len(hits)},
                            trace=self._phase_trace(handle))
            except Exception:
                pass  # telemetry must never break admission

    def call_on_scheduler(self, fn: Callable[[], Any],
                          timeout_s: float = 60.0) -> Any:
        """Run ``fn()`` on the scheduler thread between steps and
        return its result. Device state may only be touched alongside
        the donating programs from that thread — a concurrent reader
        could gather a buffer the tick just donated. Deadlocks if
        called FROM the scheduler thread (call the target directly
        there)."""
        box: List[Any] = []
        ev = threading.Event()
        with self._lock:
            self._ctrl_q.append((fn, box, ev))
        self._work.set()
        if not ev.wait(timeout_s):
            raise TimeoutError("scheduler thread did not service the "
                               "control call (is run() driving it?)")
        if isinstance(box[0], BaseException):
            raise box[0]
        return box[0]

    def _process_ctrl(self) -> bool:
        with self._lock:
            batch = list(self._ctrl_q)
            self._ctrl_q.clear()
        for fn, box, ev in batch:
            try:
                box.append(fn())
            except BaseException as e:          # relayed to the caller
                box.append(e)
            ev.set()
        return bool(batch)

    def export_prefix(self, tokens: Sequence[int],
                      max_blocks: Optional[int] = None) -> List[Any]:
        """Donor side of a peer pull: the longest HBM + tier chain
        covering a prefix of ``tokens``, as one single-block KVPrefix
        per link (plain ndarrays — a task returning them rides the
        object store zero-copy). Non-destructive: the donor keeps its
        copies. Must run on the scheduler thread — wrap in
        :meth:`call_on_scheduler` from anywhere else."""
        import numpy as np

        from ray_tpu.serve.llm.kv_cache import KVPrefix

        if not self._paged or self._prefix is None:
            return []
        c = self.config
        bs = c.kv_block_size
        cap = len(tokens) // bs
        if max_blocks is not None:
            cap = min(cap, max_blocks)
        if cap <= 0:
            return []
        out: List[Any] = []
        hit = self._prefix.match(tokens, max_blocks=cap)
        if hit:
            nb = c.max_blocks_per_slot
            n = min(len(hit), nb)
            row = np.zeros((nb,), np.int32)
            row[:n] = hit[:n]
            kb, vb = self._jit_export(self._cache, row)
            kb, vb = np.asarray(kb), np.asarray(vb)
            for j in range(n):
                out.append(KVPrefix(
                    tokens=tuple(tokens[: (j + 1) * bs]),
                    block_size=bs,
                    k_blocks=kb[:, j:j + 1].copy(),
                    v_blocks=vb[:, j:j + 1].copy()))
            self._allocator.free(hit)       # match increfed for us
        if self._tiers is not None and len(out) < cap:
            for h in self._tiers.lookup(tokens, bs,
                                        start_depth=len(out),
                                        max_blocks=cap - len(out)):
                out.append(h.prefix)
        return out

    def import_prefix(self, prefixes: Sequence[Any]) -> int:
        """Receiver side of a peer pull: park pulled chain links in the
        host tier; the pulling request's admission then promotes them
        through the normal cost-model path. Thread-safe (tier manager
        locks) — no scheduler hop needed."""
        if self._tiers is None:
            return 0
        return self._tiers.spill(list(prefixes))

    def prefix_index_heads(self,
                           max_heads: Optional[int] = None
                           ) -> List[Tuple[int, int]]:
        """What this replica publishes to the cluster-wide prefix
        index: ``(stable_hash, depth)`` chain links it can serve
        without prefilling — HBM-resident first (hottest), then tier
        residents — deduped and capped at
        ``serve_prefix_index_max_heads``."""
        from ray_tpu._private.config import GlobalConfig

        if max_heads is None:
            max_heads = int(GlobalConfig.serve_prefix_index_max_heads)
        heads: List[Tuple[int, int]] = []
        seen: set = set()
        sources: List[List[Tuple[int, int]]] = []
        if self._prefix is not None:
            sources.append(self._prefix.snapshot_heads(max_heads))
        if self._tiers is not None:
            sources.append(self._tiers.stable_heads(max_heads))
        for src in sources:
            for h, d in src:
                if len(heads) >= max_heads:
                    return heads
                if h not in seen:
                    seen.add(h)
                    heads.append((h, d))
        return heads

    def preempt(self, slot: int) -> None:
        """Checkpoint a live slot and requeue it at its lane head: the
        sequence's KV blocks are exported onto the handle
        (handle.kv_state), the slot and blocks are released, and the
        next admission resumes decoding through the adopt path — the
        preempt → resume cycle is token-invisible to the client."""
        if not self._paged:
            raise ValueError("preempt requires kv_layout='paged'")
        st = self._slots[slot]
        handle = st.handle
        if handle is None:
            raise ValueError(f"slot {slot} is not live")
        handle.kv_state = self._export_state(slot)
        self._release_slot(slot, donate=True)
        self._preempted += 1
        self._metrics.preemptions.inc(
            tags={"lane": handle.request.slo})
        self._requeue(handle, front=True)

    def _maybe_preempt(self) -> None:
        """Preemption policy, gated by the PR-7 Hysteresis controller:
        when interactive requests are waiting and admission is starved
        (no free slot, or the pool rejected an interactive admission
        last step), checkpoint the NEWEST-admitted batch decode — it
        has the least sunk prefill work per token emitted. The
        hold/cooldown gate means transient pressure (one tick of a
        full batch) never thrashes checkpoints."""
        if not self._paged:
            return
        with self._lock:
            waiting = len(self._queues["interactive"])
        if not waiting:
            self._preempt_gate.propose(0, 0)
            return
        batch_slots = [
            s for s in range(self.config.num_slots)
            if self._slots[s].handle is not None
            and self._slots[s].handle.request.slo == "batch"
            and not self._slots[s].handle.request.prefill_only
        ]
        pressure = bool(batch_slots) and (
            not self._free or self._admit_blocked)
        if self._preempt_gate.propose(0, 1 if pressure else 0) != 1:
            return
        victim = max(batch_slots,
                     key=lambda s: self._slots[s].handle.admitted_at)
        try:
            from ray_tpu.observability.control import record_decision

            record_decision(
                "llm_engine", "preempt",
                "interactive lane starved; checkpointing newest batch "
                "decode", float(waiting), slot=victim)
        except Exception:
            pass
        self.preempt(victim)

    def _process_cancels(self) -> None:
        """Tear down cancelled requests on the scheduler thread (the
        only thread allowed to touch slots/blocks): live slots are
        released, requeued checkpoints are dropped."""
        with self._lock:
            if not self._cancelled:
                return
            ids, self._cancelled = self._cancelled, set()
            requeued = []
            for q in self._queues.values():
                for h in list(q):
                    if h.request_id in ids:
                        q.remove(h)
                        requeued.append(h)
        for h in requeued:
            self._finish_cancelled(h)
        for slot in range(self.config.num_slots):
            h = self._slots[slot].handle
            if h is not None and h.request_id in ids:
                self._release_slot(slot)
                self._finish_cancelled(h)

    @staticmethod
    def _capture_trace(handle: RequestHandle) -> None:
        """Stamp the submitting thread's TraceContext onto the handle
        and pre-allocate the llm.request span id, so scheduler-thread
        phase reconstruction can parent spans correctly without any
        ambient context of its own."""
        try:
            from ray_tpu.util.tracing import current_trace, new_span_id

            tc = current_trace()
            if tc is not None:
                handle.trace = tc
                handle.trace_span_id = new_span_id()
        except Exception:
            pass  # telemetry must never break submit

    @staticmethod
    def _phase_trace(handle: RequestHandle) -> Optional[Dict[str, Any]]:
        """Explicit trace fields for a phase/KV span of this request:
        fresh span id parented under the handle's llm.request span."""
        if handle.trace is None:
            return None
        from ray_tpu.util.tracing import new_span_id

        return {"trace_id": handle.trace.trace_id,
                "span_id": new_span_id(),
                "parent_span_id": handle.trace_span_id}

    def _record_finished(self, handle: RequestHandle) -> None:
        """Latency histograms + per-request lifecycle spans
        (queued -> prefill -> decode) so `/metrics` and
        `ray_tpu.timeline()` both render a serve run end-to-end. Spans
        carry the request's captured trace identity — passed explicitly
        (not via ambient context: this runs on the scheduler thread),
        so the GCS assembles them under the request's causal tree. The
        TTFT observation links its trace_id as the histogram exemplar —
        the dashboard's jump from "p99 is bad" to the worst request's
        actual trace."""
        m = self._metrics
        e2e = handle.finished_at - handle.submitted_at
        trace_id = handle.trace.trace_id if handle.trace else None
        m.e2e.observe(e2e, trace_id=trace_id)
        if handle.ttft_s is not None:
            m.ttft.observe(handle.ttft_s, trace_id=trace_id)
        if handle.tpot_s is not None:
            m.tpot.observe(handle.tpot_s)
        m.tokens.inc(float(len(handle.tokens)))
        m.requests.inc(tags={"finish_reason": handle.finish_reason})
        try:
            from ray_tpu.util.tracing import record_span

            # Monotonic offsets re-anchored on the wall-clock submit
            # time so span rows line up with task events.
            wall0 = handle.submitted_wall
            rid = handle.request_id
            admit = handle.admitted_at or handle.finished_at
            record_span("llm.queued", wall0,
                        admit - handle.submitted_at, attrs={"rid": rid},
                        trace=self._phase_trace(handle))
            if handle.first_token_at is not None:
                record_span(
                    "llm.prefill",
                    wall0 + (admit - handle.submitted_at),
                    handle.first_token_at - admit, attrs={"rid": rid},
                    trace=self._phase_trace(handle))
                record_span(
                    "llm.decode",
                    wall0 + (handle.first_token_at - handle.submitted_at),
                    handle.finished_at - handle.first_token_at,
                    attrs={"rid": rid,
                           "tokens": len(handle.tokens)},
                    trace=self._phase_trace(handle))
            req_trace = None
            if handle.trace is not None:
                # The llm.request span itself parents under the span
                # active at submit (the replica's llm.server_call).
                req_trace = {"trace_id": handle.trace.trace_id,
                             "span_id": handle.trace_span_id,
                             "parent_span_id": handle.trace.span_id}
            record_span("llm.request", wall0, e2e, attrs={
                "rid": rid, "tokens": len(handle.tokens),
                "finish_reason": handle.finish_reason},
                trace=req_trace)
        except Exception:
            pass  # telemetry must never break the scheduler
        self._account_finished(handle, e2e)

    def _account_finished(self, handle: RequestHandle,
                          e2e: float) -> None:
        """Close the request's cost meter. A "prefill" finish does NOT
        fold — its snapshot rides the disagg hand-off next to the
        KVState and the decode tier's meter absorbs it, so the whole
        migrated request lands on one ledger row."""
        meter = handle.meter
        if meter is None:
            return
        try:
            computed = handle.prefilled_tokens
            avoided = 0
            if not handle._adopted_submit:
                # prefix/tier hits = prompt positions this engine never
                # prefilled. Adopted submissions skip the credit: their
                # prompt was prefilled (and already credited) by the
                # exporting engine.
                avoided = max(len(handle.request.prompt) - computed, 0)
            meter.note_prefill(computed, avoided)
            if handle.finish_reason == "prefill":
                if handle.ttft_s is not None:
                    meter.ttft_s = handle.ttft_s
                return
            from ray_tpu.observability.accounting import fold_finished

            row = meter.finalize(
                handle.finish_reason or "unknown",
                len(handle.tokens), ttft_s=handle.ttft_s,
                tpot_s=handle.tpot_s, e2e_s=e2e)
            fold_finished(row)
        except Exception:
            pass  # accounting must never break the scheduler

    def step(self) -> bool:
        """One scheduler iteration: process cancellations, apply the
        preemption policy, admit queued requests into free slots
        (prefill + first token each; prefill_only requests finish here
        with their checkpoint), then one decode tick — speculative when
        every live slot qualifies, plain otherwise — for every live
        slot. Returns True if any work was done."""
        import numpy as np

        did_cancel = bool(self._cancelled)
        did_ctrl = self._process_ctrl()
        self._process_cancels()
        self._maybe_preempt()
        self._admit_blocked = False
        inserted = self._admit()
        if inserted:
            # First generated token per freshly-prefilled slot (before
            # the tick below overwrites it with the second). Adopted
            # slots skip this: their pending token was emitted by the
            # exporting engine already.
            tok_host = np.asarray(self._tok)
            for slot, fresh in inserted:
                if not fresh:
                    continue
                if self._slots[slot].handle.request.prefill_only:
                    self._finish_prefill(slot, int(tok_host[slot]))
                else:
                    self._emit(slot, int(tok_host[slot]))
        if not self._active.any():
            self._update_gauges()
            return bool(inserted) or did_cancel or did_ctrl
        live = np.nonzero(self._active)[0]
        if self._spec_ready(live):
            t_tick = time.monotonic()
            toks_host, n_emit = self._spec_tick()
            self._credit_decode(live, time.monotonic() - t_tick)
            if self._acct:
                # Per-slot speculative accounting: a live slot's round
                # proposed spec_k - 1 drafts and accepted n_emit - 1.
                k_prop = self.config.spec_k - 1
                for slot in live:
                    s = int(slot)
                    h = self._slots[s].handle
                    if h is not None and h.meter is not None \
                            and int(n_emit[s]) > 0:
                        h.meter.note_spec(k_prop, int(n_emit[s]) - 1)
            for slot in live:
                s = int(slot)
                for k in range(int(n_emit[s])):
                    if self._slots[s].handle is None:
                        break      # finished earlier in the round —
                        #            remaining tokens were speculative
                    self._emit(s, int(toks_host[k, s]))
            self._update_gauges()
            return True
        t_tick = time.monotonic()
        if self._paged:
            self._cache, self._tok, self._pos, self._key, toks = \
                self._jit_tick(
                    self.params, self._cache, self._tables.copy(),
                    self._tok, self._pos, self._active.copy(),
                    self._temp.copy(), self._key)
        else:
            self._cache, self._tok, self._pos, self._key, toks = \
                self._jit_tick(
                    self.params, self._cache, self._tok, self._pos,
                    self._active.copy(), self._temp.copy(), self._key)
        toks_host = np.asarray(toks)                # [K, B]
        self._credit_decode(live, time.monotonic() - t_tick)
        for slot in live:
            s = int(slot)
            for k in range(toks_host.shape[0]):
                if self._slots[s].handle is None:
                    break          # finished earlier in the block —
                    #                remaining tokens were speculative
                self._emit(s, int(toks_host[k, s]))
        self._update_gauges()
        return True

    def _credit_decode(self, live, dt: float) -> None:
        """Split one decode/verify tick's wall time evenly across the
        slots that were live in it (an attribution, not a hardware
        counter — documented as approximate in accounting.py). Runs
        BEFORE the emit loop so a request finishing this tick still
        gets billed for it."""
        if not self._acct or dt <= 0 or len(live) == 0:
            return
        share = dt / len(live)
        for slot in live:
            h = self._slots[int(slot)].handle
            if h is not None and h.meter is not None:
                h.meter.note_chip("decode", share)

    def _spec_ready(self, live) -> bool:
        """A speculative round runs only when EVERY live slot
        qualifies: greedy sampling (acceptance compares argmaxes),
        draft cache seeded (spec_ok), and spec_k - 1 positions of
        headroom before the sequence limit. Mixed batches fall back to
        the plain tick — correctness never depends on this gate, only
        decode speed."""
        import numpy as np

        if self._draft is None:
            return False
        if not bool(self._spec_ok[live].all()):
            return False
        if bool((self._temp[live] > 0).any()):
            return False
        pos_host = np.asarray(self._pos)
        return bool((pos_host[live] <= self.config.max_seq_len
                     - self.config.spec_k).all())

    def _spec_tick(self):
        """Run one speculative round and return (tokens [K, B] host,
        n_emit [B] host); the caller emits tokens[0:n_emit[s], s] per
        slot."""
        import numpy as np

        (self._cache, self._draft_cache, self._tok, self._pos,
         t, n_emit) = self._jit_spec(
            self.params, self._draft, self._cache, self._draft_cache,
            self._tables.copy(), self._tok, self._pos,
            self._active.copy())
        n_host = np.asarray(n_emit)
        live = int((n_host > 0).sum())
        self._spec_rounds += 1
        self._spec_proposed += (self.config.spec_k - 1) * live
        self._spec_accepted += int(n_host.sum()) - live
        self._metrics.spec_proposed.inc(
            float((self.config.spec_k - 1) * live))
        self._metrics.spec_accepted.inc(float(int(n_host.sum()) - live))
        return np.asarray(t).T, n_host

    def _update_gauges(self) -> None:
        m = self._metrics
        active = int(self._active.sum())
        with self._lock:
            depths = {lane: len(q) for lane, q in self._queues.items()}
        m.queue_depth.set(float(sum(depths.values())))
        for lane, d in depths.items():
            m.lane_queue_depth.set(float(d), tags={"lane": lane})
        if self._spec_proposed:
            m.spec_accept_ratio.set(
                self._spec_accepted / self._spec_proposed)
        m.active_slots.set(float(active))
        m.batch_utilization.set(active / self.config.num_slots)
        if self._paged:
            m.kv_blocks_used.set(float(self._allocator.used_blocks))
            m.kv_blocks_free.set(float(self._allocator.free_blocks))
            if self._prefix is not None:
                cur = self._prefix.stats()
                seen = self._prefix_seen
                for field, ctr in (("hits", m.prefix_hits),
                                   ("misses", m.prefix_misses),
                                   ("hit_tokens", m.prefix_hit_tokens),
                                   ("evictions", m.prefix_evictions)):
                    d = cur[field] - seen[field]
                    if d > 0:
                        ctr.inc(float(d))
                        seen[field] = cur[field]
            if self._tiers is not None:
                ts = self._tiers.stats()
                for tier in ("host", "store"):
                    cur, seen = ts[tier], self._tier_seen[tier]
                    for field, ctr in (
                            ("hits", m.prefix_tier_hits),
                            ("misses", m.prefix_tier_misses),
                            ("spills", m.prefix_tier_spills),
                            ("promotes", m.prefix_tier_promotes)):
                        d = cur[field] - seen[field]
                        if d > 0:
                            ctr.inc(float(d), tags={"tier": tier})
                            seen[field] = cur[field]
                    m.kv_tier_bytes.set(float(cur["bytes"]),
                                        tags={"tier": tier})
                m.kv_tier_bytes.set(float(self._allocator.used_bytes),
                                    tags={"tier": "hbm"})

    def run(self, stop_event: threading.Event,
            idle_wait_s: float = 0.02) -> None:
        """Scheduler loop for a background thread (one per engine)."""
        while not stop_event.is_set():
            if not self.step():
                self._work.clear()
                if not self.has_work():
                    self._work.wait(idle_wait_s)

    def drain(self, timeout: float = 300.0) -> None:
        """Synchronously step until queue and slots are empty (tests and
        offline batch use; do not mix with a run() thread)."""
        deadline = time.monotonic() + timeout
        while self.has_work():
            if time.monotonic() > deadline:
                raise TimeoutError("engine did not drain")
            self.step()

    def warmup(self) -> None:
        """Compile every program the engine can run — the decode tick
        plus one insert per prefill bucket — before real traffic. The
        paged layout bypasses the prefix cache while warming: a warm
        hit shrinks the padded suffix to a SMALLER bucket, leaving the
        larger bucket's insert uncompiled until a cache-miss request
        pays the compile inside its own latency. Synchronous; call
        before starting a run() thread."""
        prefix, self._prefix = self._prefix, None
        draft, self._draft = self._draft, None
        try:
            # max_tokens=2: a 1-token request finishes AT insert and the
            # decode tick would never trace. Draft disabled: phase one
            # compiles the PLAIN tick (the spec gate would otherwise
            # route every greedy warmup batch through _jit_spec).
            handles = [self.submit(Request(prompt=[1] * b, max_tokens=2))
                       for b in self.config.prefill_buckets]
            while any(h.finished_at is None for h in handles):
                self.step()
            if draft is not None:
                # Phase two: draft inserts (one per bucket) + the
                # speculative round program.
                self._draft = draft
                handles = [self.submit(
                    Request(prompt=[1] * b, max_tokens=2))
                    for b in self.config.prefill_buckets]
                while any(h.finished_at is None for h in handles):
                    self.step()
        finally:
            self._prefix = prefix
            self._draft = draft

    # ------------------------------------------------------------ inspection

    @property
    def trace_count(self) -> int:
        """Number of engine XLA programs traced so far (compile guard:
        bounded by the per-family trace budgets under any workload —
        len(buckets) inserts + 1 tick, plus at most 1 export, 1 adopt,
        1 spec round, and len(buckets) draft inserts when wired)."""
        n = self._jit_tick.traces + self._jit_insert.traces
        for name in ("_jit_export", "_jit_adopt", "_jit_spec",
                     "_jit_draft_insert"):
            fn = getattr(self, name, None)
            if fn is not None:
                n += fn.traces
        return n

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            queued_by_lane = {lane: len(q)
                              for lane, q in self._queues.items()}
        traces = {"tick": self._jit_tick.traces,
                  "insert": self._jit_insert.traces}
        for name in ("export", "adopt", "spec", "draft_insert"):
            fn = getattr(self, f"_jit_{name}", None)
            if fn is not None:
                traces[name] = fn.traces
        out = {
            "num_slots": self.config.num_slots,
            "active_slots": int(self._active.sum()),
            "queued": sum(queued_by_lane.values()),
            "queued_by_lane": queued_by_lane,
            "completed": self._completed,
            "slot_reuses": self._slot_reuses,
            "preempted": self._preempted,
            "kv_layout": self.config.kv_layout,
            "traces": traces,
            "trace_count": self.trace_count,
        }
        if self._paged:
            out["kv"] = dict(self._allocator.stats(),
                             block_size=self.config.kv_block_size)
            out["migration"] = {
                "blocks": self._migrated_blocks,
                "bytes": self._migrated_bytes,
            }
            if self._prefix is not None:
                out["prefix_cache"] = self._prefix.stats()
            if self._tiers is not None:
                out["kv_tiers"] = dict(
                    self._tiers.stats(),
                    promoted_blocks=self._promoted_blocks,
                    promote_skips=self._promote_skips)
        if self._draft is not None or self._spec_rounds:
            denom = max(self._spec_proposed, 1)
            out["spec"] = {
                "rounds": self._spec_rounds,
                "proposed": self._spec_proposed,
                "accepted": self._spec_accepted,
                "accept_ratio": self._spec_accepted / denom,
            }
        return out


def _tier_store_put(prefix):
    """Object-store leg of the KV hierarchy: demote a KVPrefix below
    host RAM. Raises when no cluster is attached — KVTierManager then
    counts the drop and moves on (a dropped block is a future
    recompute, never an error)."""
    import ray_tpu
    from ray_tpu._private.worker import global_worker_or_none

    if global_worker_or_none() is None:
        raise RuntimeError(
            "no cluster attached: object-store KV tier unavailable")
    return ray_tpu.put(prefix)


def _tier_store_get(ref):
    import ray_tpu

    return ray_tpu.get(ref, timeout=30.0)


def _sample(logits, temp, key):
    """Per-row sampling: greedy where temp == 0, else temperature
    categorical. Both branches are computed (fixed shape); `where`
    selects."""
    import jax
    import jax.numpy as jnp

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temp, 1e-6)[:, None]
    sampled = jax.random.categorical(key, scaled).astype(jnp.int32)
    return jnp.where(temp > 0, sampled, greedy)


def static_batch_generate(params, model_config, requests: List[Request],
                          batch_size: int, pad_to: int,
                          steps: Optional[int] = None,
                          warmup: bool = True):
    """The lockstep baseline the engine replaces: group requests in
    arrival order, pad prompts to `pad_to`, decode `steps` (default:
    max(max_tokens)) per group via models.llama.generate, truncate per
    request. Used by bench.py for the continuous-vs-static comparison
    on identical geometry (one compiled program: fixed B/P/N). Returns
    (outputs, per-batch seconds) — the timings let the bench couple
    batches to an arrival trace.

    Throughput baseline ONLY: `generate` has no padding mask, so a
    prompt shorter than `pad_to` sees trailing pad tokens in its context
    and its output tokens differ from the unpadded result — which is one
    of the deficiencies of the static path (the other, measured by the
    bench, is that every request decodes for the group max). Compute
    cost is identical to real content at the same shapes, so the timing
    stands."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models.llama import generate

    steps = steps or max(r.max_tokens for r in requests)
    from ray_tpu.observability.jit import tracked_jit

    gen = tracked_jit(lambda p, t: generate(p, t, model_config,
                                            max_new_tokens=steps),
                      name="llm_generate_batch")
    if warmup:                              # compile outside the timings
        np.asarray(gen(params, jnp.zeros((batch_size, pad_to),
                                         jnp.int32)))
    outs: List[List[int]] = []
    batch_seconds: List[float] = []
    for i in range(0, len(requests), batch_size):
        group = requests[i:i + batch_size]
        toks = np.zeros((batch_size, pad_to), np.int32)
        for j, r in enumerate(group):
            toks[j, :len(r.prompt)] = np.asarray(r.prompt, np.int32)
        t0 = time.monotonic()
        out = np.asarray(gen(params, jnp.asarray(toks)))
        batch_seconds.append(time.monotonic() - t0)
        for j, r in enumerate(group):
            outs.append(out[j, :r.max_tokens].tolist())
    return outs, batch_seconds
