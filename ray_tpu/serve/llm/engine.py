"""Continuous-batching LLM inference engine — the Serve-on-TPU data plane.

The serving problem on TPU is a compile-boundary problem: XLA programs
are shape-specialized, so a naive server that launches one `generate`
per request (or per ad-hoc batch) either retraces constantly or decodes
in lockstep where every short request pays for the longest one
(models/llama.py::generate — the static path this engine replaces).
Podracer (arXiv:2104.06272) and RLAX (arXiv:2512.06392) both land on
the same answer: keep ONE fixed-shape compiled program fed continuously.

Design — a bounded set of compiled programs, everything else is data:

- A fixed pool of ``B = num_slots`` decode slots sharing one KV cache
  ``[L, B, S, n_kv, head_dim]``. Per-slot position/last-token/active
  state are device arrays with fixed shapes.
- ONE jitted decode tick advances all live slots together
  (models/llama.py::decode_step with the slot-active mask: dead slots
  ride through the program but their KV writes are dropped). The tick
  runs `decode_block` steps per dispatch through an internal lax.scan —
  still one compiled program — to amortize host dispatch/readback on
  tunneled TPU backends.
- Jitted prefill at a small set of padded prompt-length buckets; the
  resulting per-layer KV lands in the shared cache at a slot index via
  one `dynamic_update_slice` (insert-at-slot). One compiled program per
  bucket, so a mixed workload traces exactly
  ``len(prefill_buckets) + 1`` engine programs — `trace_count` exposes
  the number for the compile-guard test.
- Slot eviction/recycling is host-side bookkeeping: EOS / stop-token /
  max_tokens free the slot, the next queued request prefills into it.
  Stale KV beyond a recycled slot's new position is harmless — decode
  masks positions > pos and overwrites each position before ever
  attending to it.

Greedy decoding is token-identical to per-request
`models.llama.generate`: padding columns contribute exact zeros through
the masked softmax, so bucket-padded prefill and the shared-cache
decode reproduce the static path bit-for-bit (pinned by
tests/test_serve_llm.py::test_greedy_parity_*).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class EngineConfig:
    """Shapes of the engine's compiled programs (all static)."""

    num_slots: int = 8              # B: concurrent sequences in flight
    max_seq_len: int = 512          # S: shared KV cache length per slot
    # Padded prompt lengths; a prompt compiles into the smallest bucket
    # that holds it. Keep this SHORT — each bucket is one XLA program.
    prefill_buckets: Tuple[int, ...] = (32, 64, 128)
    eos_id: Optional[int] = None    # config-level end-of-sequence token
    # Decode steps per tick dispatch (lax.scan inside the ONE tick
    # program). >1 amortizes host dispatch/readback — decisive on
    # tunneled TPU backends (~tens of ms per round trip) — at the cost
    # of up to K-1 speculative tokens per finished slot (computed, then
    # discarded host-side; parity is unaffected because truncation
    # happens at the same stop condition single-stepping would hit) and
    # admission latency of one block.
    decode_block: int = 1
    # KV layout. "dense": one [S] stripe per slot (the PR-1 layout).
    # "paged": a fixed pool of [kv_block_size]-row blocks shared by all
    # slots through per-slot block tables (serve/llm/kv_cache.py) —
    # short requests stop reserving max_seq rows, and the prefix cache
    # can skip prefill for shared prompt prefixes. Both layouts are
    # token-exact for greedy decoding and trace the same number of
    # programs (block tables are data, not shape).
    kv_layout: str = "dense"
    # None -> GlobalConfig.serve_kv_block_size (RAY_TPU_-overridable).
    kv_block_size: Optional[int] = None
    # Pool size; None -> num_slots * (max_seq_len / kv_block_size), the
    # dense equivalent (no memory saving, full parity). Undersize it to
    # oversubscribe HBM: admission queues on exhaustion, never crashes.
    num_kv_blocks: Optional[int] = None
    prefix_cache: bool = True       # paged only: prompt-prefix reuse

    def __post_init__(self):
        if self.decode_block < 1:
            raise ValueError("decode_block must be >= 1")
        if not self.prefill_buckets:
            raise ValueError("need at least one prefill bucket")
        b = tuple(sorted(set(int(x) for x in self.prefill_buckets)))
        object.__setattr__(self, "prefill_buckets", b)
        if b[-1] > self.max_seq_len:
            raise ValueError(
                f"largest prefill bucket {b[-1]} exceeds max_seq_len "
                f"{self.max_seq_len}")
        if self.kv_layout not in ("dense", "paged"):
            raise ValueError(
                f"kv_layout must be 'dense' or 'paged', got "
                f"{self.kv_layout!r}")
        if self.kv_block_size is None:
            from ray_tpu._private.config import GlobalConfig

            object.__setattr__(self, "kv_block_size",
                               int(GlobalConfig.serve_kv_block_size))
        if self.kv_layout == "paged":
            bs = self.kv_block_size
            if bs < 1:
                raise ValueError("kv_block_size must be >= 1")
            if self.max_seq_len % bs:
                raise ValueError(
                    f"max_seq_len {self.max_seq_len} must be a multiple "
                    f"of kv_block_size {bs} (block tables tile the "
                    f"sequence exactly)")
            bad = [x for x in b if x % bs]
            if bad:
                raise ValueError(
                    f"prefill buckets {bad} must be multiples of "
                    f"kv_block_size {bs} (suffix KV scatters whole "
                    f"blocks)")
            if self.num_kv_blocks is not None and self.num_kv_blocks < 1:
                raise ValueError("num_kv_blocks must be >= 1")

    @property
    def max_blocks_per_slot(self) -> int:
        return self.max_seq_len // self.kv_block_size

    @property
    def pool_blocks(self) -> int:
        if self.num_kv_blocks is not None:
            return self.num_kv_blocks
        return self.num_slots * self.max_blocks_per_slot


@dataclasses.dataclass
class Request:
    """One generation request (token-id domain; tokenization is the
    caller's concern)."""

    prompt: Sequence[int]
    max_tokens: int = 64
    temperature: float = 0.0
    stop: Tuple[int, ...] = ()      # tokens that halt WITHOUT being emitted
    # Streaming hook: called as on_token(request_id, token_id) from the
    # engine loop as each token lands.
    on_token: Optional[Callable[[int, int], None]] = None


class RequestHandle:
    """Host-side view of a submitted request; completion is an Event."""

    def __init__(self, request_id: int, request: Request):
        self.request_id = request_id
        self.request = request
        self.tokens: List[int] = []
        self.submitted_at = time.monotonic()
        self.admitted_at: Optional[float] = None
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        # Wall-clock mirror of submitted_at: lifecycle spans need
        # epoch timestamps (timeline rows), latency math stays
        # monotonic.
        self.submitted_wall = time.time()
        self.finish_reason: Optional[str] = None   # "eos"|"stop"|"length"
        self._done = threading.Event()

    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None) -> List[int]:
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request_id} not finished in {timeout}s")
        return self.tokens

    # Latency accounting for the bench (seconds).
    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_at is None:
            return None
        return self.first_token_at - self.submitted_at

    @property
    def tpot_s(self) -> Optional[float]:
        """Mean per-output-token latency after the first token."""
        if self.finished_at is None or self.first_token_at is None:
            return None
        n = len(self.tokens)
        if n <= 1:
            return 0.0
        return (self.finished_at - self.first_token_at) / (n - 1)


class _Slot:
    __slots__ = ("handle", "uses")

    def __init__(self):
        self.handle: Optional[RequestHandle] = None
        self.uses = 0


class LLMEngine:
    """Slot-based continuous-batching engine over a Llama param set.

    Host-side scheduler + two families of jitted device programs
    (`_insert` per prefill bucket, `_tick` for the decode step). Thread
    model: `submit()` is thread-safe; `step()`/`run()` must be driven by
    a single scheduler thread (serve/llm/deployment.py runs one per
    replica).
    """

    def __init__(self, params: Any, model_config: Any,
                 engine_config: Optional[EngineConfig] = None,
                 rng_seed: int = 0):
        import jax
        import jax.numpy as jnp
        import numpy as np

        from ray_tpu.models.llama import init_kv_cache, init_paged_kv_cache

        self.params = params
        self.model_config = model_config
        self.config = engine_config or EngineConfig()
        c = self.config
        B = c.num_slots

        # Device state (fixed shapes for the engine's whole lifetime).
        self._paged = c.kv_layout == "paged"
        if self._paged:
            from ray_tpu.serve.llm.kv_cache import (BlockAllocator,
                                                    PrefixCache)

            self._cache = init_paged_kv_cache(
                model_config, c.pool_blocks, c.kv_block_size)
            self._allocator = BlockAllocator(c.pool_blocks,
                                             c.kv_block_size)
            self._prefix = (PrefixCache(self._allocator)
                            if c.prefix_cache else None)
            # Per-slot block tables (host copy is the truth; the device
            # sees it as a plain [B, max_blocks] int32 argument — data,
            # not shape, so tables never retrace anything).
            self._tables = np.zeros((B, c.max_blocks_per_slot), np.int32)
            self._slot_blocks: List[List[int]] = [[] for _ in range(B)]
            self._prefix_seen = {"hits": 0, "misses": 0,
                                 "hit_tokens": 0, "evictions": 0}
        else:
            self._cache = init_kv_cache(model_config, B, c.max_seq_len)
            self._allocator = None
            self._prefix = None
        self._tok = jnp.zeros((B,), jnp.int32)
        self._pos = jnp.zeros((B,), jnp.int32)
        self._key = jax.random.key(rng_seed)
        # Host-side mirrors fed into each program call (tiny transfers).
        self._active = np.zeros((B,), bool)
        self._temp = np.zeros((B,), np.float32)

        # Host-side scheduler state.
        self._slots = [_Slot() for _ in range(B)]
        self._free: deque = deque(range(B))
        self._queue: deque = deque()
        self._lock = threading.Lock()
        self._work = threading.Event()
        self._ids = itertools.count()
        self._completed = 0
        self._slot_reuses = 0

        # Compile tracking through the shared telemetry plane: the
        # TrackedJit probe runs ONLY when jax traces a new program, so
        # .traces counts compiled engine programs — the compile-guard
        # test asserts trace_count <= n_buckets + 1, and the recompile
        # detector warns if either program family exceeds its budget
        # (ONE tick, one insert per prefill bucket).
        from ray_tpu.observability import serve_metrics, tracked_jit
        from ray_tpu.observability.device import ensure_sampler_registered

        if self._paged:
            self._jit_tick = tracked_jit(
                self._tick_fn_paged, name="llm_engine_tick",
                trace_budget=1, donate_argnums=(1, 3, 4))
            self._jit_insert = tracked_jit(
                self._insert_fn_paged, name="llm_engine_insert",
                trace_budget=len(c.prefill_buckets),
                donate_argnums=(1, 2, 3))
        else:
            self._jit_tick = tracked_jit(
                self._tick_fn, name="llm_engine_tick", trace_budget=1,
                donate_argnums=(1, 2, 3))
            self._jit_insert = tracked_jit(
                self._insert_fn, name="llm_engine_insert",
                trace_budget=len(c.prefill_buckets),
                donate_argnums=(1, 2, 3))
        self._metrics = serve_metrics()
        ensure_sampler_registered()

    # ------------------------------------------------------------ programs

    def _tick_fn(self, params, cache, tok, pos, active, temp, key):
        """`decode_block` decode steps for all B slots in one dispatch
        (lax.scan — still ONE compiled program). Inactive slots are
        computed but masked: no KV write, token/pos parked. Positions
        clamp at S-1 so a slot finishing mid-block can speculate ahead
        without ever attending past rows it wrote itself; the host
        discards post-stop tokens."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.llama import decode_step

        S = self.config.max_seq_len

        def body(carry, _):
            cache, tok, pos, key = carry
            logits, cache = decode_step(params, cache, tok, pos,
                                        self.model_config, active=active)
            key, sub = jax.random.split(key)
            nxt = _sample(logits, temp, sub)
            tok = jnp.where(active, nxt, tok)
            pos = jnp.where(active, jnp.minimum(pos + 1, S - 1), pos)
            return (cache, tok, pos, key), tok

        (cache, tok, pos, key), toks = jax.lax.scan(
            body, (cache, tok, pos, key), None,
            length=self.config.decode_block)
        return cache, tok, pos, key, toks          # toks: [K, B]

    def _insert_fn(self, params, cache, tok, pos, padded_prompt,
                   prompt_len, slot, temperature, key):
        """Prefill one bucket-padded prompt and splice its KV into the
        shared cache at `slot`; sample the first generated token from
        the logits at the last REAL prompt position. One trace per
        bucket length (the shape of `padded_prompt`)."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        from ray_tpu.models.llama import lm_head_weight, prefill_kv

        c = self.model_config
        hidden, ks, vs = prefill_kv(params, padded_prompt[None], c)
        # ks/vs: [L, 1, Pb, n_kv, hd] -> rows [0, Pb) of this slot.
        cache = {
            "k": lax.dynamic_update_slice(
                cache["k"], ks.astype(c.dtype), (0, slot, 0, 0, 0)),
            "v": lax.dynamic_update_slice(
                cache["v"], vs.astype(c.dtype), (0, slot, 0, 0, 0)),
        }
        x_last = lax.dynamic_index_in_dim(
            hidden[0], prompt_len - 1, axis=0, keepdims=False)
        logits = jax.lax.dot_general(
            x_last[None], lm_head_weight(params, c),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # [1, V]
        key, sub = jax.random.split(key)
        first = _sample(logits, temperature[None], sub)[0]
        tok = tok.at[slot].set(first)
        pos = pos.at[slot].set(prompt_len)
        return cache, tok, pos, key

    def _tick_fn_paged(self, params, pools, tables, tok, pos, active,
                       temp, key):
        """Paged twin of `_tick_fn`: same scan, same sampling, but the
        KV write/read goes through the block tables (data, so still ONE
        compiled program regardless of who owns which block)."""
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.llama import decode_step_paged

        S = self.config.max_seq_len

        def body(carry, _):
            pools, tok, pos, key = carry
            logits, pools = decode_step_paged(
                params, pools, tables, tok, pos, self.model_config,
                active=active)
            key, sub = jax.random.split(key)
            nxt = _sample(logits, temp, sub)
            tok = jnp.where(active, nxt, tok)
            pos = jnp.where(active, jnp.minimum(pos + 1, S - 1), pos)
            return (pools, tok, pos, key), tok

        (pools, tok, pos, key), toks = jax.lax.scan(
            body, (pools, tok, pos, key), None,
            length=self.config.decode_block)
        return pools, tok, pos, key, toks          # toks: [K, B]

    def _insert_fn_paged(self, params, pools, tok, pos, table_row,
                         hist_len, padded_suffix, suffix_len,
                         new_block_ids, slot, temperature, key):
        """Prefill the (possibly prefix-truncated) suffix of one prompt
        and scatter its KV into the slot's freshly-allocated blocks.

        The prefix-hit path IS the miss path: ``hist_len`` (dynamic
        data) tells `prefill_kv_paged` where the suffix starts; a miss
        is just hist_len = 0 over an all-zero history. One trace per
        suffix bucket — the only static shapes are ``padded_suffix``
        [Pb] and ``new_block_ids`` [Pb / block_size], both functions of
        the bucket — so compile count stays <= len(prefill_buckets).
        """
        import jax
        import jax.numpy as jnp

        from ray_tpu.models.llama import lm_head_weight, prefill_kv_paged

        c = self.model_config
        bs = self.config.kv_block_size
        L = pools["k"].shape[0]
        n_kv, hd = pools["k"].shape[3], pools["k"].shape[4]
        S_pad = self.config.max_blocks_per_slot * bs
        Pb = padded_suffix.shape[0]
        # History view: this slot's dense [S_pad] gather. Rows at and
        # past hist_len are stale — masked inside prefill_kv_paged.
        hist_k = pools["k"][:, table_row].reshape(L, S_pad, n_kv, hd)
        hist_v = pools["v"][:, table_row].reshape(L, S_pad, n_kv, hd)
        hidden, ks, vs = prefill_kv_paged(
            params, padded_suffix[None], hist_len, hist_k, hist_v, c)
        # ks/vs: [L, 1, Pb, n_kv, hd] -> whole blocks into the pool at
        # the slot's new physical ids (padding rows ride along; decode
        # overwrites each before attending, exactly like the dense path
        # tolerates stale rows).
        kb = ks[:, 0].astype(c.dtype).reshape(L, Pb // bs, bs, n_kv, hd)
        vb = vs[:, 0].astype(c.dtype).reshape(L, Pb // bs, bs, n_kv, hd)
        pools = {
            "k": pools["k"].at[:, new_block_ids].set(kb),
            "v": pools["v"].at[:, new_block_ids].set(vb),
        }
        x_last = jax.lax.dynamic_index_in_dim(
            hidden[0], suffix_len - 1, axis=0, keepdims=False)
        logits = jax.lax.dot_general(
            x_last[None], lm_head_weight(params, c),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # [1, V]
        key, sub = jax.random.split(key)
        first = _sample(logits, temperature[None], sub)[0]
        tok = tok.at[slot].set(first)
        pos = pos.at[slot].set(hist_len + suffix_len)
        return pools, tok, pos, key

    # ----------------------------------------------------------- submission

    def submit(self, request: Request) -> RequestHandle:
        if len(request.prompt) == 0:
            raise ValueError("empty prompt")
        if len(request.prompt) > self.config.prefill_buckets[-1]:
            raise ValueError(
                f"prompt length {len(request.prompt)} exceeds largest "
                f"prefill bucket {self.config.prefill_buckets[-1]}")
        if request.max_tokens < 1:
            raise ValueError("max_tokens must be >= 1")
        if self._paged:
            # A request the pool can never hold must fail loudly at
            # submit — queuing it would deadlock admission forever.
            worst = self._blocks_needed(len(request.prompt),
                                        request.max_tokens)
            worst = max(worst,
                        self._bucket_for(len(request.prompt))
                        // self.config.kv_block_size)
            if worst > self.config.pool_blocks:
                raise ValueError(
                    f"request needs up to {worst} KV blocks but the "
                    f"pool only has {self.config.pool_blocks}; raise "
                    f"num_kv_blocks or lower max_tokens")
        handle = RequestHandle(next(self._ids), request)
        with self._lock:
            self._queue.append(handle)
        self._work.set()
        return handle

    def has_work(self) -> bool:
        return bool(self._queue) or bool(self._active.any())

    # ------------------------------------------------------------ scheduling

    def _bucket_for(self, n: int) -> int:
        for b in self.config.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(n)  # pre-checked in submit()

    def _blocks_needed(self, prompt_len: int, max_tokens: int) -> int:
        """Blocks covering every position this request can ever write:
        prompt + generated tokens + up to decode_block - 1 speculative
        writes after the stop condition, capped at the sequence limit
        (positions clamp at S - 1)."""
        c = self.config
        top = min(prompt_len + max_tokens + c.decode_block - 1,
                  c.max_seq_len)
        return -(-top // c.kv_block_size)

    def _admit(self) -> List[int]:
        """Move queued requests into free slots (one prefill each);
        returns the slots inserted this step. Paged layout: admission
        additionally needs blocks — on pool exhaustion the request goes
        BACK to the queue head and admission stops (requests queue,
        never crash; blocks free as running sequences finish)."""
        import numpy as np

        inserted = []
        while self._free:
            with self._lock:
                if not self._queue:
                    break
                handle = self._queue.popleft()
            slot = self._free.popleft()
            req = handle.request
            if self._paged and not self._admit_paged(handle, slot):
                self._free.appendleft(slot)
                with self._lock:
                    self._queue.appendleft(handle)
                break
            if not self._paged:
                P = len(req.prompt)
                bucket = self._bucket_for(P)
                padded = np.zeros((bucket,), np.int32)
                padded[:P] = np.asarray(req.prompt, np.int32)
                self._cache, self._tok, self._pos, self._key = \
                    self._jit_insert(
                        self.params, self._cache, self._tok, self._pos,
                        padded, np.int32(P), np.int32(slot),
                        np.float32(req.temperature), self._key)
            handle.admitted_at = time.monotonic()
            self._metrics.queue_wait.observe(
                handle.admitted_at - handle.submitted_at)
            st = self._slots[slot]
            if st.uses:
                self._slot_reuses += 1
                self._metrics.slot_reuses.inc()
            st.uses += 1
            st.handle = handle
            self._active[slot] = True
            self._temp[slot] = req.temperature
            inserted.append(slot)
        return inserted

    def _admit_paged(self, handle: RequestHandle, slot: int) -> bool:
        """Block accounting + paged insert for one request. Returns
        False (nothing allocated, nothing inserted) when the pool can't
        cover it even after evicting cold prefix entries."""
        import numpy as np

        req = handle.request
        c = self.config
        bs = c.kv_block_size
        P = len(req.prompt)
        need_total = self._blocks_needed(P, req.max_tokens)

        # Longest cached prefix, capped so the LAST prompt token is
        # always prefilled (its logits seed the first sampled token).
        hit_blocks: List[int] = []
        if self._prefix is not None:
            hit_blocks = self._prefix.match(req.prompt,
                                            max_blocks=(P - 1) // bs)
        # Trim the hit so history + the padded suffix bucket still fit
        # in the slot's table (a shallow hit on a near-max prompt can
        # otherwise push the bucket's whole-block scatter past S).
        while hit_blocks:
            hl = len(hit_blocks) * bs
            if hl + self._bucket_for(P - hl) <= c.max_seq_len:
                break
            self._allocator.free([hit_blocks.pop()])
        n_hit = len(hit_blocks)
        hist_len = n_hit * bs
        suffix_len = P - hist_len
        bucket = self._bucket_for(suffix_len)
        # Fresh blocks: the rest of the sequence, but at least the
        # whole suffix bucket — its scatter writes full blocks, and
        # every written block must be owned by this slot.
        n_new = max(need_total - n_hit, bucket // bs)
        new_blocks = self._allocator.alloc(n_new)
        if new_blocks is None and self._prefix is not None:
            self._prefix.evict(n_new - self._allocator.free_blocks)
            new_blocks = self._allocator.alloc(n_new)
        if new_blocks is None:
            if hit_blocks:
                self._allocator.free(hit_blocks)
            return False

        blocks = hit_blocks + new_blocks
        row = np.zeros((c.max_blocks_per_slot,), np.int32)
        row[:len(blocks)] = blocks
        self._tables[slot] = row
        self._slot_blocks[slot] = blocks

        padded = np.zeros((bucket,), np.int32)
        padded[:suffix_len] = np.asarray(req.prompt[hist_len:], np.int32)
        scatter_ids = np.asarray(new_blocks[:bucket // bs], np.int32)
        self._cache, self._tok, self._pos, self._key = \
            self._jit_insert(
                self.params, self._cache, self._tok, self._pos,
                row, np.int32(hist_len), padded, np.int32(suffix_len),
                scatter_ids, np.int32(slot),
                np.float32(req.temperature), self._key)
        if self._prefix is not None:
            # Register the prompt's FULL blocks (all rows real) so the
            # next request sharing this prefix skips their prefill.
            full = P // bs
            if full:
                self._prefix.insert(req.prompt, blocks[:full])
        return True

    def _emit(self, slot: int, token: int) -> None:
        """Record one generated token for `slot`; free the slot when the
        request is finished (eos/stop halt, max_tokens bounds)."""
        st = self._slots[slot]
        handle = st.handle
        req = handle.request
        now = time.monotonic()
        reason = None
        if token in req.stop:
            reason = "stop"                      # halt, token NOT emitted
        else:
            handle.tokens.append(token)
            if handle.first_token_at is None:
                handle.first_token_at = now
            if req.on_token is not None:
                try:
                    req.on_token(handle.request_id, token)
                except Exception:
                    pass                          # streaming is best-effort
            if (self.config.eos_id is not None
                    and token == self.config.eos_id):
                reason = "eos"                   # halt, eos IS emitted
            elif len(handle.tokens) >= req.max_tokens:
                reason = "length"
        # Hard cap: a slot may never write past the shared cache. The
        # NEXT token would land at pos = prompt + len(tokens); stop while
        # it still fits.
        if reason is None and (len(req.prompt) + len(handle.tokens)
                               >= self.config.max_seq_len):
            reason = "length"
        if reason is not None:
            handle.finish_reason = reason
            handle.finished_at = now
            st.handle = None
            self._active[slot] = False
            self._temp[slot] = 0.0
            if self._paged and self._slot_blocks[slot]:
                # Drop this sequence's refs; blocks shared with the
                # prefix cache (or other sequences) stay resident.
                self._allocator.free(self._slot_blocks[slot])
                self._slot_blocks[slot] = []
            self._free.append(slot)
            self._completed += 1
            self._record_finished(handle)
            handle._done.set()

    def _record_finished(self, handle: RequestHandle) -> None:
        """Latency histograms + per-request lifecycle spans
        (queued -> prefill -> decode) so `/metrics` and
        `ray_tpu.timeline()` both render a serve run end-to-end."""
        m = self._metrics
        e2e = handle.finished_at - handle.submitted_at
        m.e2e.observe(e2e)
        if handle.ttft_s is not None:
            m.ttft.observe(handle.ttft_s)
        if handle.tpot_s is not None:
            m.tpot.observe(handle.tpot_s)
        m.tokens.inc(float(len(handle.tokens)))
        m.requests.inc(tags={"finish_reason": handle.finish_reason})
        try:
            from ray_tpu.util.tracing import record_span

            # Monotonic offsets re-anchored on the wall-clock submit
            # time so span rows line up with task events.
            wall0 = handle.submitted_wall
            rid = handle.request_id
            admit = handle.admitted_at or handle.finished_at
            record_span("llm.queued", wall0,
                        admit - handle.submitted_at, attrs={"rid": rid})
            if handle.first_token_at is not None:
                record_span(
                    "llm.prefill",
                    wall0 + (admit - handle.submitted_at),
                    handle.first_token_at - admit, attrs={"rid": rid})
                record_span(
                    "llm.decode",
                    wall0 + (handle.first_token_at - handle.submitted_at),
                    handle.finished_at - handle.first_token_at,
                    attrs={"rid": rid,
                           "tokens": len(handle.tokens)})
            record_span("llm.request", wall0, e2e, attrs={
                "rid": rid, "tokens": len(handle.tokens),
                "finish_reason": handle.finish_reason})
        except Exception:
            pass  # telemetry must never break the scheduler

    def step(self) -> bool:
        """One scheduler iteration: admit queued requests into free
        slots (prefill + first token each), then one decode tick for
        every live slot. Returns True if any work was done."""
        import numpy as np

        inserted = self._admit()
        if inserted:
            # First generated token per inserted slot (before the tick
            # below overwrites it with the second).
            tok_host = np.asarray(self._tok)
            for slot in inserted:
                self._emit(slot, int(tok_host[slot]))
        if not self._active.any():
            self._update_gauges()
            return bool(inserted)
        live = np.nonzero(self._active)[0]
        if self._paged:
            self._cache, self._tok, self._pos, self._key, toks = \
                self._jit_tick(
                    self.params, self._cache, self._tables.copy(),
                    self._tok, self._pos, self._active.copy(),
                    self._temp.copy(), self._key)
        else:
            self._cache, self._tok, self._pos, self._key, toks = \
                self._jit_tick(
                    self.params, self._cache, self._tok, self._pos,
                    self._active.copy(), self._temp.copy(), self._key)
        toks_host = np.asarray(toks)                # [K, B]
        for slot in live:
            s = int(slot)
            for k in range(toks_host.shape[0]):
                if self._slots[s].handle is None:
                    break          # finished earlier in the block —
                    #                remaining tokens were speculative
                self._emit(s, int(toks_host[k, s]))
        self._update_gauges()
        return True

    def _update_gauges(self) -> None:
        m = self._metrics
        active = int(self._active.sum())
        m.queue_depth.set(float(len(self._queue)))
        m.active_slots.set(float(active))
        m.batch_utilization.set(active / self.config.num_slots)
        if self._paged:
            m.kv_blocks_used.set(float(self._allocator.used_blocks))
            m.kv_blocks_free.set(float(self._allocator.free_blocks))
            if self._prefix is not None:
                cur = self._prefix.stats()
                seen = self._prefix_seen
                for field, ctr in (("hits", m.prefix_hits),
                                   ("misses", m.prefix_misses),
                                   ("hit_tokens", m.prefix_hit_tokens),
                                   ("evictions", m.prefix_evictions)):
                    d = cur[field] - seen[field]
                    if d > 0:
                        ctr.inc(float(d))
                        seen[field] = cur[field]

    def run(self, stop_event: threading.Event,
            idle_wait_s: float = 0.02) -> None:
        """Scheduler loop for a background thread (one per engine)."""
        while not stop_event.is_set():
            if not self.step():
                self._work.clear()
                if not self.has_work():
                    self._work.wait(idle_wait_s)

    def drain(self, timeout: float = 300.0) -> None:
        """Synchronously step until queue and slots are empty (tests and
        offline batch use; do not mix with a run() thread)."""
        deadline = time.monotonic() + timeout
        while self.has_work():
            if time.monotonic() > deadline:
                raise TimeoutError("engine did not drain")
            self.step()

    def warmup(self) -> None:
        """Compile every program the engine can run — the decode tick
        plus one insert per prefill bucket — before real traffic. The
        paged layout bypasses the prefix cache while warming: a warm
        hit shrinks the padded suffix to a SMALLER bucket, leaving the
        larger bucket's insert uncompiled until a cache-miss request
        pays the compile inside its own latency. Synchronous; call
        before starting a run() thread."""
        prefix, self._prefix = self._prefix, None
        try:
            # max_tokens=2: a 1-token request finishes AT insert and the
            # decode tick would never trace.
            handles = [self.submit(Request(prompt=[1] * b, max_tokens=2))
                       for b in self.config.prefill_buckets]
            while any(h.finished_at is None for h in handles):
                self.step()
        finally:
            self._prefix = prefix

    # ------------------------------------------------------------ inspection

    @property
    def trace_count(self) -> int:
        """Number of engine XLA programs traced so far (compile guard:
        must stay <= len(prefill_buckets) + 1 under any workload)."""
        return self._jit_tick.traces + self._jit_insert.traces

    def stats(self) -> Dict[str, Any]:
        out = {
            "num_slots": self.config.num_slots,
            "active_slots": int(self._active.sum()),
            "queued": len(self._queue),
            "completed": self._completed,
            "slot_reuses": self._slot_reuses,
            "kv_layout": self.config.kv_layout,
            "traces": {"tick": self._jit_tick.traces,
                       "insert": self._jit_insert.traces},
            "trace_count": self.trace_count,
        }
        if self._paged:
            out["kv"] = {
                "num_blocks": self.config.pool_blocks,
                "block_size": self.config.kv_block_size,
                "used_blocks": self._allocator.used_blocks,
                "free_blocks": self._allocator.free_blocks,
            }
            if self._prefix is not None:
                out["prefix_cache"] = self._prefix.stats()
        return out


def _sample(logits, temp, key):
    """Per-row sampling: greedy where temp == 0, else temperature
    categorical. Both branches are computed (fixed shape); `where`
    selects."""
    import jax
    import jax.numpy as jnp

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temp, 1e-6)[:, None]
    sampled = jax.random.categorical(key, scaled).astype(jnp.int32)
    return jnp.where(temp > 0, sampled, greedy)


def static_batch_generate(params, model_config, requests: List[Request],
                          batch_size: int, pad_to: int,
                          steps: Optional[int] = None,
                          warmup: bool = True):
    """The lockstep baseline the engine replaces: group requests in
    arrival order, pad prompts to `pad_to`, decode `steps` (default:
    max(max_tokens)) per group via models.llama.generate, truncate per
    request. Used by bench.py for the continuous-vs-static comparison
    on identical geometry (one compiled program: fixed B/P/N). Returns
    (outputs, per-batch seconds) — the timings let the bench couple
    batches to an arrival trace.

    Throughput baseline ONLY: `generate` has no padding mask, so a
    prompt shorter than `pad_to` sees trailing pad tokens in its context
    and its output tokens differ from the unpadded result — which is one
    of the deficiencies of the static path (the other, measured by the
    bench, is that every request decodes for the group max). Compute
    cost is identical to real content at the same shapes, so the timing
    stands."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models.llama import generate

    steps = steps or max(r.max_tokens for r in requests)
    gen = jax.jit(lambda p, t: generate(p, t, model_config,
                                        max_new_tokens=steps))
    if warmup:                              # compile outside the timings
        np.asarray(gen(params, jnp.zeros((batch_size, pad_to),
                                         jnp.int32)))
    outs: List[List[int]] = []
    batch_seconds: List[float] = []
    for i in range(0, len(requests), batch_size):
        group = requests[i:i + batch_size]
        toks = np.zeros((batch_size, pad_to), np.int32)
        for j, r in enumerate(group):
            toks[j, :len(r.prompt)] = np.asarray(r.prompt, np.int32)
        t0 = time.monotonic()
        out = np.asarray(gen(params, jnp.asarray(toks)))
        batch_seconds.append(time.monotonic() - t0)
        for j, r in enumerate(group):
            outs.append(out[j, :r.max_tokens].tolist())
    return outs, batch_seconds
