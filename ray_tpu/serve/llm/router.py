"""LLM request router — queue-depth-aware spreading across replicas.

The generic handle router (serve/_private/router.py) balances on its own
*local* in-flight counts: enough when every caller owns a private view
of load, blind when load hides inside the replicas — an LLM replica
admits requests into its engine queue, so two replicas can report equal
in-flight counts while one sits on a deep prefill backlog. This router
is a *deployment* in front of N ``LLMServer`` replicas that closes that
gap:

- a probe thread samples every replica's engine (`LLMServer.load`) on a
  short period, capturing queued + active work the handle layer cannot
  see (exported as ``rtpu_serve_router_queue_depth{replica=...}``);
- request assignment is power-of-two-choices over (local in-flight +
  probed engine depth), so a stalled or backlogged replica sheds
  traffic within one probe period instead of one long-poll;
- the router pushes its total in-flight to the controller
  (`record_handle_metrics`) exactly like a handle does, so the PR-7
  ``AutoscalePolicy`` inflight law — and its queue-wait/utilization
  signals from the replicas' own gauges — keep steering replica count
  with no new plumbing.

Cache-aware routing (cluster-wide KV memory hierarchy): replicas
publish their prefix hash-chain heads to a GCS index
(``report_prefix_index``); an index thread here polls
``lookup_prefix_index`` on the same period. A decode pick then scores
``load - serve_router_cache_weight * expected_hit_blocks``, where the
expected hit is the longest run of the prompt's block-boundary
``stable_hash_prefix`` values present in a replica's published heads —
p2c with a thumb on the scale for KV the replica already holds. The
index is a hint with PR-7 staleness discipline: if the router's view is
older than ``serve_prefix_index_ttl_s`` it HOLDs to plain p2c rather
than chase dead cache state. When the loser of the pick holds
``serve_peer_pull_min_blocks`` more cached blocks than the winner, the
router pulls those blocks winner-ward first (donor ``export_prefix`` ->
chosen ``import_prefix``, payload by ObjectRef, store-to-store) so the
pick's admission promotes them instead of re-prefilling.

``build_routed_llm_app`` composes Router(LLM): the inner LLM deployment
scales (fixed N or ``num_replicas="auto"`` via autoscaling_config), the
router stays a single cheap replica.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["LLMRouter", "build_routed_llm_app", "p2c_pick"]


def p2c_pick(replicas: Sequence[Any], load: Dict[Any, float],
             rng: Optional[random.Random] = None) -> Any:
    """Power-of-two-choices over an explicit load view: sample two
    distinct replicas, keep the lighter one. Pure — the routing policy
    under test, separated from the actor plumbing."""
    if not replicas:
        raise RuntimeError("no replicas to pick from")
    if len(replicas) == 1:
        return replicas[0]
    rng = rng or random
    a, b = rng.sample(list(replicas), 2)
    return a if load.get(a, 0.0) <= load.get(b, 0.0) else b


class LLMRouter:
    """Deployment callable fronting the ``LLMServer`` deployment.

    Constructed via composition — ``build_routed_llm_app`` binds the
    inner LLM app as an init argument, which Serve rehydrates into a
    :class:`~ray_tpu.serve.handle.DeploymentHandle` inside the router
    replica. The router reads the handle's target coordinates and talks
    to the replica set directly (same controller surface the generic
    router uses), because per-replica probing needs replica identity,
    which the handle layer abstracts away.
    """

    def __init__(self, llm_handle: Any,
                 probe_interval_s: Optional[float] = None,
                 prefill_handle: Any = None,
                 prefill_threshold: int = 256):
        from ray_tpu._private.config import GlobalConfig
        from ray_tpu.observability import serve_metrics

        self._app = llm_handle._app
        self._deployment = llm_handle._deployment
        self._probe_interval = (
            GlobalConfig.serve_router_probe_interval_s
            if probe_interval_s is None else probe_interval_s)
        self._replicas: List[Any] = []
        self._version = -1
        self._inflight: Dict[Any, int] = {}
        self._depth: Dict[Any, float] = {}     # probed engine depth
        self._routed: Dict[str, int] = {}      # per-replica forward count
        self._lane_routed: Dict[Tuple[str, str], int] = {}
        # Optional prefill pool (serve/llm/disagg): prompts at or past
        # `prefill_threshold` tokens take the two-hop path — prefill
        # replica exports KV, decode replica adopts it; the prefill
        # result moves between them by ObjectRef (store-to-store).
        self._pre_app = self._pre_deployment = None
        self._pre_threshold = int(prefill_threshold)
        self._pre_replicas: List[Any] = []
        self._pre_version = -1
        self._pre_inflight: Dict[Any, int] = {}
        self._pre_depth: Dict[Any, float] = {}
        if prefill_handle is not None:
            self._pre_app = prefill_handle._app
            self._pre_deployment = prefill_handle._deployment
        # Cluster prefix index view: replica index_id (from load()) ->
        # {"heads": [(stable_hash, depth)...], "tiers": {...},
        #  "age_s": float}, plus when WE last fetched it (HOLD clock).
        self._index: Dict[str, Dict[str, Any]] = {}
        self._index_at: float = 0.0            # monotonic, 0 = never
        self._index_id: Dict[Any, str] = {}    # handle -> index_id
        self._cache_weight = float(GlobalConfig.serve_router_cache_weight)
        self._index_ttl = float(GlobalConfig.serve_prefix_index_ttl_s)
        self._pull_min = int(GlobalConfig.serve_peer_pull_min_blocks)
        self._cache_outcomes: Dict[str, int] = {
            "scored": 0, "held": 0, "pulled": 0}
        self._last_expected: Dict[str, int] = {}
        self._lock = threading.Lock()
        self._closed = False
        self._metrics = serve_metrics()
        import uuid

        self._router_id = uuid.uuid4().hex[:12]

        from ray_tpu.serve._private.controller import (
            get_or_create_controller,
        )
        import ray_tpu

        self._controller = get_or_create_controller()
        version, replicas = ray_tpu.get(
            self._controller.get_replicas.remote(self._app,
                                                 self._deployment),
            timeout=60)
        self._apply(version, replicas)
        if self._pre_app is not None:
            version, replicas = ray_tpu.get(
                self._controller.get_replicas.remote(
                    self._pre_app, self._pre_deployment),
                timeout=60)
            self._apply_prefill(version, replicas)
        for target, name in ((self._poll_loop, "llm-router-poll"),
                             (self._probe_loop, "llm-router-probe"),
                             (self._push_loop, "llm-router-push"),
                             (self._index_loop, "llm-router-index")):
            threading.Thread(target=target, daemon=True,
                             name=name).start()

    # ------------------------------------------------------------- replica set
    def _apply(self, version: int, replicas: List[Any]) -> None:
        with self._lock:
            if version != self._version:
                self._version = version
                self._replicas = replicas
                self._inflight = {r: self._inflight.get(r, 0)
                                  for r in replicas}
                self._depth = {r: self._depth.get(r, 0.0)
                               for r in replicas}

    def _apply_prefill(self, version: int, replicas: List[Any]) -> None:
        with self._lock:
            if version != self._pre_version:
                self._pre_version = version
                self._pre_replicas = replicas
                self._pre_inflight = {r: self._pre_inflight.get(r, 0)
                                      for r in replicas}
                self._pre_depth = {r: self._pre_depth.get(r, 0.0)
                                   for r in replicas}

    def _poll_loop(self) -> None:
        import ray_tpu

        while not self._closed:
            try:
                version, replicas = ray_tpu.get(
                    self._controller.poll_replicas.remote(
                        self._app, self._deployment, self._version, 25.0),
                    timeout=60)
                self._apply(version, replicas)
                if self._pre_app is not None:
                    version, replicas = ray_tpu.get(
                        self._controller.poll_replicas.remote(
                            self._pre_app, self._pre_deployment,
                            self._pre_version, 0.5),
                        timeout=60)
                    self._apply_prefill(version, replicas)
            except Exception:
                if self._closed:
                    return
                time.sleep(1.0)

    # ------------------------------------------------------------- probing
    def _probe_one(self, r: Any) -> Tuple[float, Optional[str]]:
        import ray_tpu

        try:
            load = ray_tpu.get(
                r.handle_request.remote("load", (), {}),
                timeout=min(5.0, self._probe_interval * 5))
            return (float(load.get("queued", 0)
                          + load.get("active_slots", 0)),
                    load.get("index_id"))
        except Exception:
            # Unreachable/stalled replica: poison its score so
            # traffic shifts away until it answers again.
            return float("inf"), None

    def _probe_loop(self) -> None:
        while not self._closed:
            with self._lock:
                replicas = list(self._replicas)
                pre = list(self._pre_replicas)
            for r in replicas:
                depth, index_id = self._probe_one(r)
                with self._lock:
                    if r in self._depth:
                        self._depth[r] = depth
                    if index_id:
                        self._index_id[r] = str(index_id)
                rid = getattr(r, "_actor_id", id(r))
                if depth != float("inf"):
                    self._metrics.router_queue_depth.set(
                        depth, tags={"replica": str(rid)})
            for r in pre:
                depth, _ = self._probe_one(r)
                with self._lock:
                    if r in self._pre_depth:
                        self._pre_depth[r] = depth
            time.sleep(self._probe_interval)

    def _index_loop(self) -> None:
        """Poll the GCS cluster prefix index on the publish period; a
        fetch failure just ages the view until the TTL HOLD trips."""
        from ray_tpu._private.config import GlobalConfig
        from ray_tpu._private.worker import global_worker_or_none

        interval = float(
            GlobalConfig.serve_prefix_index_publish_interval_s)
        while not self._closed:
            w = global_worker_or_none()
            if w is not None:
                try:
                    idx = w.gcs.call("lookup_prefix_index", timeout=5)
                    with self._lock:
                        self._index = dict(idx or {})
                        self._index_at = time.monotonic()
                except Exception:
                    pass
            with self._lock:
                at = self._index_at
            if at:
                self._metrics.router_index_age.set(
                    time.monotonic() - at)
            time.sleep(interval)

    def _index_age_s(self) -> float:
        with self._lock:
            at = self._index_at
        return (time.monotonic() - at) if at else float("inf")

    def _push_loop(self) -> None:
        """Handle-metrics push: the autoscaler's inflight law sees the
        router's total exactly as it would a plain handle's."""
        while not self._closed:
            time.sleep(2.0)
            with self._lock:
                total = sum(self._inflight.values())
            try:
                self._controller.record_handle_metrics.remote(
                    self._app, self._deployment, self._router_id, total)
            except Exception:
                return

    # ------------------------------------------------------------- routing
    def _score(self, pool: str = "decode") \
            -> Tuple[List[Any], Dict[Any, float]]:
        with self._lock:
            if pool == "prefill":
                replicas = list(self._pre_replicas)
                load = {r: self._pre_inflight.get(r, 0)
                        + self._pre_depth.get(r, 0.0) for r in replicas}
            else:
                replicas = list(self._replicas)
                load = {r: self._inflight.get(r, 0)
                        + self._depth.get(r, 0.0) for r in replicas}
        return replicas, load

    def _expected_hits(self, prompt: Sequence[int]) -> Dict[str, int]:
        """Per-replica expected prefix hit, in blocks: the longest run
        of this prompt's block-boundary stable hashes present in the
        replica's published heads. Pure function of the index snapshot —
        consumers on the replica re-verify against real tokens, so a
        stable-hash collision here only mis-scores, never corrupts."""
        from ray_tpu.serve.llm.kv_cache import stable_hash_prefix

        with self._lock:
            index = dict(self._index)
        out: Dict[str, int] = {}
        bound_cache: Dict[int, List[int]] = {}  # block_size -> hashes
        for iid, rec in index.items():
            try:
                bs = int(rec.get("tiers", {}).get("block_size", 0))
            except Exception:
                bs = 0
            if bs <= 0:
                continue
            if bs not in bound_cache:
                # Last token never lands in a cached block (it must be
                # prefilled to produce logits) — same cap as admission.
                n_bound = max(0, (len(prompt) - 1) // bs)
                bound_cache[bs] = [
                    stable_hash_prefix(prompt[:j * bs])
                    for j in range(1, n_bound + 1)]
            heads = {int(h) for h, _d in rec.get("heads", ())}
            n = 0
            for h in bound_cache[bs]:
                if h not in heads:
                    break
                n += 1
            out[iid] = n
        return out

    def _pick(self, pool: str) -> Any:
        deadline = time.monotonic() + 30.0
        replicas, load = self._score(pool)
        while not replicas:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no live {pool} replicas for "
                    f"{self._app}/{self._deployment}")
            time.sleep(0.05)
            replicas, load = self._score(pool)
        return p2c_pick(replicas, load)

    def _pick_cached(self, prompt: Sequence[int]) \
            -> Tuple[Any, Dict[str, int], str]:
        """Decode pick with the cluster prefix index applied. Returns
        (chosen, expected_hits_by_index_id, outcome) where outcome is
        "scored" (index applied) or "held" (stale/absent index -> plain
        p2c, PR-7 staleness discipline)."""
        deadline = time.monotonic() + 30.0
        while True:
            replicas, load = self._score("decode")
            if replicas:
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no live decode replicas for "
                    f"{self._app}/{self._deployment}")
            time.sleep(0.05)
        stale = self._index_age_s() > self._index_ttl
        if stale or self._cache_weight <= 0.0 or not prompt:
            return p2c_pick(replicas, load), {}, "held"
        expected = self._expected_hits(prompt)
        if not expected:
            return p2c_pick(replicas, load), {}, "held"
        with self._lock:
            ids = dict(self._index_id)
        adj = {r: load.get(r, 0.0)
               - self._cache_weight * expected.get(ids.get(r), 0)
               for r in replicas}
        return p2c_pick(replicas, adj), expected, "scored"

    def _maybe_peer_pull(self, chosen: Any, prompt: Sequence[int],
                         expected: Dict[str, int],
                         timeout: float) -> bool:
        """If some OTHER replica holds serve_peer_pull_min_blocks more
        of this prompt's prefix than the chosen one, pull its chain into
        the chosen replica's host tier before forwarding, so admission
        promotes instead of re-prefilling. Synchronous on purpose — the
        import must land before the request does. Best-effort: any
        failure falls back to plain recompute on the chosen replica."""
        import ray_tpu

        with self._lock:
            ids = dict(self._index_id)
            replicas = list(self._replicas)
        mine = expected.get(ids.get(chosen), 0)
        donor, donor_hits = None, mine
        for r in replicas:
            if r is chosen:
                continue
            hits = expected.get(ids.get(r), 0)
            if hits > donor_hits:
                donor, donor_hits = r, hits
        if donor is None or donor_hits - mine < self._pull_min:
            return False
        try:
            from ray_tpu.util.tracing import record_span

            # export ref flows donor -> store -> chosen; the Replica
            # layer materializes ObjectRef args in the chosen process.
            t0 = time.time()
            ref = donor.handle_request.remote(
                "export_prefix", (list(prompt),), {})
            n = ray_tpu.get(
                chosen.handle_request.remote(
                    "import_prefix", (ref,), {}),
                timeout=min(30.0, timeout))
            if n:
                # Ambient context: the serve.request root is active on
                # this thread, so the span parents there.
                record_span("kv.peer_pull", t0, time.time() - t0,
                            attrs={"blocks": int(n)})
            return bool(n)
        except Exception:
            return False

    def __call__(self, request: Dict[str, Any]) -> Dict[str, Any]:
        """Route one request under a fresh trace: ``serve.request`` is
        the trace root (the tail-sampling trigger), every hop below —
        ``serve.replica_call`` / ``serve.prefill_call`` / ``kv.*`` and
        the replica's spans across the process boundary — parents into
        one causal tree, and the response carries ``x-trace-id`` so the
        caller can fetch it back via ``util.state.get_trace``."""
        from ray_tpu.util.tracing import trace_root

        lane = str(request.get("slo", "interactive"))
        tenant = str(request.get("tenant", "default"))
        with trace_root("serve.request",
                        attrs={"lane": lane,
                               "tenant": tenant,
                               "prompt_len": len(request.get(
                                   "prompt", ()))},
                        baggage={"slo": lane}) as tc:
            out = self._route(request)
        return dict(out) | {"x-trace-id": tc.trace_id}

    def _route(self, request: Dict[str, Any]) -> Dict[str, Any]:
        import ray_tpu
        from ray_tpu.util.tracing import span

        lane = str(request.get("slo", "interactive"))
        prompt = request.get("prompt", ())
        two_hop = (self._pre_app is not None
                   and len(prompt) >= self._pre_threshold)
        chosen, expected, outcome = self._pick_cached(prompt)
        rid = str(getattr(chosen, "_actor_id", id(chosen)))
        pre = self._pick("prefill") if two_hop else None
        with self._lock:
            self._inflight[chosen] = self._inflight.get(chosen, 0) + 1
            self._routed[rid] = self._routed.get(rid, 0) + 1
            key = (lane, "prefill" if two_hop else
                   ("decode" if self._pre_app is not None
                    else "monolithic"))
            self._lane_routed[key] = self._lane_routed.get(key, 0) + 1
            if pre is not None:
                self._pre_inflight[pre] = \
                    self._pre_inflight.get(pre, 0) + 1
        self._metrics.router_requests.inc(tags={"replica": rid})
        self._metrics.router_lane_requests.inc(
            tags={"lane": key[0], "pool": key[1]})
        self._metrics.router_cache_hops.inc(tags={"outcome": outcome})
        with self._lock:
            self._cache_outcomes[outcome] = \
                self._cache_outcomes.get(outcome, 0) + 1
            if outcome == "scored":
                self._last_expected = dict(expected)
        try:
            timeout = float(request.get("timeout_s", 300.0))
            # Peer pull: only on the single-hop path (two-hop already
            # moves KV prefill->decode) and only off a scored pick.
            if (outcome == "scored" and not two_hop
                    and self._maybe_peer_pull(chosen, prompt, expected,
                                              timeout)):
                self._metrics.router_cache_hops.inc(
                    tags={"outcome": "pulled"})
                with self._lock:
                    self._cache_outcomes["pulled"] = \
                        self._cache_outcomes.get("pulled", 0) + 1
            if two_hop:
                # Two-hop disaggregated path. The prefill result — KV
                # blocks included — is forwarded as an ObjectRef: the
                # decode replica materializes it from the object store
                # (Replica.handle_request's ObjectRef-arg resolution),
                # so the payload never enters the router process.
                with span("serve.prefill_call",
                          attrs={"replica": str(getattr(
                              pre, "_actor_id", id(pre)))}):
                    prefill_ref = pre.handle_request.remote(
                        "prefill", (request,), {})
                with span("serve.replica_call",
                          attrs={"replica": rid, "hop": "adopt"}):
                    return ray_tpu.get(
                        chosen.handle_request.remote(
                            "adopt", (prefill_ref, request), {}),
                        timeout=timeout)
            with span("serve.replica_call", attrs={"replica": rid}):
                return ray_tpu.get(
                    chosen.handle_request.remote(
                        "__call__", (request,), {}),
                    timeout=timeout)
        finally:
            with self._lock:
                if chosen in self._inflight:
                    self._inflight[chosen] -= 1
                if pre is not None and pre in self._pre_inflight:
                    self._pre_inflight[pre] -= 1

    # ------------------------------------------------------------- inspection
    def stats(self) -> Dict[str, Any]:
        age = self._index_age_s()
        with self._lock:
            out = {
                "cache_index": {
                    # inf -> None so the dict stays JSON-serializable
                    # for the dashboard rollup.
                    "age_s": (None if age == float("inf")
                              else round(age, 3)),
                    "fresh": age <= self._index_ttl,
                    "ttl_s": self._index_ttl,
                    "weight": self._cache_weight,
                    "replicas_indexed": len(self._index),
                    "outcomes": dict(self._cache_outcomes),
                    "expected_hit_blocks": dict(self._last_expected),
                },
                "replicas": len(self._replicas),
                "inflight": sum(self._inflight.values()),
                "routed": dict(self._routed),
                "lanes": {f"{lane}/{pool}": n for (lane, pool), n
                          in self._lane_routed.items()},
                "depth": {str(getattr(r, "_actor_id", id(r))): d
                          for r, d in self._depth.items()},
            }
            if self._pre_app is not None:
                out["prefill_pool"] = {
                    "replicas": len(self._pre_replicas),
                    "inflight": sum(self._pre_inflight.values()),
                    "threshold": self._pre_threshold,
                    "depth": {str(getattr(r, "_actor_id", id(r))): d
                              for r, d in self._pre_depth.items()},
                }
            return out

    def check_health(self) -> None:
        if self._closed:
            raise RuntimeError("router closed")

    def __del__(self):
        try:
            self._closed = True
        except Exception:
            pass


def build_routed_llm_app(model_config: Any = None,
                         engine_config: Any = None, *,
                         name: str = "llm",
                         num_replicas: Any = 2,
                         autoscaling_config: Optional[Dict[str, Any]] = None,
                         num_tpus: float = 0,
                         max_ongoing_requests: int = 32,
                         init_seed: int = 0,
                         quantize: Optional[str] = None,
                         params_loader: Optional[Any] = None,
                         probe_interval_s: Optional[float] = None):
    """Router(LLM) composition: N engine replicas behind one
    queue-depth-aware router. ``num_replicas`` may be an int or
    ``"auto"`` (with ``autoscaling_config``) — the PR-7 autoscaler then
    drives the inner deployment while the router re-discovers the
    replica set through its controller poll."""
    from ray_tpu import serve
    from ray_tpu.serve.llm.deployment import LLMServer, _plain

    llm_kwargs: Dict[str, Any] = dict(
        name=name, num_tpus=num_tpus,
        max_ongoing_requests=max_ongoing_requests)
    if num_replicas == "auto" or autoscaling_config is not None:
        llm_kwargs["num_replicas"] = num_replicas
        if autoscaling_config is not None:
            llm_kwargs["autoscaling_config"] = autoscaling_config
    else:
        llm_kwargs["num_replicas"] = int(num_replicas)
    llm_dep = serve.deployment(LLMServer, **llm_kwargs)
    llm_app = llm_dep.bind(model_config=_plain(model_config),
                           engine_config=_plain(engine_config),
                           init_seed=init_seed, quantize=quantize,
                           params_loader=params_loader)
    router_dep = serve.deployment(
        LLMRouter, name=f"{name}-router", num_replicas=1,
        max_ongoing_requests=max(64, max_ongoing_requests * 4))
    return router_dep.bind(llm_app, probe_interval_s=probe_interval_s)
