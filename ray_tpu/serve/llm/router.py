"""LLM request router — queue-depth-aware spreading across replicas.

The generic handle router (serve/_private/router.py) balances on its own
*local* in-flight counts: enough when every caller owns a private view
of load, blind when load hides inside the replicas — an LLM replica
admits requests into its engine queue, so two replicas can report equal
in-flight counts while one sits on a deep prefill backlog. This router
is a *deployment* in front of N ``LLMServer`` replicas that closes that
gap:

- a probe thread samples every replica's engine (`LLMServer.load`) on a
  short period, capturing queued + active work the handle layer cannot
  see (exported as ``rtpu_serve_router_queue_depth{replica=...}``);
- request assignment is power-of-two-choices over (local in-flight +
  probed engine depth), so a stalled or backlogged replica sheds
  traffic within one probe period instead of one long-poll;
- the router pushes its total in-flight to the controller
  (`record_handle_metrics`) exactly like a handle does, so the PR-7
  ``AutoscalePolicy`` inflight law — and its queue-wait/utilization
  signals from the replicas' own gauges — keep steering replica count
  with no new plumbing.

``build_routed_llm_app`` composes Router(LLM): the inner LLM deployment
scales (fixed N or ``num_replicas="auto"`` via autoscaling_config), the
router stays a single cheap replica.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["LLMRouter", "build_routed_llm_app", "p2c_pick"]


def p2c_pick(replicas: Sequence[Any], load: Dict[Any, float],
             rng: Optional[random.Random] = None) -> Any:
    """Power-of-two-choices over an explicit load view: sample two
    distinct replicas, keep the lighter one. Pure — the routing policy
    under test, separated from the actor plumbing."""
    if not replicas:
        raise RuntimeError("no replicas to pick from")
    if len(replicas) == 1:
        return replicas[0]
    rng = rng or random
    a, b = rng.sample(list(replicas), 2)
    return a if load.get(a, 0.0) <= load.get(b, 0.0) else b


class LLMRouter:
    """Deployment callable fronting the ``LLMServer`` deployment.

    Constructed via composition — ``build_routed_llm_app`` binds the
    inner LLM app as an init argument, which Serve rehydrates into a
    :class:`~ray_tpu.serve.handle.DeploymentHandle` inside the router
    replica. The router reads the handle's target coordinates and talks
    to the replica set directly (same controller surface the generic
    router uses), because per-replica probing needs replica identity,
    which the handle layer abstracts away.
    """

    def __init__(self, llm_handle: Any,
                 probe_interval_s: Optional[float] = None):
        from ray_tpu._private.config import GlobalConfig
        from ray_tpu.observability import serve_metrics

        self._app = llm_handle._app
        self._deployment = llm_handle._deployment
        self._probe_interval = (
            GlobalConfig.serve_router_probe_interval_s
            if probe_interval_s is None else probe_interval_s)
        self._replicas: List[Any] = []
        self._version = -1
        self._inflight: Dict[Any, int] = {}
        self._depth: Dict[Any, float] = {}     # probed engine depth
        self._routed: Dict[str, int] = {}      # per-replica forward count
        self._lock = threading.Lock()
        self._closed = False
        self._metrics = serve_metrics()
        import uuid

        self._router_id = uuid.uuid4().hex[:12]

        from ray_tpu.serve._private.controller import (
            get_or_create_controller,
        )
        import ray_tpu

        self._controller = get_or_create_controller()
        version, replicas = ray_tpu.get(
            self._controller.get_replicas.remote(self._app,
                                                 self._deployment),
            timeout=60)
        self._apply(version, replicas)
        for target, name in ((self._poll_loop, "llm-router-poll"),
                             (self._probe_loop, "llm-router-probe"),
                             (self._push_loop, "llm-router-push")):
            threading.Thread(target=target, daemon=True,
                             name=name).start()

    # ------------------------------------------------------------- replica set
    def _apply(self, version: int, replicas: List[Any]) -> None:
        with self._lock:
            if version != self._version:
                self._version = version
                self._replicas = replicas
                self._inflight = {r: self._inflight.get(r, 0)
                                  for r in replicas}
                self._depth = {r: self._depth.get(r, 0.0)
                               for r in replicas}

    def _poll_loop(self) -> None:
        import ray_tpu

        while not self._closed:
            try:
                version, replicas = ray_tpu.get(
                    self._controller.poll_replicas.remote(
                        self._app, self._deployment, self._version, 25.0),
                    timeout=60)
                self._apply(version, replicas)
            except Exception:
                if self._closed:
                    return
                time.sleep(1.0)

    # ------------------------------------------------------------- probing
    def _probe_loop(self) -> None:
        import ray_tpu

        while not self._closed:
            with self._lock:
                replicas = list(self._replicas)
            for r in replicas:
                try:
                    load = ray_tpu.get(
                        r.handle_request.remote("load", (), {}),
                        timeout=min(5.0, self._probe_interval * 5))
                    depth = float(load.get("queued", 0)
                                  + load.get("active_slots", 0))
                except Exception:
                    # Unreachable/stalled replica: poison its score so
                    # traffic shifts away until it answers again.
                    depth = float("inf")
                with self._lock:
                    if r in self._depth:
                        self._depth[r] = depth
                rid = getattr(r, "_actor_id", id(r))
                if depth != float("inf"):
                    self._metrics.router_queue_depth.set(
                        depth, tags={"replica": str(rid)})
            time.sleep(self._probe_interval)

    def _push_loop(self) -> None:
        """Handle-metrics push: the autoscaler's inflight law sees the
        router's total exactly as it would a plain handle's."""
        while not self._closed:
            time.sleep(2.0)
            with self._lock:
                total = sum(self._inflight.values())
            try:
                self._controller.record_handle_metrics.remote(
                    self._app, self._deployment, self._router_id, total)
            except Exception:
                return

    # ------------------------------------------------------------- routing
    def _score(self) -> Tuple[List[Any], Dict[Any, float]]:
        with self._lock:
            replicas = list(self._replicas)
            load = {r: self._inflight.get(r, 0) + self._depth.get(r, 0.0)
                    for r in replicas}
        return replicas, load

    def __call__(self, request: Dict[str, Any]) -> Dict[str, Any]:
        import ray_tpu

        deadline = time.monotonic() + 30.0
        replicas, load = self._score()
        while not replicas:
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no live replicas for {self._app}/{self._deployment}")
            time.sleep(0.05)
            replicas, load = self._score()
        chosen = p2c_pick(replicas, load)
        rid = str(getattr(chosen, "_actor_id", id(chosen)))
        with self._lock:
            self._inflight[chosen] = self._inflight.get(chosen, 0) + 1
            self._routed[rid] = self._routed.get(rid, 0) + 1
        self._metrics.router_requests.inc(tags={"replica": rid})
        try:
            return ray_tpu.get(
                chosen.handle_request.remote("__call__", (request,), {}),
                timeout=float(request.get("timeout_s", 300.0)))
        finally:
            with self._lock:
                if chosen in self._inflight:
                    self._inflight[chosen] -= 1

    # ------------------------------------------------------------- inspection
    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "replicas": len(self._replicas),
                "inflight": sum(self._inflight.values()),
                "routed": dict(self._routed),
                "depth": {str(getattr(r, "_actor_id", id(r))): d
                          for r, d in self._depth.items()},
            }

    def check_health(self) -> None:
        if self._closed:
            raise RuntimeError("router closed")

    def __del__(self):
        try:
            self._closed = True
        except Exception:
            pass


def build_routed_llm_app(model_config: Any = None,
                         engine_config: Any = None, *,
                         name: str = "llm",
                         num_replicas: Any = 2,
                         autoscaling_config: Optional[Dict[str, Any]] = None,
                         num_tpus: float = 0,
                         max_ongoing_requests: int = 32,
                         init_seed: int = 0,
                         quantize: Optional[str] = None,
                         params_loader: Optional[Any] = None,
                         probe_interval_s: Optional[float] = None):
    """Router(LLM) composition: N engine replicas behind one
    queue-depth-aware router. ``num_replicas`` may be an int or
    ``"auto"`` (with ``autoscaling_config``) — the PR-7 autoscaler then
    drives the inner deployment while the router re-discovers the
    replica set through its controller poll."""
    from ray_tpu import serve
    from ray_tpu.serve.llm.deployment import LLMServer, _plain

    llm_kwargs: Dict[str, Any] = dict(
        name=name, num_tpus=num_tpus,
        max_ongoing_requests=max_ongoing_requests)
    if num_replicas == "auto" or autoscaling_config is not None:
        llm_kwargs["num_replicas"] = num_replicas
        if autoscaling_config is not None:
            llm_kwargs["autoscaling_config"] = autoscaling_config
    else:
        llm_kwargs["num_replicas"] = int(num_replicas)
    llm_dep = serve.deployment(LLMServer, **llm_kwargs)
    llm_app = llm_dep.bind(model_config=_plain(model_config),
                           engine_config=_plain(engine_config),
                           init_seed=init_seed, quantize=quantize,
                           params_loader=params_loader)
    router_dep = serve.deployment(
        LLMRouter, name=f"{name}-router", num_replicas=1,
        max_ongoing_requests=max(64, max_ongoing_requests * 4))
    return router_dep.bind(llm_app, probe_interval_s=probe_interval_s)
