"""Paged KV-cache bookkeeping: block allocator + token-prefix cache.

The HBM side lives in :mod:`ray_tpu.models.llama` (`init_paged_kv_cache`
allocates one fixed pool of ``[block_size]``-row KV blocks per layer;
`decode_step_paged` / `prefill_kv_paged` read and write it through
per-sequence *block tables*). This module is the host side: which
physical block belongs to whom, and which prompt prefixes are already
resident so admission can skip their prefill entirely.

Two pieces, both pure host-Python (no jax imports — unit-testable
without a device):

- :class:`BlockAllocator` — a fixed pool of ``num_blocks`` block ids
  with per-block reference counts. ``alloc`` hands out free ids or
  reports exhaustion (the engine *queues* the request — never crashes);
  ``incref``/``free`` implement copy-on-write sharing: a block reaching
  refcount 0 returns to the free list, a shared block stays resident
  until its last reader releases it. ``copy_on_write`` gives a private
  copy id for a shared block about to be mutated (the engine's sharing
  is block-aligned — only *full* prompt blocks are ever shared, and
  sequences write strictly past them — so the engine never triggers the
  copy; the primitive is here, and tested, for sub-block sharing).

- :class:`PrefixCache` — RadixAttention-style reuse keyed on the hash
  of the token prefix at every block boundary (a hash chain rather than
  a radix tree: block-granular lookups need only exact block-boundary
  matches). ``match`` walks the chain and increfs every hit block for
  the caller; ``insert`` registers a finished prompt's full blocks,
  taking cache-owned refs so blocks outlive the sequence that produced
  them; LRU eviction frees the coldest tails when the allocator runs
  dry (vLLM: "Efficient Memory Management for LLM Serving with
  PagedAttention"; SGLang: RadixAttention).

- :class:`KVTierManager` — the memory hierarchy below HBM. An evicted
  prefix block no longer vanishes: the engine's spill hook gathers its
  rows off the pool (one `_export_fn` dispatch per eviction batch) and
  parks them here, first in host RAM (bounded by
  ``serve_kv_host_tier_bytes``), demoting LRU entries to the object
  store when the host tier overflows (``put_fn``/``get_fn`` — wired to
  ``ray_tpu.put``/``get`` by the deployment; absent a cluster, cold
  overflow is dropped and counted). A re-admitted prompt that misses
  HBM but hits a tier re-adopts the blocks through the engine's
  `_adopt_fn` scatter instead of re-prefilling — when the
  :class:`PromoteCostModel` says the scatter beats recompute.
"""

from __future__ import annotations

import threading
import zlib
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import (Any, Callable, Dict, List, Optional, Sequence, Tuple)

__all__ = [
    "BlockAllocator", "KVPrefix", "KVState", "KVTierManager",
    "PrefixCache", "PromoteCostModel", "TierHit", "hash_prefix",
    "stable_hash_prefix",
]


@dataclass
class KVState:
    """A sequence's paged KV checkpoint, detached from any engine.

    The unit of KV migration: `LLMEngine._export_state` densifies the
    slot's live blocks into plain ndarrays ([L, n_valid, bs, n_kv, hd],
    zero-copy through the object store), and `LLMEngine.submit_adopted`
    scatters them into another engine's pool. Produced by the
    disaggregated prefill tier (serve/llm/disagg) and by batch-lane
    preemption (the checkpoint that lets a preempted decode resume).

    ``pos`` is the number of CONSUMED tokens — rows [0, pos) of the
    dense view are valid; ``next_tok`` is the last sampled token, not
    yet consumed (the engine's device ``tok`` at export time).
    ``tokens`` are the tokens already emitted to the caller (the first
    sampled token onward), so an adopting engine resumes max_tokens /
    stop accounting exactly where the exporter left off.
    """

    prompt: List[int]
    tokens: List[int]
    next_tok: int
    pos: int
    temperature: float
    block_size: int
    k_blocks: object        # np [L, n_valid, bs, n_kv, head_dim]
    v_blocks: object

    @property
    def n_blocks(self) -> int:
        return int(self.k_blocks.shape[1])

    @property
    def payload_bytes(self) -> int:
        return int(self.k_blocks.nbytes + self.v_blocks.nbytes)

    def validate(self) -> None:
        bs = self.block_size
        need = -(-self.pos // bs)
        if self.n_blocks != need:
            raise ValueError(
                f"KVState holds {self.n_blocks} blocks but pos="
                f"{self.pos} at block_size={bs} needs {need}")
        if self.k_blocks.shape != self.v_blocks.shape:
            raise ValueError("k/v block shape mismatch")
        if not self.tokens or self.tokens[-1] != self.next_tok:
            raise ValueError(
                "next_tok must be the last emitted token (sampled but "
                "not yet consumed)")
        if self.pos != len(self.prompt) + len(self.tokens) - 1:
            raise ValueError(
                f"pos={self.pos} inconsistent with prompt "
                f"{len(self.prompt)} + emitted {len(self.tokens)} "
                f"(expected prompt + emitted - 1 consumed tokens)")


def hash_prefix(tokens: Sequence[int]) -> int:
    """Fast key for a token prefix. Python's tuple hash is salted per
    process (PYTHONHASHSEED) which is fine *locally* — each replica
    owns its pool, so its prefix cache is process-local. Anything that
    crosses processes (the cluster-wide prefix index, the GCS
    ``report/lookup_prefix_index`` RPCs) must use
    :func:`stable_hash_prefix` instead."""
    return hash(tuple(tokens))


def stable_hash_prefix(tokens: Sequence[int]) -> int:
    """Process-independent key for a token prefix — the hash that may
    cross the wire. crc32 over the little-endian token stream: cheap,
    deterministic everywhere, and collisions only cost a wasted peer
    probe (every consumer re-verifies against real tokens before
    trusting a match)."""
    import numpy as np

    return int(zlib.crc32(
        np.asarray(tokens, np.int64).tobytes()))


class BlockAllocator:
    """Fixed pool of ``num_blocks`` KV blocks with refcounts.

    Thread-safe: the engine's scheduler thread allocates while the
    dashboard thread reads stats. All ops are O(1) amortized.
    """

    def __init__(self, num_blocks: int, block_size: int,
                 block_bytes: int = 0):
        if num_blocks <= 0 or block_size <= 0:
            raise ValueError(
                f"need positive num_blocks/block_size, got "
                f"{num_blocks}/{block_size}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        # HBM bytes per block (k + v rows across all layers); 0 when
        # the caller doesn't care about byte-level accounting.
        self.block_bytes = int(block_bytes)
        self._free: deque = deque(range(num_blocks))
        self._refs: List[int] = [0] * num_blocks
        self._lock = threading.Lock()

    # -- core ------------------------------------------------------------
    def alloc(self, n: int) -> Optional[List[int]]:
        """n fresh blocks at refcount 1, or None if the pool can't cover
        it (caller queues / evicts; nothing is partially allocated)."""
        with self._lock:
            if n > len(self._free):
                return None
            out = [self._free.popleft() for _ in range(n)]
            for b in out:
                self._refs[b] = 1
            return out

    def incref(self, blocks: Sequence[int]) -> None:
        with self._lock:
            for b in blocks:
                if self._refs[b] <= 0:
                    raise ValueError(f"incref on free block {b}")
                self._refs[b] += 1

    def free(self, blocks: Sequence[int]) -> None:
        """Drop one reference per id; blocks hitting 0 rejoin the pool."""
        with self._lock:
            for b in blocks:
                r = self._refs[b] - 1
                if r < 0:
                    raise ValueError(f"double free of block {b}")
                self._refs[b] = r
                if r == 0:
                    self._free.append(b)

    def fork(self, blocks: Sequence[int]) -> List[int]:
        """Share an existing table (copy-on-write fork): the child holds
        the same physical ids, each with one more reference."""
        self.incref(blocks)
        return list(blocks)

    def copy_on_write(self, block: int) -> Tuple[int, bool]:
        """Prepare ``block`` for mutation. Uniquely-owned blocks are
        returned as-is; shared ones release one ref and return a fresh
        private id (returns (id, needs_copy) — the caller must copy the
        HBM rows when needs_copy). None is never returned: raises on
        exhaustion so callers treat COW pressure as a hard signal."""
        with self._lock:
            if self._refs[block] <= 0:
                raise ValueError(f"copy_on_write on free block {block}")
            if self._refs[block] == 1:
                return block, False
            if not self._free:
                raise MemoryError(
                    "copy_on_write: pool exhausted (free a sequence or "
                    "evict prefix-cache entries first)")
            new = self._free.popleft()
            self._refs[new] = 1
            self._refs[block] -= 1
            return new, True

    # -- migration -------------------------------------------------------
    def adopt(self, n: int,
              prefix_cache: Optional["PrefixCache"] = None
              ) -> Optional[List[int]]:
        """All-or-nothing allocation for an imported/resumed sequence:
        like :meth:`alloc`, but under pressure it first evicts cold
        prefix-cache entries to make room (the same fallback admission
        uses). Returns None — nothing allocated, nothing evicted beyond
        the attempt — when the pool still can't cover ``n``; the caller
        requeues the import and retries as running sequences finish."""
        blocks = self.alloc(n)
        if blocks is None and prefix_cache is not None:
            prefix_cache.evict(n - self.free_blocks)
            blocks = self.alloc(n)
        return blocks

    def donate(self, blocks: Sequence[int]) -> None:
        """Release a live sequence's block refs after its KV has been
        exported (the ownership hand-off half of a migration: the rows
        now live in a :class:`KVState` / another engine's pool, so this
        engine's copies may be recycled). Identical accounting to
        :meth:`free` — the name records intent at export sites, and the
        liveness check catches exporting an already-freed slot."""
        for b in blocks:
            if self.refcount(b) <= 0:
                raise ValueError(
                    f"donate of free block {b}: export must happen "
                    f"before the slot is torn down")
        self.free(blocks)

    # -- introspection ---------------------------------------------------
    def refcount(self, block: int) -> int:
        with self._lock:
            return self._refs[block]

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - self.free_blocks

    @property
    def used_bytes(self) -> int:
        return self.used_blocks * self.block_bytes

    @property
    def free_bytes(self) -> int:
        return self.free_blocks * self.block_bytes

    def stats(self) -> Dict[str, int]:
        return {
            "num_blocks": self.num_blocks,
            "used_blocks": self.used_blocks,
            "free_blocks": self.free_blocks,
            "block_bytes": self.block_bytes,
            "used_bytes": self.used_bytes,
            "free_bytes": self.free_bytes,
        }


@dataclass
class _Entry:
    """One full block of one cached prefix: the chain link at block
    boundary ``depth`` (prefix length = depth * block_size).

    ``tokens`` is the full covered prefix — needed so an evicted entry
    can be spilled down a tier under a key the next admission (or a
    peer replica, via the stable hash) can still resolve, and so tier
    hits verify against real tokens instead of trusting a hash."""
    block: int
    depth: int
    tokens: Tuple[int, ...] = ()
    _stable: Optional[int] = None

    @property
    def stable(self) -> int:
        if self._stable is None:
            self._stable = stable_hash_prefix(self.tokens)
        return self._stable


class PrefixCache:
    """Block-granular prompt-prefix reuse over a :class:`BlockAllocator`.

    Entries are keyed ``hash(tokens[: j * block_size])`` for j = 1..;
    each holds exactly one cache-owned reference on one block. ``match``
    walks j upward until the first miss — the hit blocks cover positions
    ``[0, hits * block_size)`` and arrive *increffed for the caller*
    (the engine later frees them with the rest of the sequence's table,
    no special-casing). Eviction pops least-recently-matched entries;
    an entry's block only truly returns to the pool once every sequence
    still reading it has also released it — refcounts make eviction safe
    mid-flight.
    """

    def __init__(self, allocator: BlockAllocator,
                 max_blocks: Optional[int] = None):
        self.allocator = allocator
        self.max_blocks = (allocator.num_blocks if max_blocks is None
                           else max_blocks)
        self._entries: "OrderedDict[int, _Entry]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0            # match() calls that found >= 1 block
        self.misses = 0
        self.hit_tokens = 0      # positions whose prefill was skipped
        self.hit_bytes = 0       # HBM bytes those positions occupy
        self.evictions = 0       # entries evicted (≈ blocks released)
        self.evicted_bytes = 0
        self.spilled = 0         # evicted blocks handed to spill_fn
        self.spilled_bytes = 0
        self.spill_errors = 0
        # Engine-installed eviction hook: called with the victim
        # ``_Entry`` list while their blocks STILL hold the cache ref
        # (the HBM rows are valid until the ``allocator.free`` that
        # follows). Returns how many blocks it actually spilled.
        self.spill_fn: Optional[Callable[[List[_Entry]], int]] = None

    def __len__(self) -> int:
        return len(self._entries)

    # -- lookup ----------------------------------------------------------
    def match(self, tokens: Sequence[int],
              max_blocks: Optional[int] = None) -> List[int]:
        """Longest cached block-chain covering a prefix of ``tokens``.

        Returns the physical block ids (may be empty), each increffed on
        behalf of the caller. ``max_blocks`` caps the hit (the engine
        passes ``(len(prompt) - 1) // block_size`` so at least the last
        prompt token is always prefilled — its logits seed sampling)."""
        bs = self.allocator.block_size
        limit = len(tokens) // bs
        if max_blocks is not None:
            limit = min(limit, max_blocks)
        out: List[int] = []
        with self._lock:
            for j in range(1, limit + 1):
                e = self._entries.get(hash_prefix(tokens[: j * bs]))
                if e is None or e.depth != j:
                    break
                out.append(e.block)
                self._entries.move_to_end(hash_prefix(tokens[: j * bs]))
            if out:
                self.hits += 1
                self.hit_tokens += len(out) * bs
                self.hit_bytes += len(out) * self.allocator.block_bytes
            else:
                self.misses += 1
        if out:
            self.allocator.incref(out)
        return out

    # -- registration ----------------------------------------------------
    def insert(self, tokens: Sequence[int], blocks: Sequence[int]) -> None:
        """Register a prompt's resident full blocks. ``blocks[j]`` must
        hold the KV rows for positions ``[j*bs, (j+1)*bs)`` of
        ``tokens``. Already-cached depths are skipped (the shared block
        is already registered); new depths take one cache-owned ref."""
        bs = self.allocator.block_size
        n = min(len(tokens) // bs, len(blocks))
        fresh: List[Tuple[int, _Entry]] = []
        with self._lock:
            for j in range(1, n + 1):
                key = hash_prefix(tokens[: j * bs])
                if key in self._entries:
                    self._entries.move_to_end(key)
                    continue
                fresh.append((key, _Entry(block=blocks[j - 1], depth=j,
                                          tokens=tuple(tokens[: j * bs]))))
        if not fresh:
            return
        self.allocator.incref([e.block for _, e in fresh])
        with self._lock:
            for key, e in fresh:
                if key in self._entries:       # lost a race: drop our ref
                    self.allocator.free([e.block])
                    continue
                self._entries[key] = e
            overflow = len(self._entries) - self.max_blocks
        if overflow > 0:
            self.evict(overflow)

    # -- eviction --------------------------------------------------------
    def evict(self, n_blocks: int) -> int:
        """Release the ``n_blocks`` least-recently-matched entries'
        cache refs (deepest-first within equal recency, so a chain's
        tail goes before its root and surviving prefixes stay usable).
        Returns how many refs were dropped; the pool only grows by the
        blocks nobody else still reads.

        If the engine installed :attr:`spill_fn`, the victims are
        offered to it *before* their refs drop — at that point the
        cache still owns the blocks, so the hook may gather their HBM
        rows and park them in a lower tier. Spill failures are counted
        and never block the eviction itself (the pool must grow)."""
        victims: List[_Entry] = []
        with self._lock:
            # LRU order with chain-tail preference: scan from coldest,
            # take deepest entries first among the same prefix family.
            while len(victims) < n_blocks and self._entries:
                # coldest key
                key = next(iter(self._entries))
                victims.append(self._entries.pop(key))
                self.evictions += 1
        if not victims:
            return 0
        self.evicted_bytes += len(victims) * self.allocator.block_bytes
        if self.spill_fn is not None:
            try:
                n = int(self.spill_fn(victims))
                self.spilled += n
                self.spilled_bytes += n * self.allocator.block_bytes
            except Exception:
                self.spill_errors += 1
        self.allocator.free([e.block for e in victims])
        return len(victims)

    def clear(self) -> None:
        self.evict(len(self._entries))

    def snapshot_heads(self, max_heads: int = 512) -> List[Tuple[int, int]]:
        """Hottest cached chain links as ``(stable_hash, depth)`` pairs,
        most-recently-matched first — what a replica publishes to the
        cluster-wide prefix index. Uses :func:`stable_hash_prefix` so
        peers can compare against their own prompts; entries inserted
        without tokens (pre-tiering callers) are skipped."""
        with self._lock:
            entries = [e for e in reversed(self._entries.values())
                       if e.tokens][:max_heads]
        return [(e.stable, e.depth) for e in entries]

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "hit_tokens": self.hit_tokens,
                "hit_bytes": self.hit_bytes,
                "evictions": self.evictions,
                "evicted_bytes": self.evicted_bytes,
                "spilled": self.spilled,
                "spilled_bytes": self.spilled_bytes,
                "spill_errors": self.spill_errors,
            }


@dataclass
class KVPrefix:
    """One spilled prefix block, detached from any pool.

    The tier-resident sibling of :class:`KVState`: where KVState
    checkpoints a *live sequence* (sampling state, emitted tokens),
    KVPrefix carries only the KV rows of full prompt blocks — no
    ``next_tok``/``pos`` semantics, because a promoted prefix re-enters
    through admission, not through resume. ``tokens`` is the full
    covered prefix; the payload holds its LAST ``n_blocks`` blocks
    (spilled chain links carry one block each — the earlier links are
    their own entries), and doubles as the collision check for
    hash-keyed tier lookups. Plain ndarrays so the object-store tier
    holds it zero-copy.
    """

    tokens: Tuple[int, ...]
    block_size: int
    k_blocks: object        # np [L, n_blocks, bs, n_kv, head_dim]
    v_blocks: object

    @property
    def n_blocks(self) -> int:
        return int(self.k_blocks.shape[1])

    @property
    def payload_bytes(self) -> int:
        return int(self.k_blocks.nbytes + self.v_blocks.nbytes)

    def validate(self) -> None:
        if not self.tokens or len(self.tokens) % self.block_size:
            raise ValueError(
                f"KVPrefix must cover whole blocks, got "
                f"{len(self.tokens)} tokens at block_size "
                f"{self.block_size}")
        if self.n_blocks * self.block_size > len(self.tokens):
            raise ValueError(
                f"KVPrefix holds {self.n_blocks} blocks but the "
                f"covered prefix is only {len(self.tokens)} tokens")
        if self.k_blocks.shape != self.v_blocks.shape:
            raise ValueError("k/v block shape mismatch")


@dataclass
class PromoteCostModel:
    """Is the scatter cheaper than the recompute?

    Promoting n tier blocks back into HBM costs a fixed dispatch (host
    staging + one `_adopt_fn` launch) plus a per-block transfer;
    recomputing costs prefill over the covered tokens. Short suffixes
    lose to recompute — prefill is one fused program and the fixed
    adopt cost dominates — so admission only promotes when the model
    says the crossover is passed. Defaults come from the
    ``serve_kv_adopt_cost_*`` / ``serve_kv_prefill_cost_per_token_ms``
    config knobs; benches overwrite them with measured numbers.
    """

    adopt_fixed_s: float = 2e-3
    adopt_per_block_s: float = 1e-4
    prefill_per_token_s: float = 5e-5

    def promote_cost_s(self, n_blocks: int) -> float:
        return self.adopt_fixed_s + n_blocks * self.adopt_per_block_s

    def recompute_cost_s(self, n_tokens: int) -> float:
        return n_tokens * self.prefill_per_token_s

    def should_promote(self, n_blocks: int, block_size: int) -> bool:
        return (self.promote_cost_s(n_blocks)
                < self.recompute_cost_s(n_blocks * block_size))


@dataclass
class TierHit:
    """One tier-lookup result: where ``prefix`` was found and under
    which key, so a successful promote can :meth:`KVTierManager.pop`
    exactly what it consumed (all-or-nothing: nothing is popped until
    the scatter landed)."""
    key: int
    tier: str
    prefix: KVPrefix


class KVTierManager:
    """Host-RAM + object-store tiers below the HBM block pool.

    Spilled blocks land in an LRU host dict bounded by
    ``host_budget_bytes``; overflow demotes the coldest entries to the
    object store via ``put_fn`` (→ ``ray_tpu.put``) when a cluster is
    attached, else drops them (counted — a dropped block just means a
    future recompute, never an error). ``lookup`` extends an HBM
    partial hit with the longest contiguous tier run; ``pop`` commits
    consumption after the engine's scatter succeeded.

    Keys are process-local :func:`hash_prefix` values — the manager
    lives and dies with its engine. What crosses processes is the
    *stable* hash (:meth:`stable_heads`, the cluster index) and the
    KVPrefix payloads themselves (peer pull), both of which re-verify
    against real tokens here before anything is trusted.

    Thread-safe: the engine scheduler spills/promotes while dashboard
    and publisher threads read stats/heads.
    """

    TIERS = ("host", "store")

    def __init__(self, host_budget_bytes: int, block_size: int = 16,
                 put_fn: Optional[Callable[[Any], Any]] = None,
                 get_fn: Optional[Callable[[Any], Any]] = None):
        self.host_budget_bytes = int(host_budget_bytes)
        self.block_size = int(block_size)
        self.put_fn = put_fn
        self.get_fn = get_fn
        self._host: "OrderedDict[int, KVPrefix]" = OrderedDict()
        self._store: "OrderedDict[int, Tuple[Any, Tuple[int, ...], int]]" \
            = OrderedDict()          # key -> (ref, tokens, payload_bytes)
        self._host_bytes = 0
        self._store_bytes = 0
        self._lock = threading.Lock()
        self._c = {t: {"hits": 0, "misses": 0, "spills": 0,
                       "promotes": 0} for t in self.TIERS}
        self.dropped_blocks = 0
        self.dropped_bytes = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._host) + len(self._store)

    # -- spill (HBM -> host -> store) ------------------------------------
    def spill(self, prefixes: Sequence[KVPrefix]) -> int:
        """Park evicted blocks in the host tier (newest hottest),
        demoting over-budget cold entries downward. Returns how many of
        ``prefixes`` were accepted (all, unless a prefix fails
        validation)."""
        n = 0
        for p in prefixes:
            try:
                p.validate()
            except (ValueError, AttributeError):
                continue
            key = hash_prefix(p.tokens)
            with self._lock:
                old = self._host.pop(key, None)
                if old is not None:
                    self._host_bytes -= old.payload_bytes
                self._host[key] = p
                self._host_bytes += p.payload_bytes
                self._c["host"]["spills"] += 1
                n += 1
        self._demote_overflow()
        return n

    def _demote_overflow(self) -> None:
        """Push the coldest host entries down until under budget."""
        while True:
            with self._lock:
                if self._host_bytes <= self.host_budget_bytes \
                        or not self._host:
                    return
                key, p = self._host.popitem(last=False)
                self._host_bytes -= p.payload_bytes
            if self.put_fn is None:
                with self._lock:
                    self.dropped_blocks += p.n_blocks
                    self.dropped_bytes += p.payload_bytes
                continue
            try:
                ref = self.put_fn(p)
            except Exception:
                with self._lock:
                    self.dropped_blocks += p.n_blocks
                    self.dropped_bytes += p.payload_bytes
                continue
            with self._lock:
                self._store[key] = (ref, p.tokens, p.payload_bytes)
                self._store_bytes += p.payload_bytes
                self._c["store"]["spills"] += 1

    # -- lookup (promote candidates) -------------------------------------
    def lookup(self, tokens: Sequence[int], block_size: int,
               start_depth: int = 0,
               max_blocks: Optional[int] = None) -> List[TierHit]:
        """Longest contiguous tier run continuing ``tokens`` from block
        boundary ``start_depth`` (the HBM hit depth). Walks depths
        upward, host tier first, resolving store refs through
        ``get_fn``; every hit is token-verified. Entries stay resident —
        call :meth:`pop` only after the promote scatter landed."""
        limit = len(tokens) // block_size
        if max_blocks is not None:
            limit = min(limit, start_depth + max_blocks)
        hits: List[TierHit] = []
        for j in range(start_depth + 1, limit + 1):
            want = tuple(tokens[: j * block_size])
            key = hash_prefix(want)
            hit = self._lookup_one(key, want)
            if hit is None:
                break
            hits.append(hit)
        return hits

    def _lookup_one(self, key: int,
                    want: Tuple[int, ...]) -> Optional[TierHit]:
        with self._lock:
            p = self._host.get(key)
            if p is not None and p.tokens == want:
                self._host.move_to_end(key)
                self._c["host"]["hits"] += 1
                return TierHit(key=key, tier="host", prefix=p)
            self._c["host"]["misses"] += 1
            entry = self._store.get(key)
        if entry is None or self.get_fn is None:
            with self._lock:
                self._c["store"]["misses"] += 1
            return None
        ref, tok, _ = entry
        if tok != want:
            with self._lock:
                self._c["store"]["misses"] += 1
            return None
        try:
            p = self.get_fn(ref)
        except Exception:
            p = None
        if p is None or tuple(p.tokens) != want:
            with self._lock:
                self._c["store"]["misses"] += 1
            return None
        with self._lock:
            self._c["store"]["hits"] += 1
        return TierHit(key=key, tier="store", prefix=p)

    def pop(self, hits: Sequence[TierHit]) -> None:
        """Commit consumption of promoted entries: drop them from their
        tier (a promoted block is HBM-resident again and re-enters the
        PrefixCache via the normal insert path — keeping the tier copy
        would double-count the budget)."""
        with self._lock:
            for h in hits:
                p = self._host.pop(h.key, None)
                if p is not None:
                    self._host_bytes -= p.payload_bytes
                    self._c["host"]["promotes"] += 1
                    continue
                entry = self._store.pop(h.key, None)
                if entry is not None:
                    self._store_bytes -= entry[2]
                    self._c["store"]["promotes"] += 1

    # -- cluster index ---------------------------------------------------
    def stable_heads(self, max_heads: int = 512) -> List[Tuple[int, int]]:
        """Tier-resident chain links as ``(stable_hash, depth)`` pairs,
        hottest first — merged with :meth:`PrefixCache.snapshot_heads`
        into the replica's published index entry."""
        toks: List[Tuple[int, ...]] = []
        with self._lock:
            for p in reversed(self._host.values()):
                if len(toks) >= max_heads:
                    break
                toks.append(p.tokens)
            for _, tok, _ in reversed(self._store.values()):
                if len(toks) >= max_heads:
                    break
                toks.append(tok)
        return [(stable_hash_prefix(t), len(t) // self.block_size)
                for t in toks]

    def clear(self) -> None:
        with self._lock:
            self._host.clear()
            self._store.clear()
            self._host_bytes = self._store_bytes = 0

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "host": dict(self._c["host"], blocks=len(self._host),
                             bytes=self._host_bytes,
                             budget_bytes=self.host_budget_bytes),
                "store": dict(self._c["store"], blocks=len(self._store),
                              bytes=self._store_bytes),
                "dropped_blocks": self.dropped_blocks,
                "dropped_bytes": self.dropped_bytes,
            }
