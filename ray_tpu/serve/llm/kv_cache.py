"""Paged KV-cache bookkeeping: block allocator + token-prefix cache.

The HBM side lives in :mod:`ray_tpu.models.llama` (`init_paged_kv_cache`
allocates one fixed pool of ``[block_size]``-row KV blocks per layer;
`decode_step_paged` / `prefill_kv_paged` read and write it through
per-sequence *block tables*). This module is the host side: which
physical block belongs to whom, and which prompt prefixes are already
resident so admission can skip their prefill entirely.

Two pieces, both pure host-Python (no jax imports — unit-testable
without a device):

- :class:`BlockAllocator` — a fixed pool of ``num_blocks`` block ids
  with per-block reference counts. ``alloc`` hands out free ids or
  reports exhaustion (the engine *queues* the request — never crashes);
  ``incref``/``free`` implement copy-on-write sharing: a block reaching
  refcount 0 returns to the free list, a shared block stays resident
  until its last reader releases it. ``copy_on_write`` gives a private
  copy id for a shared block about to be mutated (the engine's sharing
  is block-aligned — only *full* prompt blocks are ever shared, and
  sequences write strictly past them — so the engine never triggers the
  copy; the primitive is here, and tested, for sub-block sharing).

- :class:`PrefixCache` — RadixAttention-style reuse keyed on the hash
  of the token prefix at every block boundary (a hash chain rather than
  a radix tree: block-granular lookups need only exact block-boundary
  matches). ``match`` walks the chain and increfs every hit block for
  the caller; ``insert`` registers a finished prompt's full blocks,
  taking cache-owned refs so blocks outlive the sequence that produced
  them; LRU eviction frees the coldest tails when the allocator runs
  dry (vLLM: "Efficient Memory Management for LLM Serving with
  PagedAttention"; SGLang: RadixAttention).
"""

from __future__ import annotations

import threading
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["BlockAllocator", "KVState", "PrefixCache", "hash_prefix"]


@dataclass
class KVState:
    """A sequence's paged KV checkpoint, detached from any engine.

    The unit of KV migration: `LLMEngine._export_state` densifies the
    slot's live blocks into plain ndarrays ([L, n_valid, bs, n_kv, hd],
    zero-copy through the object store), and `LLMEngine.submit_adopted`
    scatters them into another engine's pool. Produced by the
    disaggregated prefill tier (serve/llm/disagg) and by batch-lane
    preemption (the checkpoint that lets a preempted decode resume).

    ``pos`` is the number of CONSUMED tokens — rows [0, pos) of the
    dense view are valid; ``next_tok`` is the last sampled token, not
    yet consumed (the engine's device ``tok`` at export time).
    ``tokens`` are the tokens already emitted to the caller (the first
    sampled token onward), so an adopting engine resumes max_tokens /
    stop accounting exactly where the exporter left off.
    """

    prompt: List[int]
    tokens: List[int]
    next_tok: int
    pos: int
    temperature: float
    block_size: int
    k_blocks: object        # np [L, n_valid, bs, n_kv, head_dim]
    v_blocks: object

    @property
    def n_blocks(self) -> int:
        return int(self.k_blocks.shape[1])

    @property
    def payload_bytes(self) -> int:
        return int(self.k_blocks.nbytes + self.v_blocks.nbytes)

    def validate(self) -> None:
        bs = self.block_size
        need = -(-self.pos // bs)
        if self.n_blocks != need:
            raise ValueError(
                f"KVState holds {self.n_blocks} blocks but pos="
                f"{self.pos} at block_size={bs} needs {need}")
        if self.k_blocks.shape != self.v_blocks.shape:
            raise ValueError("k/v block shape mismatch")
        if not self.tokens or self.tokens[-1] != self.next_tok:
            raise ValueError(
                "next_tok must be the last emitted token (sampled but "
                "not yet consumed)")
        if self.pos != len(self.prompt) + len(self.tokens) - 1:
            raise ValueError(
                f"pos={self.pos} inconsistent with prompt "
                f"{len(self.prompt)} + emitted {len(self.tokens)} "
                f"(expected prompt + emitted - 1 consumed tokens)")


def hash_prefix(tokens: Sequence[int]) -> int:
    """Stable key for a token prefix. Python's tuple hash is salted per
    process (PYTHONHASHSEED) which is fine — keys never cross processes;
    each replica owns its pool, so its cache is process-local too."""
    return hash(tuple(tokens))


class BlockAllocator:
    """Fixed pool of ``num_blocks`` KV blocks with refcounts.

    Thread-safe: the engine's scheduler thread allocates while the
    dashboard thread reads stats. All ops are O(1) amortized.
    """

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks <= 0 or block_size <= 0:
            raise ValueError(
                f"need positive num_blocks/block_size, got "
                f"{num_blocks}/{block_size}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._free: deque = deque(range(num_blocks))
        self._refs: List[int] = [0] * num_blocks
        self._lock = threading.Lock()

    # -- core ------------------------------------------------------------
    def alloc(self, n: int) -> Optional[List[int]]:
        """n fresh blocks at refcount 1, or None if the pool can't cover
        it (caller queues / evicts; nothing is partially allocated)."""
        with self._lock:
            if n > len(self._free):
                return None
            out = [self._free.popleft() for _ in range(n)]
            for b in out:
                self._refs[b] = 1
            return out

    def incref(self, blocks: Sequence[int]) -> None:
        with self._lock:
            for b in blocks:
                if self._refs[b] <= 0:
                    raise ValueError(f"incref on free block {b}")
                self._refs[b] += 1

    def free(self, blocks: Sequence[int]) -> None:
        """Drop one reference per id; blocks hitting 0 rejoin the pool."""
        with self._lock:
            for b in blocks:
                r = self._refs[b] - 1
                if r < 0:
                    raise ValueError(f"double free of block {b}")
                self._refs[b] = r
                if r == 0:
                    self._free.append(b)

    def fork(self, blocks: Sequence[int]) -> List[int]:
        """Share an existing table (copy-on-write fork): the child holds
        the same physical ids, each with one more reference."""
        self.incref(blocks)
        return list(blocks)

    def copy_on_write(self, block: int) -> Tuple[int, bool]:
        """Prepare ``block`` for mutation. Uniquely-owned blocks are
        returned as-is; shared ones release one ref and return a fresh
        private id (returns (id, needs_copy) — the caller must copy the
        HBM rows when needs_copy). None is never returned: raises on
        exhaustion so callers treat COW pressure as a hard signal."""
        with self._lock:
            if self._refs[block] <= 0:
                raise ValueError(f"copy_on_write on free block {block}")
            if self._refs[block] == 1:
                return block, False
            if not self._free:
                raise MemoryError(
                    "copy_on_write: pool exhausted (free a sequence or "
                    "evict prefix-cache entries first)")
            new = self._free.popleft()
            self._refs[new] = 1
            self._refs[block] -= 1
            return new, True

    # -- migration -------------------------------------------------------
    def adopt(self, n: int,
              prefix_cache: Optional["PrefixCache"] = None
              ) -> Optional[List[int]]:
        """All-or-nothing allocation for an imported/resumed sequence:
        like :meth:`alloc`, but under pressure it first evicts cold
        prefix-cache entries to make room (the same fallback admission
        uses). Returns None — nothing allocated, nothing evicted beyond
        the attempt — when the pool still can't cover ``n``; the caller
        requeues the import and retries as running sequences finish."""
        blocks = self.alloc(n)
        if blocks is None and prefix_cache is not None:
            prefix_cache.evict(n - self.free_blocks)
            blocks = self.alloc(n)
        return blocks

    def donate(self, blocks: Sequence[int]) -> None:
        """Release a live sequence's block refs after its KV has been
        exported (the ownership hand-off half of a migration: the rows
        now live in a :class:`KVState` / another engine's pool, so this
        engine's copies may be recycled). Identical accounting to
        :meth:`free` — the name records intent at export sites, and the
        liveness check catches exporting an already-freed slot."""
        for b in blocks:
            if self.refcount(b) <= 0:
                raise ValueError(
                    f"donate of free block {b}: export must happen "
                    f"before the slot is torn down")
        self.free(blocks)

    # -- introspection ---------------------------------------------------
    def refcount(self, block: int) -> int:
        with self._lock:
            return self._refs[block]

    @property
    def free_blocks(self) -> int:
        with self._lock:
            return len(self._free)

    @property
    def used_blocks(self) -> int:
        return self.num_blocks - self.free_blocks


@dataclass
class _Entry:
    """One full block of one cached prefix: the chain link at block
    boundary ``depth`` (prefix length = depth * block_size)."""
    block: int
    depth: int


class PrefixCache:
    """Block-granular prompt-prefix reuse over a :class:`BlockAllocator`.

    Entries are keyed ``hash(tokens[: j * block_size])`` for j = 1..;
    each holds exactly one cache-owned reference on one block. ``match``
    walks j upward until the first miss — the hit blocks cover positions
    ``[0, hits * block_size)`` and arrive *increffed for the caller*
    (the engine later frees them with the rest of the sequence's table,
    no special-casing). Eviction pops least-recently-matched entries;
    an entry's block only truly returns to the pool once every sequence
    still reading it has also released it — refcounts make eviction safe
    mid-flight.
    """

    def __init__(self, allocator: BlockAllocator,
                 max_blocks: Optional[int] = None):
        self.allocator = allocator
        self.max_blocks = (allocator.num_blocks if max_blocks is None
                           else max_blocks)
        self._entries: "OrderedDict[int, _Entry]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0            # match() calls that found >= 1 block
        self.misses = 0
        self.hit_tokens = 0      # positions whose prefill was skipped
        self.evictions = 0       # entries evicted (≈ blocks released)

    def __len__(self) -> int:
        return len(self._entries)

    # -- lookup ----------------------------------------------------------
    def match(self, tokens: Sequence[int],
              max_blocks: Optional[int] = None) -> List[int]:
        """Longest cached block-chain covering a prefix of ``tokens``.

        Returns the physical block ids (may be empty), each increffed on
        behalf of the caller. ``max_blocks`` caps the hit (the engine
        passes ``(len(prompt) - 1) // block_size`` so at least the last
        prompt token is always prefilled — its logits seed sampling)."""
        bs = self.allocator.block_size
        limit = len(tokens) // bs
        if max_blocks is not None:
            limit = min(limit, max_blocks)
        out: List[int] = []
        with self._lock:
            for j in range(1, limit + 1):
                e = self._entries.get(hash_prefix(tokens[: j * bs]))
                if e is None or e.depth != j:
                    break
                out.append(e.block)
                self._entries.move_to_end(hash_prefix(tokens[: j * bs]))
            if out:
                self.hits += 1
                self.hit_tokens += len(out) * bs
            else:
                self.misses += 1
        if out:
            self.allocator.incref(out)
        return out

    # -- registration ----------------------------------------------------
    def insert(self, tokens: Sequence[int], blocks: Sequence[int]) -> None:
        """Register a prompt's resident full blocks. ``blocks[j]`` must
        hold the KV rows for positions ``[j*bs, (j+1)*bs)`` of
        ``tokens``. Already-cached depths are skipped (the shared block
        is already registered); new depths take one cache-owned ref."""
        bs = self.allocator.block_size
        n = min(len(tokens) // bs, len(blocks))
        fresh: List[Tuple[int, _Entry]] = []
        with self._lock:
            for j in range(1, n + 1):
                key = hash_prefix(tokens[: j * bs])
                if key in self._entries:
                    self._entries.move_to_end(key)
                    continue
                fresh.append((key, _Entry(block=blocks[j - 1], depth=j)))
        if not fresh:
            return
        self.allocator.incref([e.block for _, e in fresh])
        with self._lock:
            for key, e in fresh:
                if key in self._entries:       # lost a race: drop our ref
                    self.allocator.free([e.block])
                    continue
                self._entries[key] = e
            overflow = len(self._entries) - self.max_blocks
        if overflow > 0:
            self.evict(overflow)

    # -- eviction --------------------------------------------------------
    def evict(self, n_blocks: int) -> int:
        """Release the ``n_blocks`` least-recently-matched entries'
        cache refs (deepest-first within equal recency, so a chain's
        tail goes before its root and surviving prefixes stay usable).
        Returns how many refs were dropped; the pool only grows by the
        blocks nobody else still reads."""
        victims: List[int] = []
        with self._lock:
            # LRU order with chain-tail preference: scan from coldest,
            # take deepest entries first among the same prefix family.
            while len(victims) < n_blocks and self._entries:
                # coldest key
                key = next(iter(self._entries))
                e = self._entries.pop(key)
                victims.append(e.block)
                self.evictions += 1
        if victims:
            self.allocator.free(victims)
        return len(victims)

    def clear(self) -> None:
        self.evict(len(self._entries))

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "entries": len(self._entries),
                "hits": self.hits,
                "misses": self.misses,
                "hit_tokens": self.hit_tokens,
                "evictions": self.evictions,
            }
