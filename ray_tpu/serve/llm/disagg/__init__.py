"""Disaggregated LLM serving: prefill and decode on separate pools.

The monolithic tier (serve/llm/deployment.py) runs prefill and decode
in the same engine, so one long prefill stalls every decode slot behind
it — the continuous-batching head-of-line failure. This package splits
the two phases across replica pools and ships the only state that ties
them together — the request's paged KV blocks — through the object
store:

- :class:`PrefillServer` runs prefill + the first sampled token and
  exports the sequence as a :class:`~ray_tpu.serve.llm.kv_cache.KVState`
  (dense per-layer block slices: plain ndarrays, so the object-store
  put is zero-copy; on real pods this hop becomes an ICI transfer).
- :class:`DecodeServer` adopts the blocks into its own
  ``BlockAllocator`` — all-or-nothing — and continues decoding with
  token-for-token parity to the monolithic path.
- The router (serve/llm/router.py) passes the prefill result between
  the pools **by ObjectRef**: the KV bytes move store-to-store and
  never transit the router process.

Speculative decoding (disagg/spec.py) rides along as the raw
decode-speed lever for the decode pool: a tiny draft proposes
``spec_k - 1`` tokens, one paged verify step on the target accepts the
longest agreeing prefix — greedy parity by construction.
"""

from ray_tpu.serve.llm.disagg.app import build_disagg_llm_app
from ray_tpu.serve.llm.disagg.decode import DecodeServer
from ray_tpu.serve.llm.disagg.prefill import PrefillServer
from ray_tpu.serve.llm.disagg.spec import build_draft, draft_config_for
from ray_tpu.serve.llm.disagg.transfer import KVExporter, KVImporter

__all__ = [
    "KVExporter",
    "KVImporter",
    "PrefillServer",
    "DecodeServer",
    "build_disagg_llm_app",
    "build_draft",
    "draft_config_for",
]
