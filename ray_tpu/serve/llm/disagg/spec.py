"""Draft-model helpers for speculative decoding.

The engine accepts any (draft_params, draft_config) pair whose
tokenizer/vocab matches the target; these helpers build the standard
one: a shrunk Llama sharing the target's vocab and rope geometry. The
draft only needs to *rank* next tokens like the target often enough to
pay for its own forward pass — acceptance is verified, so a bad draft
costs speed, never correctness.
"""

from __future__ import annotations

from typing import Any

__all__ = ["draft_config_for", "build_draft"]


def draft_config_for(config: Any, *, n_layers: int = 2,
                     dim: int = 64, n_heads: int = 4,
                     n_kv_heads: int = 2, hidden_dim: int = 128):
    """A tiny draft config compatible with ``config``: same vocab,
    sequence limit, rope theta and dtype (the draft's cache rows must
    cover the same positions), everything else shrunk to the floors
    the model code supports."""
    import dataclasses

    return dataclasses.replace(
        config,
        n_layers=min(n_layers, config.n_layers),
        dim=min(dim, config.dim),
        n_heads=min(n_heads, config.n_heads),
        n_kv_heads=min(n_kv_heads, config.n_kv_heads),
        hidden_dim=min(hidden_dim, config.hidden_dim),
        n_experts=0,
    )


def build_draft(config: Any, seed: int = 0, draft_config: Any = None):
    """(draft_params, draft_config) for ``config``. Random init — the
    production hook is to pass a distilled checkpoint straight to
    ``LLMEngine(draft_params=..., draft_config=...)``; this helper
    exists for tests/benchmarks where acceptance rate is not the
    subject."""
    import jax

    from ray_tpu.models.llama import init_params

    dc = draft_config or draft_config_for(config)
    return init_params(dc, jax.random.key(seed)), dc
