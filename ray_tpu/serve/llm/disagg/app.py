"""Disaggregated serving composition: Router(Prefill, Decode).

``build_disagg_llm_app`` is the disagg twin of
``serve.llm.build_routed_llm_app``: two independently-sized replica
pools behind the lane-aware router. Short prompts go straight to the
decode pool (their prefill is cheap); prompts at or past
``prefill_threshold`` tokens take the two-hop path — prefill replica
exports KV, decode replica adopts it, payload by ObjectRef.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

__all__ = ["build_disagg_llm_app"]


def build_disagg_llm_app(model_config: Any = None,
                         engine_config: Any = None, *,
                         name: str = "llm",
                         prefill_replicas: int = 1,
                         decode_replicas: int = 1,
                         prefill_engine_config: Any = None,
                         prefill_threshold: int = 256,
                         speculative: Any = None,
                         num_tpus: float = 0,
                         max_ongoing_requests: int = 32,
                         init_seed: int = 0,
                         quantize: Optional[str] = None,
                         params_loader: Optional[Any] = None,
                         probe_interval_s: Optional[float] = None):
    """Bind the disaggregated tier as one Serve application.

    ``engine_config`` shapes the decode pool; ``prefill_engine_config``
    (default: same config) shapes the prefill pool — both must be
    paged, and the prefill pool needs ``prefix_cache=True`` (chunked
    long-prompt admission hands off through it). ``speculative`` is
    forwarded to the decode pool only: the draft model speeds decoding
    and has nothing to do during prefill.
    """
    from ray_tpu import serve
    from ray_tpu.serve.llm.deployment import _plain
    from ray_tpu.serve.llm.disagg.decode import DecodeServer
    from ray_tpu.serve.llm.disagg.prefill import PrefillServer
    from ray_tpu.serve.llm.router import LLMRouter

    common: Dict[str, Any] = dict(
        model_config=_plain(model_config), init_seed=init_seed,
        quantize=quantize, params_loader=params_loader)
    decode_dep = serve.deployment(
        DecodeServer, name=f"{name}-decode",
        num_replicas=int(decode_replicas), num_tpus=num_tpus,
        max_ongoing_requests=max_ongoing_requests)
    decode_app = decode_dep.bind(
        engine_config=_plain(engine_config),
        speculative=speculative, **common)
    prefill_dep = serve.deployment(
        PrefillServer, name=f"{name}-prefill",
        num_replicas=int(prefill_replicas), num_tpus=num_tpus,
        max_ongoing_requests=max_ongoing_requests)
    prefill_app = prefill_dep.bind(
        engine_config=_plain(prefill_engine_config
                             if prefill_engine_config is not None
                             else engine_config),
        **common)
    router_dep = serve.deployment(
        LLMRouter, name=f"{name}-router", num_replicas=1,
        max_ongoing_requests=max(64, max_ongoing_requests * 4))
    return router_dep.bind(decode_app,
                           probe_interval_s=probe_interval_s,
                           prefill_handle=prefill_app,
                           prefill_threshold=prefill_threshold)
