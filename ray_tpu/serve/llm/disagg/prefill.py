"""PrefillServer — the prefill half of a disaggregated LLM tier.

An ``LLMServer`` whose public method is ``prefill``: run the request
through admission (chunked for prompts past the largest bucket, so one
4k prefill never monopolizes the engine for a whole step) up to its
FIRST sampled token, then export the sequence's paged KV blocks as a
:class:`~ray_tpu.serve.llm.kv_cache.KVState` and free the slot. The
returned dict is the unit the router forwards **by ObjectRef** to a
decode replica: the KV payload is plain ndarrays, so returning it from
the deployment task puts it in the object store zero-copy, and the
decode worker pulls it without the bytes ever touching the router.

A request that already terminates at its first token (stop / eos /
``max_tokens == 1`` / sequence limit) comes back ``done`` with the
finished response — the router answers directly and skips the decode
hop.
"""

from __future__ import annotations

from typing import Any, Dict

from ray_tpu.serve.llm.deployment import LLMServer

__all__ = ["PrefillServer"]


class PrefillServer(LLMServer):
    """Deployment callable for the prefill pool.

    The engine config should lean prefill-shaped: few slots (each
    admission occupies a slot only for its prefill), a deep block pool,
    and ``prefix_cache=True`` so shared prompt prefixes amortize across
    requests — and so chunked long-prompt prefill works at all (chunks
    hand off through the prefix cache).
    """

    def prefill(self, request: Dict[str, Any]) -> Dict[str, Any]:
        """Run prefill + first token for ``request`` (same dict schema
        as ``LLMServer.__call__``) and return::

            {"done": bool,          # True: response is final
             "response": {...},     # __call__-shaped result dict
             "kv_state": KVState | None,
             "request": {...}}      # echo for the decode hop

        Long prompts are admitted in bucket-sized chunks automatically
        (``chunked_prefill``), interleaving with other admissions.
        """
        from ray_tpu.observability import serve_metrics
        from ray_tpu.serve.llm.disagg.transfer import KVExporter
        from ray_tpu.serve.llm.engine import Request
        from ray_tpu.util.tracing import span

        prompt = list(request["prompt"])
        req = Request(
            prompt=prompt,
            max_tokens=int(request.get("max_tokens", 64)),
            temperature=float(request.get("temperature", 0.0)),
            stop=tuple(request.get("stop", ())),
            slo=str(request.get("slo", "interactive")),
            prefill_only=True,
            chunked_prefill=True,
            tenant=str(request.get("tenant", "default")))
        with span("llm.disagg_prefill",
                  attrs={"prompt_len": len(prompt)}):
            try:
                handle = KVExporter(self._engine).run(
                    req, timeout_s=float(request.get("timeout_s", 300.0)))
            except TimeoutError:
                serve_metrics().request_timeouts.inc()
                raise
        return {
            "done": handle.kv_state is None,
            "response": {
                "tokens": handle.tokens,
                "num_tokens": len(handle.tokens),
                "finish_reason": handle.finish_reason,
                "ttft_s": handle.ttft_s,
                "tpot_s": handle.tpot_s,
            },
            "kv_state": handle.kv_state,
            # Cost meter snapshot rides next to the KVState (NOT inside
            # it — KVState is a strict device-payload schema): the
            # decode tier's meter absorbs it so prefill chip-seconds
            # land on the migrated request's single ledger row.
            "meter": (handle.meter.snapshot()
                      if handle.meter is not None else None),
            "request": dict(request),
        }
