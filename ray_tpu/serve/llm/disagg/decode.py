"""DecodeServer — the decode half of a disaggregated LLM tier.

An ``LLMServer`` (so it still serves plain ``__call__`` traffic — the
router sends short interactive prompts straight here, where their
prefill is cheap) plus ``adopt``: take a :class:`PrefillServer` result,
import its KV blocks into this engine's pool, and decode to completion.

``adopt``'s first argument is passed by the router as an **ObjectRef**
of the prefill task's result — the replica's ``handle_request``
materializes ObjectRef args from the object store in this replica's
process, so the KV bytes move store-to-store and never transit the
router.
"""

from __future__ import annotations

from typing import Any, Dict

from ray_tpu.serve.llm.deployment import LLMServer

__all__ = ["DecodeServer"]


class DecodeServer(LLMServer):
    """Deployment callable for the decode pool. Engine config should
    lean decode-shaped: many slots, ``prefix_cache=True`` so adopted
    prompts stay warm for lookalikes, and optionally a draft model
    (``speculative=...``) — speculative decoding is the decode pool's
    raw speed lever and composes with adoption (the draft cache is
    re-seeded from the adopted prompt)."""

    def adopt(self, prefill_result: Dict[str, Any],
              request: Dict[str, Any]) -> Dict[str, Any]:
        """Continue a prefilled request: adopt its exported KVState and
        decode until finish. Returns the same response dict as
        ``__call__``; TTFT fields come from the prefill side of the
        migration (the first token was sampled there)."""
        from ray_tpu.observability import serve_metrics
        from ray_tpu.serve.llm.disagg.transfer import KVImporter
        from ray_tpu.serve.llm.engine import Request
        from ray_tpu.util.tracing import span

        if prefill_result["done"]:
            return prefill_result["response"]
        state = prefill_result["kv_state"]
        req = Request(
            prompt=list(request["prompt"]),
            max_tokens=int(request.get("max_tokens", 64)),
            temperature=float(request.get("temperature", 0.0)),
            stop=tuple(request.get("stop", ())),
            slo=str(request.get("slo", "interactive")),
            tenant=str(request.get("tenant", "default")))
        with span("llm.disagg_decode",
                  attrs={"prompt_len": len(req.prompt),
                         "adopted_blocks": state.n_blocks}):
            handle = KVImporter(self._engine).adopt(
                req, state,
                meter_snapshot=prefill_result.get("meter"))
            try:
                tokens = handle.result(timeout=float(
                    request.get("timeout_s", 300.0)))
            except TimeoutError:
                serve_metrics().request_timeouts.inc()
                raise
        prefill_resp = prefill_result["response"]
        return {
            "tokens": tokens,
            "num_tokens": len(tokens),
            "finish_reason": handle.finish_reason,
            # First token latency belongs to the prefill replica; the
            # decode-side tpot covers the migrated remainder.
            "ttft_s": prefill_resp.get("ttft_s"),
            "tpot_s": handle.tpot_s,
        }
