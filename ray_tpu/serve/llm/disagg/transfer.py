"""KV-block migration between engines — the disaggregation seam.

Both halves are thin: the device work (gather-to-dense on export,
padded scatter on adopt) lives in the engine's two migration programs
(`LLMEngine._export_fn` / `_adopt_fn`, ONE trace each), and the wire
format is :class:`~ray_tpu.serve.llm.kv_cache.KVState` — plain
ndarrays plus resume bookkeeping, chosen so a task returning it hits
the object store's zero-copy ndarray path.

Accounting lives on the IMPORT side only (`rtpu_serve_kv_migrated_*`
count blocks/bytes adopted into a pool): a checkpoint can be exported
once and adopted elsewhere or dropped, and counting both ends would
double-book the panel.
"""

from __future__ import annotations

from typing import Any, Optional

__all__ = ["KVExporter", "KVImporter"]


class KVExporter:
    """Prefill-side half: run a request to its first sampled token and
    hand back the exported checkpoint.

    ``run()`` is synchronous (the prefill deployment blocks one Serve
    thread per request, exactly like the monolithic ``__call__``); the
    engine interleaves all concurrent prefills through its slot pool.
    """

    def __init__(self, engine: Any):
        self._engine = engine

    def run(self, request: Any, timeout_s: float = 300.0):
        """Submit ``request`` (an engine Request; ``prefill_only`` is
        forced on) and return its finished handle. ``handle.kv_state``
        is the exported KVState — or None when the sequence already
        terminated at its first token (stop/eos/length), in which case
        the caller should skip the decode hop entirely."""
        import dataclasses

        if not request.prefill_only:
            request = dataclasses.replace(request, prefill_only=True)
        handle = self._engine.submit(request)
        handle.result(timeout=timeout_s)
        return handle


class KVImporter:
    """Decode-side half: adopt an exported checkpoint into this
    engine's pool and resume decoding."""

    def __init__(self, engine: Any):
        self._engine = engine

    def adopt(self, request: Any, state: Any, *,
              front: bool = False,
              meter_snapshot: Optional[dict] = None):
        """All-or-nothing adoption via ``LLMEngine.submit_adopted``:
        the request queues until the allocator can cover every block
        the sequence may ever need (evicting cold prefix entries if
        that closes the gap), then one scatter lands the blocks and
        decoding continues token-for-token where the exporter
        stopped. ``meter_snapshot`` is the prefill-side cost meter
        (PrefillServer result key "meter") — absorbed into the
        decode-side meter so the migration bills ONE ledger row."""
        return self._engine.submit_adopted(
            request, state, front=front,
            meter_snapshot=meter_snapshot)

    def stats(self) -> dict:
        s = self._engine.stats()
        return dict(s.get("migration", {"blocks": 0, "bytes": 0}))
