"""ray_tpu.serve.llm — continuous-batching LLM serving on TPU.

The engine (`engine.py`) keeps a fixed pool of decode slots inside a
bounded set of compiled XLA programs; the deployment (`deployment.py`)
exposes it as a Serve replica; `kv_cache.py` pages the KV pool and
reuses shared prompt prefixes; `router.py` spreads requests across N
replicas on probed queue depth, SLO lane, and expected prefix-cache
hit (cluster-wide KV index); `disagg/` splits prefill and decode onto
separate replica pools with KV-block migration over the object store
and speculative decoding on the decode side. Evicted prefix blocks
spill down a memory hierarchy (HBM -> host RAM -> object store,
`KVTierManager`) and are promoted back through the adopt scatter when
`PromoteCostModel` says re-adopt beats re-prefill. See PERF.md
"Serving throughput" and README "Paged KV cache & routing" /
"Disaggregated serving" / "KV memory hierarchy" for the design
narrative and bench numbers.
"""

from ray_tpu.serve.llm.deployment import LLMServer, build_llm_app
from ray_tpu.serve.llm.disagg import (
    DecodeServer, KVExporter, KVImporter, PrefillServer,
    build_disagg_llm_app,
)
from ray_tpu.serve.llm.engine import (
    EngineConfig, LLMEngine, Request, RequestHandle, static_batch_generate,
)
from ray_tpu.serve.llm.kv_cache import (
    BlockAllocator, KVPrefix, KVState, KVTierManager, PrefixCache,
    PromoteCostModel, TierHit, stable_hash_prefix,
)
from ray_tpu.serve.llm.router import LLMRouter, build_routed_llm_app

__all__ = [
    "BlockAllocator", "DecodeServer", "EngineConfig", "KVExporter",
    "KVImporter", "KVPrefix", "KVState", "KVTierManager", "LLMEngine",
    "LLMRouter", "LLMServer", "PrefillServer", "PrefixCache",
    "PromoteCostModel", "Request", "RequestHandle", "TierHit",
    "build_disagg_llm_app", "build_llm_app", "build_routed_llm_app",
    "stable_hash_prefix", "static_batch_generate",
]
