"""ray_tpu.serve.llm — continuous-batching LLM serving on TPU.

The engine (`engine.py`) keeps a fixed pool of decode slots inside a
bounded set of compiled XLA programs; the deployment (`deployment.py`)
exposes it as a Serve replica. See PERF.md "Serving throughput" for the
design narrative and bench numbers.
"""

from ray_tpu.serve.llm.deployment import LLMServer, build_llm_app
from ray_tpu.serve.llm.engine import (
    EngineConfig, LLMEngine, Request, RequestHandle, static_batch_generate,
)

__all__ = [
    "EngineConfig", "LLMEngine", "LLMServer", "Request", "RequestHandle",
    "build_llm_app", "static_batch_generate",
]
