"""ray_tpu.serve.llm — continuous-batching LLM serving on TPU.

The engine (`engine.py`) keeps a fixed pool of decode slots inside a
bounded set of compiled XLA programs; the deployment (`deployment.py`)
exposes it as a Serve replica; `kv_cache.py` pages the KV pool and
reuses shared prompt prefixes; `router.py` spreads requests across N
replicas on probed queue depth. See PERF.md "Serving throughput" and
README "Paged KV cache & routing" for the design narrative and bench
numbers.
"""

from ray_tpu.serve.llm.deployment import LLMServer, build_llm_app
from ray_tpu.serve.llm.engine import (
    EngineConfig, LLMEngine, Request, RequestHandle, static_batch_generate,
)
from ray_tpu.serve.llm.kv_cache import BlockAllocator, PrefixCache
from ray_tpu.serve.llm.router import LLMRouter, build_routed_llm_app

__all__ = [
    "BlockAllocator", "EngineConfig", "LLMEngine", "LLMRouter",
    "LLMServer", "PrefixCache", "Request", "RequestHandle",
    "build_llm_app", "build_routed_llm_app", "static_batch_generate",
]
