"""LLMDeployment — the continuous-batching engine as a Serve replica.

Requests flow router -> replica -> engine: the replica actor hosts one
`LLMEngine` plus a single scheduler thread driving it; `__call__`
invocations (which Serve runs concurrently up to
``max_ongoing_requests``) just submit into the engine's queue and block
on their handle, so many in-flight HTTP/handle requests share the one
compiled decode program. This is the piece that turns the single-chip
decode number (bench `llama_decode_tokens_per_sec`) into a serving
throughput number (`llama_serve_tokens_per_sec`).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Sequence


class LLMServer:
    """Deployment callable: owns the engine and its scheduler thread.

    ``model_config`` / ``engine_config`` may be the dataclasses or plain
    kwargs dicts (dicts survive cloudpickle across replicas trivially).
    Weights: ``init_seed`` builds random params in-replica (tests,
    benchmarks); ``params_loader`` — a zero-arg callable returning the
    params pytree — is the production hook (checkpoint load happens in
    the replica process, never on the serialization path).

    ``quantize`` defaults to ``"int8"`` — weight-only int8 decode
    measured 1.28x decode throughput (BENCH_r05: 2158 vs 1683 tok/s) at
    matched quality on the serving path, so it is the serve default;
    pass ``quantize="bf16"`` to opt out (e.g. for bit-parity against an
    offline bf16 reference). The legacy ``quantize_int8=True`` flag is
    honored as a synonym for ``quantize="int8"``.
    """

    def __init__(self, model_config: Any = None,
                 engine_config: Any = None,
                 init_seed: int = 0,
                 params_loader: Optional[Any] = None,
                 quantize: Optional[str] = None,
                 quantize_int8: bool = False,
                 speculative: Any = None):
        import jax

        from ray_tpu.models.llama import (
            LlamaConfig, init_params, quantize_weights_int8,
        )
        from ray_tpu.serve.llm.engine import EngineConfig, LLMEngine

        if model_config is None:
            model_config = LlamaConfig.tiny()
        elif isinstance(model_config, dict):
            model_config = LlamaConfig(**model_config)
        if engine_config is None:
            engine_config = EngineConfig()
        elif isinstance(engine_config, dict):
            engine_config = EngineConfig(**engine_config)

        if quantize is None:
            quantize = "int8"           # serve default (BENCH_r05)
        if quantize not in ("int8", "bf16"):
            raise ValueError(
                f"quantize must be 'int8' or 'bf16', got {quantize!r}")
        self.quantize = quantize

        if params_loader is not None:
            params = params_loader()
        else:
            params = init_params(model_config, jax.random.key(init_seed))
        if quantize == "int8":
            params = quantize_weights_int8(params)

        # Speculative decoding (disagg/spec.py): ``speculative`` is
        # True (default draft geometry), a dict of draft kwargs
        # ({"draft_seed": .., "draft_config": {..}, "params_loader":
        # zero-arg callable}), or None to decode plainly. Weights load
        # in-replica like the target's.
        draft_params = draft_config = None
        if speculative:
            from ray_tpu.serve.llm.disagg.spec import (
                build_draft, draft_config_for,
            )

            spec = speculative if isinstance(speculative, dict) else {}
            dc = spec.get("draft_config")
            if isinstance(dc, dict):
                dc = LlamaConfig(**dc)
            draft_config = dc or draft_config_for(model_config)
            loader = spec.get("params_loader")
            if loader is not None:
                draft_params = loader()
            else:
                draft_params, draft_config = build_draft(
                    model_config, seed=int(spec.get("draft_seed", 0)),
                    draft_config=draft_config)

        self._engine = LLMEngine(params, model_config, engine_config,
                                 draft_params=draft_params,
                                 draft_config=draft_config)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._engine.run, args=(self._stop,),
            name="llm-engine-scheduler", daemon=True)
        self._thread.start()

        # Cluster-wide prefix index: this replica's identity in the
        # GCS index (the router learns it from load()), plus the
        # publisher thread pushing hash-chain heads on a fixed period.
        # The publish IS the liveness signal — a dead replica ages out
        # of cache-aware routing at the index TTL.
        import uuid

        self._replica_id = uuid.uuid4().hex[:12]
        if getattr(self._engine, "_prefix", None) is not None:
            threading.Thread(
                target=self._publish_index_loop, daemon=True,
                name="llm-prefix-index-publish").start()

    def __call__(self, request: Dict[str, Any]) -> Dict[str, Any]:
        """request: {"prompt": [token ids], "max_tokens": int,
        "temperature": float, "stop": [token ids]} -> completed tokens
        plus latency detail. Blocks the calling Serve thread; the engine
        thread interleaves all concurrent requests."""
        from ray_tpu.observability import serve_metrics
        from ray_tpu.serve.llm.engine import Request
        from ray_tpu.util.tracing import span

        # Submit INSIDE the span: the engine captures the submitting
        # thread's trace context on the handle, so llm.request and its
        # phases parent under this llm.server_call hop.
        with span("llm.server_call",
                  attrs={"prompt_len": len(request["prompt"])}):
            handle = self._engine.submit(Request(
                prompt=list(request["prompt"]),
                max_tokens=int(request.get("max_tokens", 64)),
                temperature=float(request.get("temperature", 0.0)),
                stop=tuple(request.get("stop", ())),
                slo=str(request.get("slo", "interactive")),
                chunked_prefill=bool(
                    request.get("chunked_prefill", False)),
                tenant=str(request.get("tenant", "default"))))
            try:
                tokens = handle.result(timeout=float(
                    request.get("timeout_s", 300.0)))
            except TimeoutError:
                serve_metrics().request_timeouts.inc()
                raise
        return {
            "tokens": tokens,
            "num_tokens": len(tokens),
            "finish_reason": handle.finish_reason,
            "ttft_s": handle.ttft_s,
            "tpot_s": handle.tpot_s,
        }

    def _publish_index_loop(self) -> None:
        from ray_tpu._private.config import GlobalConfig
        from ray_tpu._private.worker import global_worker_or_none

        interval = float(
            GlobalConfig.serve_prefix_index_publish_interval_s)
        while not self._stop.wait(interval):
            w = global_worker_or_none()
            if w is None:
                continue        # no cluster: nothing to publish to
            try:
                eng = self._engine
                tiers: Dict[str, Any] = {
                    "block_size": eng.config.kv_block_size}
                if eng._tiers is not None:
                    ts = eng._tiers.stats()
                    tiers["host_blocks"] = ts["host"]["blocks"]
                    tiers["store_blocks"] = ts["store"]["blocks"]
                w.gcs.call("report_prefix_index", timeout=5,
                           replica=self._replica_id,
                           heads=eng.prefix_index_heads(),
                           tiers=tiers)
            except Exception:
                pass            # index is a hint; never crash a replica

    def export_prefix(self, tokens, max_blocks=None):
        """Donor side of a router-initiated peer pull: the longest
        HBM + tier chain covering ``tokens`` as per-block KVPrefix
        links. Hops to the scheduler thread — device state may only be
        read alongside the engine's donating programs there."""
        return self._engine.call_on_scheduler(
            lambda: self._engine.export_prefix(tokens,
                                               max_blocks=max_blocks),
            timeout_s=30.0)

    def import_prefix(self, prefixes) -> int:
        """Receiver side of a peer pull: park pulled links in the host
        tier; the pulling request's admission promotes them through
        the cost model. Thread-safe, no scheduler hop."""
        return self._engine.import_prefix(prefixes)

    def load(self) -> Dict[str, Any]:
        """Cheap load snapshot for the LLM router's queue-depth probe
        (serve/llm/router.py): engine queue + busy slots, no jit-stat
        scan, safe to call at probe frequency. ``index_id`` is how the
        router joins this replica's handle to its GCS prefix-index
        entry."""
        s = self._engine.stats()
        return {
            "queued": s["queued"],
            "active_slots": s["active_slots"],
            "free_slots": s["num_slots"] - s["active_slots"],
            "lanes": s["queued_by_lane"],
            "index_id": self._replica_id,
        }

    def stats(self) -> Dict[str, Any]:
        from ray_tpu.observability import jit_stats

        out = self._engine.stats()
        out["quantize"] = self.quantize
        out["jit"] = {k: v for k, v in jit_stats().items()
                      if k.startswith("llm_engine_")}
        return out

    def check_health(self) -> None:
        if not self._thread.is_alive():
            raise RuntimeError("llm engine scheduler thread died")

    def __del__(self):
        try:
            self._stop.set()
        except Exception:
            pass


def build_llm_app(model_config: Any = None, engine_config: Any = None,
                  *, name: str = "llm", num_replicas: int = 1,
                  num_tpus: float = 0, max_ongoing_requests: int = 32,
                  init_seed: int = 0, quantize: Optional[str] = None,
                  quantize_int8: bool = False,
                  params_loader: Optional[Any] = None):
    """Bind LLMServer as a Serve application: one engine per replica,
    `max_ongoing_requests` concurrent submitters feeding its slot pool.
    Pass configs as dicts (e.g. ``{"num_slots": 8}``) or dataclasses.
    ``quantize`` defaults to the int8 serve config; pass "bf16" to opt
    out. For N replicas behind a queue-depth-aware router, use
    ``serve.llm.build_routed_llm_app`` instead."""
    from ray_tpu import serve

    if quantize is None and quantize_int8:
        quantize = "int8"
    dep = serve.deployment(
        LLMServer, name=name, num_replicas=num_replicas,
        num_tpus=num_tpus, max_ongoing_requests=max_ongoing_requests)
    return dep.bind(model_config=_plain(model_config),
                    engine_config=_plain(engine_config),
                    init_seed=init_seed, quantize=quantize,
                    params_loader=params_loader)


def _plain(cfg: Any):
    """Dataclass -> dict so the spec cloudpickles without importing jax
    dtypes driver-side; dicts/None pass through."""
    import dataclasses

    if cfg is None or isinstance(cfg, dict):
        return cfg
    if dataclasses.is_dataclass(cfg):
        return {f.name: getattr(cfg, f.name)
                for f in dataclasses.fields(cfg)}
    return cfg
