"""Router — picks a replica for each request.

Reference: `serve/_private/router.py:254` + power-of-two-choices scheduler
(`replica_scheduler/pow_2_scheduler.py:44`): sample two random replicas,
send to the one with fewer locally-tracked in-flight requests. The replica
set refreshes from the controller when its routing version bumps.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu


class Router:
    def __init__(self, controller, app_name: str, deployment_name: str):
        import uuid

        self._controller = controller
        self._app = app_name
        self._deployment = deployment_name
        self._replicas: List[Any] = []
        self._version = -1
        self._inflight: Dict[Any, int] = {}
        self._lock = threading.Lock()
        self._last_refresh = 0.0
        self._router_id = uuid.uuid4().hex[:12]
        self._push_thread_started = False
        self._refresh(force=True)

    def _maybe_push_metrics(self) -> None:
        """Start the periodic load reporter on first traffic. A background
        thread (not push-on-assign) keeps reports fresh while long
        requests run with no new arrivals — otherwise the controller sees
        stale-then-zero load and downscales mid-traffic."""
        if self._push_thread_started:
            return
        self._push_thread_started = True

        def run():
            while True:
                time.sleep(2.0)
                with self._lock:
                    total = sum(self._inflight.values())
                try:
                    self._controller.record_handle_metrics.remote(
                        self._app, self._deployment, self._router_id, total)
                except Exception:
                    return    # cluster gone; let the thread die

        threading.Thread(target=run, daemon=True,
                         name="serve-metrics-push").start()

    def _refresh(self, force: bool = False) -> None:
        now = time.monotonic()
        if not force and now - self._last_refresh < 1.0:
            return
        self._last_refresh = now
        version, replicas = ray_tpu.get(
            self._controller.get_replicas.remote(self._app, self._deployment),
            timeout=60)
        with self._lock:
            if version != self._version:
                self._version = version
                self._replicas = replicas
                self._inflight = {r: self._inflight.get(r, 0)
                                  for r in replicas}

    def assign_request(self, method_name: str, args: tuple, kwargs: dict,
                       model_id: str = ""):
        """Returns an ObjectRef for the response."""
        deadline = time.monotonic() + 30.0
        while True:
            self._refresh()
            with self._lock:
                replicas = list(self._replicas)
            if replicas:
                break
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"no live replicas for {self._app}/{self._deployment}")
            self._refresh(force=True)
            time.sleep(0.1)

        with self._lock:
            if len(replicas) == 1:
                chosen = replicas[0]
            elif model_id:
                # Cache affinity: one stable replica per model id so its
                # weights load once, not on every replica (reference:
                # multiplexed routing).
                from ray_tpu.serve.multiplex import rendezvous_pick

                chosen = rendezvous_pick(
                    sorted(replicas, key=lambda r: r._actor_id),
                    model_id)
            else:
                a, b = random.sample(replicas, 2)
                chosen = (a if self._inflight.get(a, 0)
                          <= self._inflight.get(b, 0) else b)
            self._inflight[chosen] = self._inflight.get(chosen, 0) + 1
        self._maybe_push_metrics()

        ref = chosen.handle_request.remote(method_name, args, kwargs,
                                           model_id)

        def _done(_fut):
            with self._lock:
                if chosen in self._inflight:
                    self._inflight[chosen] -= 1

        ref.future().add_done_callback(_done)
        return ref
