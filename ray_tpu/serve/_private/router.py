"""Router — picks a replica for each request.

Reference: `serve/_private/router.py:254` + power-of-two-choices scheduler
(`replica_scheduler/pow_2_scheduler.py:44`): sample two random replicas,
send to the one with fewer locally-tracked in-flight requests. The replica
set is push-invalidated: a background thread long-polls the controller
(`poll_replicas`, the LongPollHost analogue) and replies arrive the moment
the routing version bumps — the request hot path never talks to the
controller.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, List

import ray_tpu


class Router:
    def __init__(self, controller, app_name: str, deployment_name: str):
        import uuid

        self._controller = controller
        self._app = app_name
        self._deployment = deployment_name
        self._replicas: List[Any] = []
        self._version = -1
        self._inflight: Dict[Any, int] = {}
        self._lock = threading.Lock()
        self._have_replicas = threading.Event()
        self._router_id = uuid.uuid4().hex[:12]
        self._push_thread_started = False
        self._closed = False
        # The worker this router was born under: background threads must
        # die with it. Without this, every serve handle ever created
        # leaked a forever-polling daemon thread that kept hammering a
        # dead controller (and the ref-counter lock) for the rest of the
        # PROCESS — dozens of zombie pollers ground long test sessions
        # to a halt two modules later.
        from ray_tpu._private.worker import global_worker_or_none

        self._worker = global_worker_or_none()
        # Synchronous first snapshot, then the long-poll keeps it fresh.
        self._apply(*ray_tpu.get(
            self._controller.get_replicas.remote(app_name, deployment_name),
            timeout=60))
        threading.Thread(target=self._poll_loop, daemon=True,
                         name="serve-router-poll").start()

    def _apply(self, version: int, replicas: List[Any]) -> None:
        with self._lock:
            if version != self._version:
                self._version = version
                self._replicas = replicas
                self._inflight = {r: self._inflight.get(r, 0)
                                  for r in replicas}
            if self._replicas:
                self._have_replicas.set()
            else:
                self._have_replicas.clear()

    def _alive(self) -> bool:
        from ray_tpu._private.worker import global_worker_or_none

        w = global_worker_or_none()
        return (not self._closed and w is not None and w is self._worker
                and not getattr(w, "_dead", False))

    def _poll_loop(self) -> None:
        while self._alive():
            try:
                version, replicas = ray_tpu.get(
                    self._controller.poll_replicas.remote(
                        self._app, self._deployment, self._version, 25.0),
                    timeout=60)
                self._apply(version, replicas)
            except Exception:
                if not self._alive():
                    return
                time.sleep(1.0)

    def _maybe_push_metrics(self) -> None:
        """Start the periodic load reporter on first traffic. A background
        thread (not push-on-assign) keeps reports fresh while long
        requests run with no new arrivals — otherwise the controller sees
        stale-then-zero load and downscales mid-traffic."""
        with self._lock:
            if self._push_thread_started:
                return
            self._push_thread_started = True

        def run():
            while self._alive():
                time.sleep(2.0)
                with self._lock:
                    total = sum(self._inflight.values())
                try:
                    self._controller.record_handle_metrics.remote(
                        self._app, self._deployment, self._router_id, total)
                except Exception:
                    return    # cluster gone; let the thread die

        threading.Thread(target=run, daemon=True,
                         name="serve-metrics-push").start()

    def close(self) -> None:
        """Stop the background threads; the router routes no further
        requests. Safe to call more than once."""
        self._closed = True

    def assign_request(self, method_name: str, args: tuple, kwargs: dict,
                       model_id: str = "", stream: bool = False):
        """Returns an ObjectRef (or ObjectRefGenerator when streaming)."""
        if not self._alive():
            # A handle that outlived its worker would otherwise route on
            # a frozen replica snapshot from the dead cluster.
            raise RuntimeError(
                f"router for {self._app}/{self._deployment} is detached "
                "(its cluster connection was shut down); recreate the "
                "handle after ray_tpu.init()")
        if not self._have_replicas.wait(timeout=30.0):
            raise RuntimeError(
                f"no live replicas for {self._app}/{self._deployment}")
        with self._lock:
            replicas = list(self._replicas)
            if not replicas:
                raise RuntimeError(
                    f"no live replicas for {self._app}/{self._deployment}")
            if len(replicas) == 1:
                chosen = replicas[0]
            elif model_id:
                # Cache affinity: one stable replica per model id so its
                # weights load once, not on every replica (reference:
                # multiplexed routing).
                from ray_tpu.serve.multiplex import rendezvous_pick

                chosen = rendezvous_pick(
                    sorted(replicas, key=lambda r: r._actor_id),
                    model_id)
            else:
                a, b = random.sample(replicas, 2)
                chosen = (a if self._inflight.get(a, 0)
                          <= self._inflight.get(b, 0) else b)
            self._inflight[chosen] = self._inflight.get(chosen, 0) + 1
        self._maybe_push_metrics()

        method = chosen.handle_request
        if stream:
            method = method.options(num_returns="streaming")
        ref = method.remote(method_name, args, kwargs, model_id)

        def _done(_fut):
            with self._lock:
                if chosen in self._inflight:
                    self._inflight[chosen] -= 1

        if stream:
            # Generator: decrement when the final item lands (the
            # generator ref resolves at completion).
            ref._ref0.future().add_done_callback(_done)
        else:
            ref.future().add_done_callback(_done)
        return ref
