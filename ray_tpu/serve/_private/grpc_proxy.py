"""gRPC ingress — deployed applications over gRPC, same routing plane as
the HTTP proxy.

Reference: the gRPC proxy in `serve/_private/proxy.py` +
`serve/_private/grpc_util.py` (gRPCGenericServer). Re-designed without
compiled protos: a generic bytes-in/bytes-out service

    /ray_tpu.serve.ServeAPI/Predict        (unary-unary)
    /ray_tpu.serve.ServeAPI/PredictStream  (unary-stream)

where the target application, method and multiplexed model id travel in
invocation metadata (``application``, ``method``,
``multiplexed_model_id``) — exactly how the reference's gRPC ingress
selects apps. Payload bytes that parse as JSON become Python values;
replies that are bytes pass through raw, strings utf-8, anything else
JSON. Routing state (long-polled route table, per-app handles) mirrors
the HTTP proxy.

User-DEFINED protobuf servicers are supported via
``grpc_servicer_functions`` (reference: `grpc_options.grpc_servicer_
functions` + `grpc_util.gRPCGenericServer`): each generated
``add_XServicer_to_server`` function is invoked against a capture shim
that harvests every RPC's full method path, kind, and request/response
(de)serializers; the proxy then serves those exact paths, handing the
DESERIALIZED request message to the deployment method named after the
rpc and serializing its returned message back — schema'd stubs work
unchanged against the proxy.
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Any, Dict

import ray_tpu
from ray_tpu.serve._private.route_plane import RoutePlane

SERVICE = "ray_tpu.serve.ServeAPI"
PREDICT = f"/{SERVICE}/Predict"
PREDICT_STREAM = f"/{SERVICE}/PredictStream"


def _encode(value: Any) -> bytes:
    if isinstance(value, bytes):
        return value
    if isinstance(value, str):
        return value.encode()
    return json.dumps(value).encode()


def _decode(raw: bytes) -> Any:
    if not raw:
        return None
    try:
        return json.loads(raw)
    except ValueError:
        return raw


class _DummyServicer:
    """Stand-in passed to generated add_*_to_server functions during
    harvesting; generated code only getattr()s rpc method names."""

    def __getattr__(self, name):
        return lambda *a, **k: None


class _HarvestServer:
    """Capture shim with the grpc.Server registration surface. Generated
    code either wraps its handler dict in a generic handler
    (add_generic_rpc_handlers) or, in newer grpcio, registers the dict
    directly (add_registered_method_handlers); both are captured."""

    def __init__(self):
        self.methods: Dict[str, Any] = {}   # "/pkg.Svc/Rpc" -> handler

    def add_generic_rpc_handlers(self, handlers):
        for h in handlers:
            per_method = getattr(h, "_method_handlers", None)
            if per_method:
                self.methods.update(per_method)

    def add_registered_method_handlers(self, service, handlers):
        for name, h in handlers.items():
            self.methods[f"/{service}/{name}"] = h


def harvest_servicer_methods(servicer_functions) -> Dict[str, Any]:
    """Run each add_XServicer_to_server against the capture shim; returns
    {method_path: grpc RpcMethodHandler} carrying each rpc's kind and
    request_deserializer / response_serializer."""
    import importlib

    out: Dict[str, Any] = {}
    for fn in servicer_functions or []:
        if isinstance(fn, str):
            module, _, attr = fn.rpartition(".")
            fn = getattr(importlib.import_module(module), attr)
        shim = _HarvestServer()
        fn(_DummyServicer(), shim)
        for path, h in shim.methods.items():
            if getattr(h, "request_streaming", False):
                # Client-streaming kinds would need request iterator
                # plumbing across the handle; serving them with a unary
                # handler mis-frames the call — reject loudly instead.
                raise ValueError(
                    f"grpc_servicer_functions: rpc '{path}' is "
                    "client-streaming (stream_unary/stream_stream), "
                    "which the proxy does not support; only unary_unary "
                    "and unary_stream rpcs can be routed")
            out[path] = h
    return out


@ray_tpu.remote(num_cpus=0.5, max_concurrency=16)
class GrpcProxyActor(RoutePlane):
    """Per-cluster gRPC ingress actor (HeadOnly placement by default).
    Routing state comes from the shared RoutePlane mixin — one route
    table implementation for both ingress flavors."""

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 servicer_functions=None):
        from ray_tpu.serve._private.controller import get_or_create_controller

        self.port = None
        self._user_methods = harvest_servicer_methods(servicer_functions)
        self._pre_init_route_plane()
        started = threading.Event()
        self._loop_thread = threading.Thread(
            target=self._serve_forever, args=(host, port, started),
            daemon=True, name="serve-grpc-proxy")
        self._loop_thread.start()
        started.wait(timeout=30)
        self._init_route_plane(get_or_create_controller())

    # ---- grpc server ------------------------------------------------------
    def _serve_forever(self, host: str, port: int,
                       started: threading.Event):
        import grpc
        import grpc.aio

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        outer = self

        def _meta(context) -> Dict[str, str]:
            return {k: v for k, v in (context.invocation_metadata() or ())}

        async def _handle_or_abort(app: str, context):
            # The route table is push-invalidated; tolerate only the
            # short deploy-to-first-poll race (bounded), then NOT_FOUND.
            for _ in range(15):
                try:
                    return outer._handle_for(app)
                except KeyError:
                    await asyncio.sleep(0.1)
            await context.abort(grpc.StatusCode.NOT_FOUND,
                                f"no application '{app}'")

        async def predict(request: bytes, context) -> bytes:
            md = _meta(context)
            app = md.get("application", "default")
            method = md.get("method", "__call__")
            handle = await _handle_or_abort(app, context)
            if md.get("multiplexed_model_id"):
                handle = handle.options(
                    multiplexed_model_id=md["multiplexed_model_id"])
            payload = _decode(request)
            args = (payload,) if payload is not None else ()
            caller = getattr(handle, method) if method != "__call__" \
                else handle
            try:
                reply = await asyncio.get_running_loop().run_in_executor(
                    None, lambda: caller.remote(*args).result(timeout=120))
            except Exception as e:  # noqa: BLE001 — surfaced as grpc error
                await context.abort(grpc.StatusCode.INTERNAL,
                                    f"{type(e).__name__}: {e}")
            return _encode(reply)

        async def predict_stream(request: bytes, context):
            md = _meta(context)
            app = md.get("application", "default")
            method = md.get("method", "__call__")
            handle = await _handle_or_abort(app, context)
            if md.get("multiplexed_model_id"):
                handle = handle.options(
                    multiplexed_model_id=md["multiplexed_model_id"])
            payload = _decode(request)
            args = (payload,) if payload is not None else ()
            shandle = handle.options(stream=True)
            caller = getattr(shandle, method) if method != "__call__" \
                else shandle
            loop = asyncio.get_running_loop()
            gen = await loop.run_in_executor(
                None, lambda: caller.remote(*args))
            it = iter(gen)
            _stop = object()

            def _next():
                try:
                    return next(it)
                except StopIteration:
                    return _stop

            while True:
                item = await loop.run_in_executor(None, _next)
                if item is _stop:
                    break
                yield _encode(item)

        def _user_method(path: str, spec):
            """Route a harvested user-proto rpc: app from metadata,
            deployment method named after the rpc, request handed over
            as the DESERIALIZED message."""
            rpc_name = path.rsplit("/", 1)[-1]

            async def unary(request, context):
                md = _meta(context)
                app = md.get("application", "default")
                handle = await _handle_or_abort(app, context)
                if md.get("multiplexed_model_id"):
                    handle = handle.options(
                        multiplexed_model_id=md["multiplexed_model_id"])
                caller = getattr(handle, rpc_name)
                try:
                    return await asyncio.get_running_loop().run_in_executor(
                        None,
                        lambda: caller.remote(request).result(timeout=120))
                except Exception as e:  # noqa: BLE001
                    await context.abort(grpc.StatusCode.INTERNAL,
                                        f"{type(e).__name__}: {e}")

            async def stream(request, context):
                md = _meta(context)
                app = md.get("application", "default")
                handle = await _handle_or_abort(app, context)
                if md.get("multiplexed_model_id"):
                    handle = handle.options(
                        multiplexed_model_id=md["multiplexed_model_id"])
                caller = getattr(handle.options(stream=True), rpc_name)
                loop = asyncio.get_running_loop()
                gen = await loop.run_in_executor(
                    None, lambda: caller.remote(request))
                it = iter(gen)
                _stop = object()

                def _next():
                    try:
                        return next(it)
                    except StopIteration:
                        return _stop

                while True:
                    item = await loop.run_in_executor(None, _next)
                    if item is _stop:
                        break
                    yield item

            if getattr(spec, "unary_stream", None) is not None:
                return grpc.unary_stream_rpc_method_handler(
                    stream,
                    request_deserializer=spec.request_deserializer,
                    response_serializer=spec.response_serializer)
            return grpc.unary_unary_rpc_method_handler(
                unary,
                request_deserializer=spec.request_deserializer,
                response_serializer=spec.response_serializer)

        class Handler(grpc.GenericRpcHandler):
            def service(self, call_details):
                if call_details.method == PREDICT:
                    return grpc.unary_unary_rpc_method_handler(predict)
                if call_details.method == PREDICT_STREAM:
                    return grpc.unary_stream_rpc_method_handler(
                        predict_stream)
                spec = outer._user_methods.get(call_details.method)
                if spec is not None:
                    return _user_method(call_details.method, spec)
                return None

        async def _main():
            server = grpc.aio.server()
            server.add_generic_rpc_handlers((Handler(),))
            bound = server.add_insecure_port(f"{host}:{port}")
            await server.start()
            self.port = bound
            started.set()
            await server.wait_for_termination()

        loop.run_until_complete(_main())

    # ---- actor api --------------------------------------------------------
    def get_user_method_paths(self):
        """The harvested user-proto rpc paths this proxy serves (lets
        serve.start_grpc detect a live proxy that lacks newly requested
        servicers and recreate it)."""
        return sorted(self._user_methods)

    def get_port(self) -> int:
        # The server thread publishes the port asynchronously; never hand
        # out None to a client that called right after creation.
        import time as _time

        deadline = _time.monotonic() + 20
        while self.port is None and _time.monotonic() < deadline:
            _time.sleep(0.05)
        return self.port

    def healthz(self) -> bool:
        return True
