"""Serve replica autoscaling policy (reference:
`serve/_private/autoscaling_policy.py` — replica-count decisions from
aggregated ongoing-request metrics, with up/downscale delays).

Two signal families feed one decision:

- handle-side ongoing-request reports (always fresh — routers push
  every ~2s straight to the controller): the reference's
  ``target_ongoing_requests`` law, ``ceil(inflight / target)``.
- the observability plane through the MetricsHub: queue-wait p95 and
  slot-utilization gauges from the replicas' LLM engines. These catch
  what inflight counts cannot — requests admitted but *queued* inside
  a replica, and decode batches running full — and they come with
  explicit staleness: a reading whose sources stopped pushing makes
  the policy HOLD rather than act on a frozen number.

The decision then passes the shared :class:`~ray_tpu.observability.
control.Hysteresis` gate (hold delays + cooldown), so an oscillating
gauge cannot flap the replica set.
"""

from __future__ import annotations

import math
import time
from typing import Any, Dict, Optional, Tuple

from ray_tpu.observability.control import Hysteresis

# Filled into every autoscaling_config by serve/api.py's spec build;
# schema.py validates user-supplied overrides against the same keys.
AUTOSCALING_DEFAULTS: Dict[str, Any] = {
    "min_replicas": 1,
    "max_replicas": 4,
    "target_ongoing_requests": 2,
    "upscale_delay_s": 2.0,
    "downscale_delay_s": 10.0,
    # Queue-wait p95 above this proposes one extra replica even when
    # inflight counts look fine (requests are aging inside replicas).
    "queue_wait_target_s": 0.5,
    # Mean batch utilization above this proposes one extra replica;
    # a saturated decode program serves at max latency.
    "slot_utilization_target": 0.9,
}


def validate_autoscaling_config(cfg: Dict[str, Any], *,
                                error_cls: type = ValueError) -> None:
    """Reject impossible autoscaling configs loudly (satellite of the
    `num_replicas="auto"` fix: a min above max used to pin silently)."""
    lo = cfg.get("min_replicas", AUTOSCALING_DEFAULTS["min_replicas"])
    hi = cfg.get("max_replicas", AUTOSCALING_DEFAULTS["max_replicas"])
    if not (isinstance(lo, int) and isinstance(hi, int)) or lo < 0:
        raise error_cls(
            f"autoscaling_config min_replicas/max_replicas must be "
            f"non-negative ints, got min_replicas={lo!r} "
            f"max_replicas={hi!r}")
    if lo > hi:
        raise error_cls(
            f"autoscaling_config min_replicas ({lo}) must be <= "
            f"max_replicas ({hi})")


class AutoscalePolicy:
    """Per-deployment desired-replica policy: signals -> clamp ->
    hysteresis gate. Pure against injected readings (unit tests feed a
    synthetic MetricsHub and clock)."""

    def __init__(self, cfg: Dict[str, Any],
                 cooldown_s: Optional[float] = None):
        self.cfg = dict(AUTOSCALING_DEFAULTS)
        self.cfg.update(cfg or {})
        validate_autoscaling_config(self.cfg)
        self.lo = self.cfg["min_replicas"]
        self.hi = self.cfg["max_replicas"]
        self.target = max(self.cfg["target_ongoing_requests"], 1e-9)
        if cooldown_s is None:
            from ray_tpu._private.config import GlobalConfig
            cooldown_s = GlobalConfig.serve_autoscale_cooldown_s
        self.gate = Hysteresis(self.cfg["upscale_delay_s"],
                               self.cfg["downscale_delay_s"],
                               cooldown_s)

    def desired(self, current: int, inflight: int, hub=None,
                now: Optional[float] = None,
                window: float = 30.0) -> Tuple[int, Dict[str, Any]]:
        """Returns (replicas to converge to, the reading that decided).

        ``hub`` is a MetricsHub (or None when the metrics plane is not
        wired); series that are *absent* just don't contribute, series
        that are *stale* hold the whole decision.
        """
        now = time.time() if now is None else now
        reading: Dict[str, Any] = {"inflight": inflight,
                                   "current": current}
        if current == 0 and self.lo > 0:
            # Bootstrap, not a scale decision: a fresh deployment goes
            # straight to min_replicas without waiting out the gate.
            reading["desired"] = self.lo
            self.gate.note_external_change(now)
            return self.lo, reading

        raw = math.ceil(inflight / self.target)
        if hub is not None:
            qwait = hub.query("serve_queue_wait_seconds", window=window)
            util = hub.query("serve_batch_utilization", window=window)
            for series in (qwait, util):
                if series and series.stale():
                    reading["held"] = "stale_metrics"
                    reading["metric"] = series.name
                    reading["age_s"] = round(series.age_s or -1.0, 2)
                    return current, reading
            if qwait and (qwait.delta() or 0) > 0:
                p95 = qwait.quantile(0.95)
                reading["queue_wait_p95_s"] = p95
                if p95 is not None and \
                        p95 > self.cfg["queue_wait_target_s"]:
                    raw = max(raw, current + 1)
            if util and util.n_series:
                u = (util.latest or 0.0) / util.n_series
                reading["slot_utilization"] = round(u, 3)
                if u > self.cfg["slot_utilization_target"]:
                    raw = max(raw, current + 1)
        want = max(self.lo, min(self.hi, max(raw, 0)))
        reading["desired"] = want
        return self.gate.propose(current, want, now), reading
