"""Shared proxy routing plane (HTTP + gRPC ingress).

One implementation of the push-invalidated route table: long-poll the
controller for route versions, cache per-app DeploymentHandles, evict
stale handles on redeploy (reference: the route table both proxy flavors
share in `serve/_private/proxy.py`)."""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

import ray_tpu


class RoutePlane:
    """Mixin for proxy actors. Call ``_init_route_plane(controller)``
    from __init__ after the serving thread is up."""

    def _pre_init_route_plane(self) -> None:
        """Call BEFORE the serving thread starts: requests that land in
        the window before _init_route_plane see empty-but-valid state
        (404s) instead of AttributeErrors."""
        self._handles: Dict[str, Any] = {}
        self._routes: Dict[str, Dict[str, Any]] = {}
        self._routes_version = -1
        self._routes_ready = threading.Event()

    def _init_route_plane(self, controller) -> None:
        if not hasattr(self, "_routes"):
            self._pre_init_route_plane()
        self._controller = controller
        threading.Thread(target=self._route_poll_loop, daemon=True,
                         name="serve-proxy-routes").start()
        # First snapshot so early requests route.
        try:
            version, routes = ray_tpu.get(
                self._controller.poll_routes.remote(-1, 0.1), timeout=30)
            self._routes_version, self._routes = version, routes
        except Exception:
            pass
        self._routes_ready.set()

    def _route_poll_loop(self) -> None:
        while True:
            try:
                version, routes = ray_tpu.get(
                    self._controller.poll_routes.remote(
                        self._routes_version, 25.0), timeout=60)
                self._routes_version = version
                self._routes = routes
                for app in set(self._handles) - set(routes):
                    self._handles.pop(app, None)
            except Exception:
                time.sleep(1.0)

    def _handle_for(self, app: str):
        from ray_tpu.serve.handle import DeploymentHandle

        route = self._routes.get(app)
        if route is None:
            raise KeyError(f"no application '{app}'")
        cached = self._handles.get(app)
        if cached is not None and cached[0] == route["deployment"]:
            return cached[1]
        # First request, or the ingress deployment was renamed by a
        # redeploy — a stale handle would route to the retired name.
        handle = DeploymentHandle(app, route["deployment"])
        self._handles[app] = (route["deployment"], handle)
        return handle

    def _lookup_handle(self, app: str, wait_s: float = 0.0):
        """Handle for `app`, or None. ``wait_s`` bounds a retry for the
        short deploy-to-first-poll race; 0 matches the HTTP proxy's
        immediate-404 behavior."""
        self._routes_ready.wait(timeout=10)
        deadline = time.monotonic() + wait_s
        while True:
            try:
                return self._handle_for(app)
            except KeyError:
                if time.monotonic() >= deadline:
                    return None
                time.sleep(0.1)
