"""ServeController — the serving control plane, as a singleton actor.

Reference: `serve/_private/controller.py:84` (deploy_application at
`:700`) + `deployment_state.py:1229`: the controller holds the goal state
(deployment specs) and a reconcile loop converges actual replica actors to
it — scaling up/down, replacing crashed replicas, and bumping a routing
version so handles/proxies refresh their replica sets.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import ray_tpu

CONTROLLER_NAME = "SERVE_CONTROLLER"


@ray_tpu.remote(num_cpus=0.5, max_concurrency=32)
class ServeController:
    def __init__(self):
        from ray_tpu.serve._private.replica import Replica

        self._replica_cls = Replica
        # app -> deployment name -> spec dict
        self._apps: Dict[str, Dict[str, Dict[str, Any]]] = {}
        # (app, deployment) -> list of replica handles
        self._replicas: Dict[tuple, List[Any]] = {}
        # (app, deployment) -> router_id -> (inflight, ts): handle-side
        # load reports driving the autoscaler.
        self._handle_metrics: Dict[tuple, Dict[str, tuple]] = {}
        # (app, deployment) -> AutoscalePolicy (hysteresis + cooldown
        # state lives inside; rebuilt when the config changes).
        self._policies: Dict[tuple, Any] = {}
        self._policy_cfgs: Dict[tuple, Any] = {}
        # (app, deployment) -> the metric reading behind the latest
        # desired-replica verdict (attached to scale decisions/events).
        self._last_reading: Dict[tuple, Dict[str, Any]] = {}
        # MetricsHub over the serve_* gauges, refreshed by the
        # bounded-period autoscale policy loop (None until first fetch).
        self._hub = None
        # (app, deployment) -> hash of the spec its replicas were built
        # from; a mismatch triggers a rolling replacement.
        self._replica_hash: Dict[tuple, str] = {}
        self._version = 0
        self._lock = threading.Lock()
        # Long-pollers park on this until the routing version bumps
        # (reference: serve LongPollHost — push-invalidated routers).
        self._version_cond = threading.Condition(self._lock)
        self._stop = threading.Event()
        threading.Thread(target=self._reconcile_loop, daemon=True,
                         name="serve-reconcile").start()
        threading.Thread(target=self._autoscale_policy_loop, daemon=True,
                         name="serve-autoscale-policy").start()

    # ------------------------------------------------------------- deploy
    def deploy_application(self, app_name: str,
                           deployments: List[Dict[str, Any]]) -> bool:
        with self._lock:
            self._apps[app_name] = {d["name"]: d for d in deployments}
        self._reconcile_once()
        return True

    def delete_application(self, app_name: str) -> bool:
        with self._lock:
            deployments = self._apps.pop(app_name, {})
            for name in deployments:
                for replica in self._replicas.pop((app_name, name), []):
                    try:
                        ray_tpu.kill(replica)
                    except Exception:
                        pass
                self._handle_metrics.pop((app_name, name), None)
                self._policies.pop((app_name, name), None)
                self._policy_cfgs.pop((app_name, name), None)
                self._last_reading.pop((app_name, name), None)
            self._version += 1
            self._version_cond.notify_all()
        return True

    # ---------------------------------------------------------- reconcile
    def _reconcile_loop(self):
        while not self._stop.is_set():
            try:
                self._reconcile_once()
            except Exception:
                pass
            self._stop.wait(1.0)

    def _reconcile_once(self):
        with self._lock:
            goal = [(app, dict(spec))
                    for app, deps in self._apps.items()
                    for spec in deps.values()]
        changed = False
        for app, spec in goal:
            key = (app, spec["name"])
            spec_hash = self._spec_hash(spec)
            # This method runs on the reconcile thread while RPC threads
            # read and delete the same maps under self._lock, so every
            # touch of shared state below happens under the lock too; the
            # slow work (health probes, spawns, drains) runs outside it
            # on local snapshots.
            with self._lock:
                if spec["name"] not in self._apps.get(app, {}):
                    continue  # deleted since the goal snapshot
                replicas = self._replicas.setdefault(key, [])
                # Rolling code update (reference: deployment_state version
                # rollout): a redeploy with different code/config retires
                # every replica built from the old spec — matching replica
                # count alone would keep serving stale code.
                retiring = []
                if replicas and self._replica_hash.get(key) != spec_hash:
                    # Old-spec replicas keep serving until the new ones
                    # exist; they drain only after the spawn loop below
                    # has refilled the replica set (no empty-routing
                    # window on redeploy).
                    retiring = list(replicas)
                    replicas.clear()
                    changed = True
                self._replica_hash[key] = spec_hash
                probe = list(replicas)
            # Drop dead replicas (health probe).
            live = []
            for r in probe:
                try:
                    ray_tpu.get(r.check_health.remote(), timeout=30)
                    live.append(r)
                except Exception:
                    changed = True
            want = self._desired_replicas(key, spec, len(live))
            if spec.get("autoscaling_config") and len(live) > 0 \
                    and want != len(live):
                self._record_scale_decision(key, len(live), want)
            spawned = []
            while len(live) + len(spawned) < want:
                options: Dict[str, Any] = dict(
                    num_cpus=spec.get("num_cpus", 1),
                    max_concurrency=spec.get("max_ongoing_requests", 8))
                if spec.get("num_tpus"):
                    options["num_tpus"] = spec["num_tpus"]
                spawned.append(self._replica_cls.options(**options).remote(
                    spec["name"], spec["serialized_callable"],
                    tuple(spec.get("init_args", ())),
                    dict(spec.get("init_kwargs", {}))))
                changed = True
            with self._lock:
                if self._replicas.get(key) is not replicas:
                    # delete_application() removed this deployment while
                    # we were probing/spawning. Nothing may be
                    # resurrected: the survivors were already killed by
                    # the delete, the fresh spawns were never routed —
                    # tear them all down and walk away.
                    retiring, doomed_list, count = [], live + spawned, None
                else:
                    replicas[:] = live + spawned
                    # Remove downscaled replicas from routing first, then
                    # drain before killing — autoscaling makes downscale
                    # routine; in-flight requests must finish (reference:
                    # graceful replica shutdown).
                    doomed_list = replicas[want:]
                    del replicas[want:]
                    if doomed_list:
                        changed = True
                    if retiring or doomed_list:
                        self._version += 1
                        self._version_cond.notify_all()
                    count = len(replicas)
            for doomed in retiring:
                self._drain_and_kill(doomed)
            for doomed in doomed_list:
                self._drain_and_kill(doomed)
            if count is None:
                continue
            try:
                from ray_tpu.observability.serve import serve_metrics
                serve_metrics().replicas.set(
                    count,
                    tags={"deployment": f"{app}/{spec['name']}"})
            except Exception:
                pass
        if changed:
            with self._lock:
                self._version += 1
                self._version_cond.notify_all()

    @staticmethod
    def _spec_hash(spec: Dict[str, Any]) -> str:
        import hashlib

        import cloudpickle

        h = hashlib.md5()
        h.update(spec.get("serialized_callable", b""))
        # cloudpickle (not repr): init args may hold DeploymentHandles,
        # whose default repr embeds a memory address — the hash must be
        # stable across identical redeploys.
        h.update(cloudpickle.dumps((spec.get("init_args"),
                                    spec.get("init_kwargs"))))
        for field in ("num_cpus", "num_tpus", "max_ongoing_requests",
                      "stream"):
            h.update(repr(spec.get(field)).encode())
        return h.hexdigest()

    def _drain_and_kill(self, replica, timeout_s: float = 10.0) -> None:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                stats = ray_tpu.get(replica.stats.remote(), timeout=10)
                if stats.get("ongoing", 0) == 0:
                    break
            except Exception:
                break
            time.sleep(0.25)
        try:
            ray_tpu.get(replica.prepare_shutdown.remote(), timeout=5)
        except Exception:
            pass
        try:
            ray_tpu.kill(replica)
        except Exception:
            pass

    # --------------------------------------------------------- autoscaling
    def record_handle_metrics(self, app_name: str, deployment_name: str,
                              router_id: str, inflight: int) -> bool:
        """Handle-side ongoing-request report (reference: handles push
        metrics the controller's autoscaler aggregates)."""
        key = (app_name, deployment_name)
        with self._lock:
            self._handle_metrics.setdefault(key, {})[router_id] = (
                inflight, time.monotonic())
        return True

    def _total_inflight(self, key: tuple) -> int:
        now = time.monotonic()
        with self._lock:
            reports = self._handle_metrics.get(key, {})
            # Routers report every ~2s; prune dead routers' entries so a
            # long-lived controller doesn't accumulate them forever.
            for rid, (_, ts) in list(reports.items()):
                if now - ts >= 10.0:
                    del reports[rid]
            return sum(v for v, _ in reports.values())

    def _desired_replicas(self, key: tuple, spec: Dict[str, Any],
                          current: int) -> int:
        # Defaults live in api.py's spec build (single source of truth);
        # specs arriving here always carry the full config.
        cfg = spec.get("autoscaling_config")
        if not cfg:
            return spec.get("num_replicas", 1)
        from ray_tpu.serve._private.autoscale import AutoscalePolicy

        # The policy maps are shared with delete_application() on the RPC
        # threads; mutate them only under the lock. policy.desired() runs
        # outside it (_total_inflight re-acquires, and the lock must stay
        # cheap for the long-pollers parked on its condition).
        with self._lock:
            policy = self._policies.get(key)
            if policy is None or self._policy_cfgs.get(key) != cfg:
                policy = AutoscalePolicy(cfg)
                self._policies[key] = policy
                self._policy_cfgs[key] = dict(cfg)
        want, reading = policy.desired(
            current, self._total_inflight(key), hub=self._hub)
        with self._lock:
            self._last_reading[key] = reading
        return want

    def _autoscale_policy_loop(self):
        """Bounded-period metrics side of the autoscaler: refresh the
        MetricsHub view of the serve_* gauges that `_desired_replicas`
        reads on the next reconcile tick. Jittered so a fleet of
        controllers never thunders the GCS in phase; separate from the
        reconcile loop so a slow GCS fetch cannot stall replica health
        probes."""
        import random

        from ray_tpu._private.config import GlobalConfig
        from ray_tpu.util.metrics import MetricsHub

        while not self._stop.is_set():
            period = max(0.25, GlobalConfig.serve_autoscale_interval_s)
            self._stop.wait(period * random.uniform(0.8, 1.2))
            if self._stop.is_set():
                return
            try:
                if self._hub is None:
                    self._hub = MetricsHub()
                self._hub.refresh(prefixes=["serve_"], force=True)
            except Exception:
                pass

    def _record_scale_decision(self, key: tuple, current: int,
                               want: int) -> None:
        """Every granted scale action is observable: decision counter,
        timeline span, typed cluster event with the triggering reading,
        and the GCS decision ring (GET /api/controller)."""
        from ray_tpu.observability.control import record_decision

        app, name = key
        with self._lock:
            reading = dict(self._last_reading.get(key, {}))
        reading.update({"app": app, "deployment": name,
                        "from": current, "to": want})
        message = (f"{app}/{name}: {current} -> {want} replicas "
                   f"(inflight={reading.get('inflight')}, "
                   f"queue_wait_p95_s={reading.get('queue_wait_p95_s')}, "
                   f"slot_utilization={reading.get('slot_utilization')})")
        try:
            if want > current:
                record_decision(
                    "serve_autoscaler", "scale_up", "load above target",
                    reading, event_type="AUTOSCALE_UP", message=message)
            else:
                record_decision(
                    "serve_autoscaler", "scale_down", "load below target",
                    reading, event_type="AUTOSCALE_DOWN", message=message)
        except Exception:
            pass

    # -------------------------------------------------------------- query
    def get_replicas(self, app_name: str, deployment_name: str):
        """Returns (version, [replica handles]) for router refresh."""
        with self._lock:
            return self._version, list(
                self._replicas.get((app_name, deployment_name), []))

    def routing_version(self) -> int:
        with self._lock:
            return self._version

    def poll_replicas(self, app_name: str, deployment_name: str,
                      known_version: int = -1, timeout_s: float = 25.0):
        """Long-poll get_replicas: replies immediately when the routing
        version moved past `known_version`, else parks until a bump or the
        window closes (reference: `long_poll.py` LongPollHost.listen)."""
        deadline = time.time() + timeout_s
        with self._version_cond:
            while self._version == known_version:
                remaining = deadline - time.time()
                if remaining <= 0:
                    break
                self._version_cond.wait(min(1.0, remaining))
            return self._version, list(
                self._replicas.get((app_name, deployment_name), []))

    def poll_routes(self, known_version: int = -1,
                    timeout_s: float = 25.0):
        """Long-poll the route table: app name -> ingress deployment."""
        deadline = time.time() + timeout_s
        with self._version_cond:
            while self._version == known_version:
                remaining = deadline - time.time()
                if remaining <= 0:
                    break
                self._version_cond.wait(min(1.0, remaining))
            routes = {}
            for app, deployments in self._apps.items():
                for name, spec in deployments.items():
                    if spec.get("is_ingress"):
                        routes[app] = {
                            "deployment": name,
                            "route_prefix": spec.get("route_prefix")
                            or f"/{app}",
                            "stream": bool(spec.get("stream")),
                            "asgi": bool(spec.get("asgi")),
                        }
            return self._version, routes

    def list_deployments(self, app_name: str) -> List[Dict[str, Any]]:
        with self._lock:
            out = []
            for name, spec in self._apps.get(app_name, {}).items():
                out.append({
                    "name": name,
                    "num_replicas": spec.get("num_replicas", 1),
                    "live_replicas": len(
                        self._replicas.get((app_name, name), [])),
                    "route_prefix": spec.get("route_prefix"),
                    "is_ingress": spec.get("is_ingress", False),
                })
            return out

    def list_applications(self) -> List[str]:
        with self._lock:
            return list(self._apps)

    def get_ingress(self, app_name: str) -> Optional[str]:
        with self._lock:
            for name, spec in self._apps.get(app_name, {}).items():
                if spec.get("is_ingress"):
                    return name
        return None

    def graceful_shutdown(self) -> bool:
        self._stop.set()
        with self._lock:
            doomed = [r for replicas in self._replicas.values()
                      for r in replicas]
            self._replicas.clear()
            self._apps.clear()
            # Wake parked long-pollers so they observe the empty tables
            # now instead of sleeping out their window.
            self._version += 1
            self._version_cond.notify_all()
        for r in doomed:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        return True


def get_or_create_controller():
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        pass
    try:
        return ServeController.options(
            name=CONTROLLER_NAME, lifetime="detached").remote()
    except Exception:
        # Raced with another creator.
        return ray_tpu.get_actor(CONTROLLER_NAME)
