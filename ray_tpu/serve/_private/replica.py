"""Replica actor — hosts one copy of a deployment's user callable.

Reference: `serve/_private/replica.py` (user callable wrapper, health
checks, graceful shutdown).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import ray_tpu


@ray_tpu.remote
class Replica:
    def __init__(self, deployment_name: str, serialized_callable: bytes,
                 init_args: tuple, init_kwargs: dict):
        import cloudpickle

        self._name = deployment_name
        cls_or_fn = cloudpickle.loads(serialized_callable)
        if isinstance(cls_or_fn, type):
            self._callable = cls_or_fn(*init_args, **init_kwargs)
            self._is_function = False
        else:
            self._callable = cls_or_fn
            self._is_function = True
        import threading

        self._num_ongoing = 0
        self._num_served = 0
        # handle_request runs on a thread pool (max_ongoing_requests ->
        # actor max_concurrency); bare += on counters would drift.
        self._stats_lock = threading.Lock()

    def handle_request(self, method_name: str, args: tuple,
                       kwargs: dict, model_id: str = "") -> Any:
        from ray_tpu.serve.multiplex import _reset_model_id, _set_model_id

        # The (method_name, args, kwargs) envelope hides the logical
        # call args from the worker's task-arg resolution, so give
        # ObjectRef elements task-arg semantics here: materialize them
        # in THIS process. This is the disagg two-hop's transfer seam —
        # the router forwards a prefill replica's result ref untouched
        # and the payload moves store-to-store, never through the
        # router.
        if any(isinstance(a, ray_tpu.ObjectRef) for a in args):
            args = tuple(ray_tpu.get(a)
                         if isinstance(a, ray_tpu.ObjectRef) else a
                         for a in args)
        if any(isinstance(v, ray_tpu.ObjectRef) for v in kwargs.values()):
            kwargs = {k: ray_tpu.get(v)
                      if isinstance(v, ray_tpu.ObjectRef) else v
                      for k, v in kwargs.items()}
        with self._stats_lock:
            self._num_ongoing += 1
        token = _set_model_id(model_id)
        try:
            if self._is_function:
                target = self._callable
            elif method_name == "__call__":
                target = self._callable
            else:
                target = getattr(self._callable, method_name)
            out = target(*args, **kwargs)
            with self._stats_lock:
                self._num_served += 1
            return out
        finally:
            _reset_model_id(token)
            with self._stats_lock:
                self._num_ongoing -= 1

    def check_health(self) -> bool:
        checker = getattr(self._callable, "check_health", None)
        if checker is not None and not self._is_function:
            checker()
        return True

    def stats(self) -> Dict[str, int]:
        return {"ongoing": self._num_ongoing, "served": self._num_served}

    def prepare_shutdown(self) -> bool:
        """User teardown hook before the controller kills this replica.
        Draining happens CALLER-side (controller._drain_and_kill polls
        stats until ongoing==0) — a replica-side wait would share the
        max_concurrency pool with handle_request and so could never run
        exactly when the replica is saturated."""
        if not self._is_function:
            hook = getattr(self._callable, "__del__", None)
            if hook is not None:
                try:
                    hook()
                except Exception:
                    pass
        return True
