"""HTTP proxy — exposes deployed applications over REST.

Reference: `serve/_private/proxy.py` (per-node ProxyActor). Stdlib
ThreadingHTTPServer (the image ships no ASGI stack): each request resolves
the app by route prefix, forwards the JSON body (or raw bytes) to the
app's ingress deployment through the same pow-2 router as Python handles,
and returns the JSON-encoded response.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict

import ray_tpu


@ray_tpu.remote(num_cpus=0.5)
class ProxyActor:
    def __init__(self, port: int = 0):
        from ray_tpu.serve._private.controller import get_or_create_controller
        from ray_tpu.serve.handle import DeploymentHandle

        self._controller = get_or_create_controller()
        self._handles: Dict[str, DeploymentHandle] = {}
        proxy = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _dispatch(self):
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    raw = self.rfile.read(length) if length else b""
                    if raw:
                        try:
                            payload = json.loads(raw)
                        except ValueError:
                            payload = raw.decode("utf-8", "replace")
                    else:
                        payload = None
                    result = proxy._route(self.path, payload)
                    body = json.dumps({"result": result}).encode()
                    self.send_response(200)
                except KeyError as e:
                    body = json.dumps({"error": str(e)}).encode()
                    self.send_response(404)
                except Exception as e:  # noqa: BLE001
                    body = json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode()
                    self.send_response(500)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            do_GET = do_POST = _dispatch

        self._server = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self.port = self._server.server_address[1]
        threading.Thread(target=self._server.serve_forever, daemon=True,
                         name="serve-proxy").start()

    def _route(self, path: str, payload: Any) -> Any:
        from ray_tpu.serve.handle import DeploymentHandle

        app_name = path.strip("/").split("/")[0] or "default"
        apps = ray_tpu.get(self._controller.list_applications.remote(),
                           timeout=30)
        if app_name not in apps:
            raise KeyError(f"no application '{app_name}'")
        ingress = ray_tpu.get(
            self._controller.get_ingress.remote(app_name), timeout=30)
        if ingress is None:
            raise KeyError(f"application '{app_name}' has no ingress")
        handle = self._handles.get(app_name)
        if handle is None:
            handle = self._handles[app_name] = DeploymentHandle(
                app_name, ingress)
        if payload is None:
            response = handle.remote()
        else:
            response = handle.remote(payload)
        return response.result(timeout=120)

    def get_port(self) -> int:
        return self.port

    def healthz(self) -> bool:
        return True
