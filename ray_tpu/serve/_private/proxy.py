"""HTTP proxy — exposes deployed applications over REST.

Reference: `serve/_private/proxy.py` (per-node ProxyActor on uvicorn).
This one runs aiohttp on a dedicated event-loop thread inside the proxy
actor: async request handling, streaming (chunked) responses for
deployments declared with ``stream=True``, and a push-invalidated route
table (long-polled from the controller) so the request hot path never
does a controller round trip.

Routing: the first path segment picks the application (``/`` -> app
"default"). JSON bodies decode to Python values; others pass through as
text.
"""

from __future__ import annotations

import asyncio
import json
import threading
from typing import Any, Dict

import ray_tpu
from ray_tpu.serve._private.route_plane import RoutePlane


@ray_tpu.remote(num_cpus=0.5, max_concurrency=16)
class ProxyActor(RoutePlane):
    def __init__(self, port: int = 0, host: str = "127.0.0.1"):
        from ray_tpu.serve._private.controller import get_or_create_controller

        self._requests_served = 0
        self._pre_init_route_plane()
        self.port = None
        started = threading.Event()
        self._host = host
        self._loop_thread = threading.Thread(
            target=self._serve_forever, args=(port, started),
            daemon=True, name="serve-proxy")
        self._loop_thread.start()
        started.wait(timeout=30)
        # Shared push-invalidated route table (route_plane.py).
        self._init_route_plane(get_or_create_controller())

    # ---- http -------------------------------------------------------------
    def _serve_forever(self, port: int, started: threading.Event):
        from aiohttp import web

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        async def handler(request: "web.Request"):
            self._requests_served += 1
            parts = request.path.strip("/").split("/", 1)
            app = parts[0] or "default"
            if request.path == "/-/healthz":
                return web.json_response({"ok": True})
            if request.path == "/-/routes":
                return web.json_response(
                    {a: r.get("route_prefix") for a, r in
                     self._routes.items()})
            self._routes_ready.wait(timeout=10)
            raw = await request.read()
            if raw:
                try:
                    payload = json.loads(raw)
                except ValueError:
                    payload = raw.decode("utf-8", "replace")
            else:
                payload = None
            route = self._routes.get(app)
            if route is None:
                return web.json_response({"error": f"no application '{app}'"},
                                         status=404)
            try:
                handle = self._handle_for(app)
            except KeyError as e:
                return web.json_response({"error": str(e)}, status=404)
            if route.get("asgi"):
                # ASGI ingress: forward the raw request; the replica
                # drives the app and returns status/headers/body
                # (reference: proxy -> ASGIAppReplicaWrapper).
                prefix = (route.get("route_prefix") or f"/{app}").rstrip("/")
                sub = request.path
                if sub.startswith(prefix):
                    sub = sub[len(prefix):] or "/"
                asgi_req = {
                    "method": request.method,
                    "path": sub,
                    "query_string": request.query_string,
                    # List of pairs, not a dict: duplicate headers
                    # (multiple Cookie/Set-Cookie) must survive the
                    # proxy->replica hop.
                    "headers": list(request.headers.items()),
                    "body": raw,
                }
                try:
                    rep = await asyncio.get_running_loop().run_in_executor(
                        None, lambda: handle.remote(asgi_req)
                        .result(timeout=120))
                except Exception as e:  # noqa: BLE001
                    return web.json_response(
                        {"error": f"{type(e).__name__}: {e}"}, status=500)
                from multidict import CIMultiDict

                hdrs = CIMultiDict()
                for k, v in (rep.get("header_list")
                             or list((rep.get("headers") or {}).items())):
                    if k.lower() not in ("content-length",
                                         "transfer-encoding"):
                        hdrs.add(k, v)
                return web.Response(
                    body=rep.get("body", b""),
                    status=rep.get("status", 200),
                    headers=hdrs)
            args = (payload,) if payload is not None else ()
            if route.get("stream"):
                return await self._stream_response(request, handle, args)
            try:
                response = await asyncio.get_running_loop().run_in_executor(
                    None,
                    lambda: handle.remote(*args).result(timeout=120))
            except Exception as e:  # noqa: BLE001 — surfaced as HTTP 500
                return web.json_response(
                    {"error": f"{type(e).__name__}: {e}"}, status=500)
            return web.json_response({"result": response})

        async def _stream(request, handle, args):
            from aiohttp import web

            resp = web.StreamResponse()
            resp.headers["Content-Type"] = "text/plain; charset=utf-8"
            await resp.prepare(request)
            loop = asyncio.get_running_loop()
            # The router blocks (replica waits, sync submission) — keep it
            # off the event loop, same as the non-streaming path.
            gen = await loop.run_in_executor(
                None, lambda: handle.options(stream=True).remote(*args))
            it = iter(gen)

            def _next():
                try:
                    return next(it)
                except StopIteration:
                    return _STOP

            while True:
                item = await loop.run_in_executor(None, _next)
                if item is _STOP:
                    break
                if isinstance(item, bytes):
                    chunk = item
                elif isinstance(item, str):
                    chunk = item.encode()
                else:
                    chunk = (json.dumps(item) + "\n").encode()
                await resp.write(chunk)
            await resp.write_eof()
            return resp

        _STOP = object()
        self._stream_response = _stream

        app = web.Application(client_max_size=256 * 1024 * 1024)
        app.router.add_route("*", "/{tail:.*}", handler)
        runner = web.AppRunner(app, access_log=None)
        loop.run_until_complete(runner.setup())
        # Loopback by default: the ingress has no authentication, so it is
        # only exposed on all interfaces when the operator explicitly asks
        # (serve.start(http_host="0.0.0.0") or proxy_location="EveryNode",
        # where cross-node traffic is the point).
        site = web.TCPSite(runner, self._host, port)
        loop.run_until_complete(site.start())
        self.port = site._server.sockets[0].getsockname()[1]
        started.set()
        loop.run_forever()

    # ---- actor api --------------------------------------------------------
    def get_port(self) -> int:
        # The aiohttp thread publishes the port asynchronously; never
        # hand out None to a client that called right after creation.
        import time as _time

        deadline = _time.monotonic() + 20
        while self.port is None and _time.monotonic() < deadline:
            _time.sleep(0.05)
        return self.port

    def healthz(self) -> bool:
        return True

    def stats(self) -> Dict[str, Any]:
        return {"requests_served": self._requests_served,
                "routes": dict(self._routes)}
