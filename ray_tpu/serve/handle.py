"""DeploymentHandle — the Python-level way to call a deployment.

Reference: `serve/handle.py` (DeploymentHandle.remote -> DeploymentResponse
with .result()); supports composition (handles passed into other
deployments rehydrate in the replica process).
"""

from __future__ import annotations

from typing import Any, Optional

import ray_tpu


class DeploymentResponse:
    def __init__(self, ref):
        self._ref = ref

    def result(self, timeout: float = 120.0) -> Any:
        return ray_tpu.get(self._ref, timeout=timeout)

    @property
    def ref(self):
        return self._ref


class DeploymentResponseGenerator:
    """Streaming response (reference: handle.options(stream=True) ->
    DeploymentResponseGenerator): iterate to receive items as the replica
    yields them."""

    def __init__(self, ref_gen):
        self._gen = ref_gen

    def __iter__(self):
        for ref in self._gen:
            yield ray_tpu.get(ref, timeout=120)

    @property
    def ref_generator(self):
        return self._gen


class _MethodCaller:
    def __init__(self, handle: "DeploymentHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._handle._call(self._method, args, kwargs)


class DeploymentHandle:
    def __init__(self, app_name: str, deployment_name: str,
                 multiplexed_model_id: str = "", stream: bool = False):
        self._app = app_name
        self._deployment = deployment_name
        self._model_id = multiplexed_model_id
        self._stream = stream
        self._router = None

    def options(self, *, multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None) -> "DeploymentHandle":
        """Request options (reference: handle.options(multiplexed_model_id=…)
        routes to a replica already holding that model;
        handle.options(stream=True) returns a DeploymentResponseGenerator
        over the replica's yielded items). Unspecified options keep the
        current handle's values — chained .options() calls compose."""
        clone = DeploymentHandle(
            self._app, self._deployment,
            self._model_id if multiplexed_model_id is None
            else multiplexed_model_id,
            self._stream if stream is None else stream)
        clone._router = self._router    # share the router + inflight view
        return clone

    def _get_router(self):
        if self._router is None:
            from ray_tpu.serve._private.controller import (
                get_or_create_controller,
            )
            from ray_tpu.serve._private.router import Router

            self._router = Router(get_or_create_controller(), self._app,
                                  self._deployment)
        return self._router

    def _call(self, method: str, args: tuple, kwargs: dict):
        ref = self._get_router().assign_request(method, args, kwargs,
                                                model_id=self._model_id,
                                                stream=self._stream)
        if self._stream:
            return DeploymentResponseGenerator(ref)
        return DeploymentResponse(ref)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return self._call("__call__", args, kwargs)

    def __getattr__(self, name: str) -> _MethodCaller:
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodCaller(self, name)

    # Handles serialize into replicas for model composition; the router is
    # process-local state and rebuilds lazily after rehydration.
    def __reduce__(self):
        return DeploymentHandle, (self._app, self._deployment,
                                  self._model_id)

    def __repr__(self):
        return f"DeploymentHandle({self._app}/{self._deployment})"
