"""Request batching (reference: `serve/batching.py` `@serve.batch`).

Coalesces concurrent single-item calls into one list-call of the wrapped
function — the TPU-relevant feature: a model replica should see a padded
batch hitting the MXU, not 16 single-row matmuls.

The replica executes requests on a thread pool (`max_ongoing_requests` →
actor max_concurrency), so batching is thread-based: the first caller to
enqueue becomes the batch leader, waits up to `batch_wait_timeout_s` for
the batch to fill, then runs the wrapped function once and distributes
results to the other callers' futures.
"""

from __future__ import annotations

import concurrent.futures
import functools
import threading
import time
from typing import Any, Callable, List, Optional


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int, wait_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._wait_s = wait_s
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._items: List[Any] = []
        self._futs: List[concurrent.futures.Future] = []

    def submit(self, bound_args: tuple, item: Any) -> Any:
        fut: concurrent.futures.Future = concurrent.futures.Future()
        with self._lock:
            self._items.append(item)
            self._futs.append(fut)
            leader = len(self._items) == 1
            if len(self._items) >= self._max:
                self._cond.notify_all()
        if leader:
            self._lead(bound_args)
        return fut.result()

    def _lead(self, bound_args: tuple) -> None:
        deadline = time.monotonic() + self._wait_s
        with self._lock:
            while len(self._items) < self._max:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
        # Keep leading until the queue drains: late arrivals past the cap
        # (or enqueued while a batch runs) form follow-up batches instead
        # of overflowing this one or stranding leaderless.
        while True:
            with self._lock:
                items = self._items[:self._max]
                futs = self._futs[:self._max]
                del self._items[:self._max]
                del self._futs[:self._max]
            if not items:
                return
            try:
                results = self._fn(*bound_args, items)
                if not isinstance(results, (list, tuple)) \
                        or len(results) != len(items):
                    raise TypeError(
                        f"@serve.batch function must return a list of "
                        f"{len(items)} results (one per input), got "
                        f"{type(results).__name__}")
                for f, r in zip(futs, results):
                    f.set_result(r)
            except Exception as e:  # noqa: BLE001
                for f in futs:
                    if not f.done():
                        f.set_exception(e)


class _BatchedCallable:
    """Descriptor so @serve.batch works on methods and free functions."""

    def __init__(self, fn: Callable, max_batch_size: int, wait_s: float):
        self._fn = fn
        self._max = max_batch_size
        self._wait_s = wait_s
        self._queues: dict = {}
        self._lock = threading.Lock()
        functools.update_wrapper(self, fn)

    def _queue_for(self, owner) -> _BatchQueue:
        key = id(owner)
        with self._lock:
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = _BatchQueue(
                    self._fn, self._max, self._wait_s)
            return q

    def __reduce__(self):
        # Class attrs ship to replicas via cloudpickle; queues and locks
        # are process-local and rebuild empty on the other side.
        return (_BatchedCallable, (self._fn, self._max, self._wait_s))

    def __call__(self, item: Any) -> Any:          # free function
        return self._queue_for(None).submit((), item)

    def __get__(self, instance, owner=None):       # bound method
        if instance is None:
            return self

        def bound(item: Any) -> Any:
            return self._queue_for(instance).submit((instance,), item)

        bound.__name__ = getattr(self._fn, "__name__", "batched")
        return bound


def batch(_func: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """`@serve.batch` — wrapped fn takes a LIST of items and returns a
    list of results of the same length; callers pass single items."""

    def wrap(fn: Callable) -> _BatchedCallable:
        return _BatchedCallable(fn, max_batch_size, batch_wait_timeout_s)

    return wrap(_func) if _func is not None else wrap
