"""ray_tpu.serve — model serving (reference: `python/ray/serve/`).

Minimal-but-real equivalent of the reference architecture: a singleton
ServeController actor reconciles deployment specs into replica actors
(`serve/_private/controller.py:84`, `deployment_state.py:1229`); the data
plane routes requests through a power-of-two-choices replica scheduler
(`replica_scheduler/pow_2_scheduler.py:44`); an HTTP proxy exposes
deployments over REST (`_private/proxy.py`). TPU-relevant: replicas can
claim TPU chips for accelerated inference (jitted model calls), while the
control plane stays on CPU.
"""

from ray_tpu.serve import asgi
from ray_tpu.serve.api import (
    Application, Deployment, delete, deployment, get_app_handle,
    list_applications, run, shutdown, start, start_grpc, status,
)
from ray_tpu.serve.asgi import ingress
from ray_tpu.serve.batching import batch
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve.schema import (
    deploy_config, deploy_config_file, import_application,
)

__all__ = [
    "Application", "Deployment", "DeploymentHandle", "asgi", "batch",
    "delete", "deploy_config", "deploy_config_file", "deployment",
    "get_app_handle", "get_multiplexed_model_id", "import_application",
    "ingress", "list_applications", "multiplexed", "run", "shutdown",
    "start", "start_grpc", "status",
]

from ray_tpu._private.usage_stats import record_library_usage as _rlu

_rlu("serve")
del _rlu
