"""ray_tpu.rllib — RL library (minimal new-API-stack equivalent).

Reference: `rllib/core/` (RLModule / Learner / LearnerGroup),
`rllib/env/single_agent_env_runner.py`, `rllib/algorithms/ppo/ppo.py`.
TPU-first: the learner update is a single pjit'd SPMD step over the learner
gang's global mesh (gradients psum over ICI), not DDP-wrapped modules.
"""

from ray_tpu.rllib.algorithms.appo import APPO, APPOConfig
from ray_tpu.rllib.algorithms.bc import BC, BCConfig
from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig
from ray_tpu.rllib.algorithms.es import ARS, ARSConfig, ES, ESConfig
from ray_tpu.rllib.algorithms.impala import IMPALA, IMPALAConfig
from ray_tpu.rllib.algorithms.marwil import MARWIL, MARWILConfig
from ray_tpu.rllib.algorithms.ppo import PPO, PPOConfig
from ray_tpu.rllib.algorithms.rainbow import Rainbow, RainbowConfig
from ray_tpu.rllib.algorithms.sac import SAC, SACConfig
from ray_tpu.rllib.algorithms.td3 import DDPG, DDPGConfig, TD3, TD3Config
from ray_tpu.rllib.core.learner_group import LearnerGroup
from ray_tpu.rllib.core.multi_rl_module import (MultiRLModule,
                                                MultiRLModuleSpec)
from ray_tpu.rllib.core.rl_module import MLPModule, RLModuleSpec
from ray_tpu.rllib.env.multi_agent_env import (MultiAgentCartPole,
                                               MultiAgentEnv,
                                               RockPaperScissors)
from ray_tpu.rllib.podracer import (InferenceServer, LearnerPool,
                                    WeightStore)

__all__ = ["APPO", "APPOConfig", "ARS", "ARSConfig", "BC", "BCConfig",
           "DQN", "DQNConfig", "ES", "ESConfig",
           "IMPALA", "IMPALAConfig", "MARWIL", "MARWILConfig",
           "PPO", "PPOConfig", "Rainbow", "RainbowConfig",
           "SAC", "SACConfig",
           "TD3", "TD3Config", "DDPG", "DDPGConfig",
           "LearnerGroup", "MLPModule", "RLModuleSpec",
           "MultiRLModule", "MultiRLModuleSpec", "MultiAgentEnv",
           "MultiAgentCartPole", "RockPaperScissors",
           "InferenceServer", "LearnerPool", "WeightStore"]

from ray_tpu._private.usage_stats import record_library_usage as _rlu

_rlu("rllib")
del _rlu
