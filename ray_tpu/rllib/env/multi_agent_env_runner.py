"""MultiAgentEnvRunner — rollout collection over agent-keyed envs.

Reference: `rllib/env/multi_agent_env_runner.py` (episodes as per-agent
streams routed through policy_mapping_fn).  TPU-first shape: instead of
ragged per-episode lists, every (env, agent) pair is a fixed LANE and the
fragment is a rectangular time-major [T, L, ...] block per module with an
explicit `mask` row — inactive lanes still flow through the batched
forward (zero obs) so shapes are static and each module's exploration
pass compiles exactly once.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.core.multi_rl_module import (MultiRLModuleSpec,
                                                default_policy_mapping_fn)
from ray_tpu.rllib.env.cartpole import make_env


@ray_tpu.remote(num_cpus=1)
class MultiAgentEnvRunner:
    def __init__(self, env_spec, multi_module_spec: MultiRLModuleSpec,
                 policy_mapping_fn: Optional[Callable[[str], str]] = None,
                 num_envs: int = 1, seed: int = 0):
        import jax

        self._cpu = jax.devices("cpu")[0]
        self._mapping = policy_mapping_fn or default_policy_mapping_fn
        self._envs = [make_env(env_spec, seed=seed * 10007 + i)
                      for i in range(num_envs)]
        agents = self._envs[0].possible_agents
        self._agents = list(agents)

        # Lane layout: per module, the ordered list of (env_idx, agent_id).
        self._lanes: Dict[str, List] = {}
        for ei in range(num_envs):
            for a in agents:
                self._lanes.setdefault(self._mapping(a), []).append((ei, a))
        self._module_ids = sorted(self._lanes)
        # env index -> [(module_id, lane_idx, agent_id)] so per-env work
        # touches only that env's lanes, not every lane of every module.
        self._env_lanes: List[List] = [[] for _ in range(num_envs)]
        for mid, lanes in self._lanes.items():
            for li, (ei, a) in enumerate(lanes):
                self._env_lanes[ei].append((mid, li, a))

        from ray_tpu.observability.jit import tracked_jit

        with jax.default_device(self._cpu):
            self._module = multi_module_spec.build()
            self._params = self._module.init(jax.random.key(seed))
            self._fwd = {mid: tracked_jit(
                self._module[mid].forward_exploration,
                name=f"ma_env_runner_fwd_{mid}")
                for mid in self._module_ids}
        self._rng = jax.random.key(seed + 1)

        # Current per-lane obs (zeros while inactive) and active flags.
        self._obs: Dict[str, np.ndarray] = {}
        self._active: Dict[str, np.ndarray] = {}
        for mid, lanes in self._lanes.items():
            dim = int(np.prod(
                self._envs[0].get_observation_space(lanes[0][1]).shape))
            self._obs[mid] = np.zeros((len(lanes), dim), np.float32)
            self._active[mid] = np.zeros(len(lanes), bool)

        self._env_return = np.zeros(num_envs, np.float32)
        self._agent_return = [dict.fromkeys(agents, 0.0)
                              for _ in range(num_envs)]
        # Agents whose episode already ended (distinct from "not acting
        # this turn" — both look inactive to the lane mask).
        self._finalized = [set() for _ in range(num_envs)]
        self._completed: List[float] = []
        self._agent_completed: Dict[str, List[float]] = {a: [] for a in agents}
        for ei, env in enumerate(self._envs):
            obs, _ = env.reset(seed=seed * 31 + ei)
            self._ingest_obs(ei, obs)

    # ------------------------------------------------------------ lane utils
    def _ingest_obs(self, env_idx: int, obs: Dict[str, np.ndarray]) -> None:
        for mid, li, a in self._env_lanes[env_idx]:
            if a in obs:
                self._obs[mid][li] = np.asarray(obs[a], np.float32).ravel()
                self._active[mid][li] = True
            else:
                self._active[mid][li] = False

    def set_weights(self, weights) -> bool:
        import jax

        with jax.default_device(self._cpu):
            self._params = jax.device_put(weights, self._cpu)
        return True

    # ---------------------------------------------------------------- sample
    def sample(self, num_steps: int) -> Dict[str, Any]:
        import jax

        bufs = {mid: {k: [] for k in ("obs", "actions", "logp", "vf",
                                      "rewards", "dones", "terminateds",
                                      "mask")}
                for mid in self._module_ids}
        # Step index of each lane's most recent recorded (mask=1) row in
        # THIS fragment — turn-based envs may deliver a reward or a
        # termination to an agent on a step it didn't act; both are
        # retro-credited to that row (cross-fragment arrivals only reach
        # the episode-return metrics, not training).
        last_rec = {mid: np.full(len(self._lanes[mid]), -1, np.int64)
                    for mid in self._module_ids}

        with jax.default_device(self._cpu):
            for step_t in range(num_steps):
                # One fixed-shape batched forward per module.
                step_out = {}
                for mid in self._module_ids:
                    self._rng, key = jax.random.split(self._rng)
                    out = self._fwd[mid](self._params[mid],
                                         self._obs[mid], key)
                    step_out[mid] = {k: np.asarray(v)
                                     for k, v in out.items()}

                # Assemble per-env action dicts from active lanes.
                act_dicts = [dict() for _ in self._envs]
                for mid, lanes in self._lanes.items():
                    acts = step_out[mid]["actions"]
                    discrete = np.issubdtype(acts.dtype, np.integer)
                    for li, (ei, a) in enumerate(lanes):
                        if self._active[mid][li]:
                            act_dicts[ei][a] = (int(acts[li]) if discrete
                                                else acts[li])

                # Record pre-step state.
                pre_active = {mid: self._active[mid].copy()
                              for mid in self._module_ids}
                for mid in self._module_ids:
                    b = bufs[mid]
                    b["obs"].append(self._obs[mid].copy())
                    b["actions"].append(step_out[mid]["actions"])
                    b["logp"].append(step_out[mid]["logp"])
                    b["vf"].append(step_out[mid]["vf"])
                    b["mask"].append(pre_active[mid].astype(np.float32))

                # Step the envs.
                rew = {mid: np.zeros(len(self._lanes[mid]), np.float32)
                       for mid in self._module_ids}
                done = {mid: np.zeros(len(self._lanes[mid]), bool)
                        for mid in self._module_ids}
                term = {mid: np.zeros(len(self._lanes[mid]), bool)
                        for mid in self._module_ids}
                for ei, env in enumerate(self._envs):
                    if not act_dicts[ei]:
                        continue
                    obs, rews, terms, truncs, _ = env.step(act_dicts[ei])
                    env_done = terms.get("__all__", False) or \
                        truncs.get("__all__", False)
                    # Fallback: an env that marks every agent done per-key
                    # without "__all__" must still end the episode, or all
                    # lanes go inactive and the env never resets.
                    if not env_done:
                        env_done = all(
                            a in self._finalized[ei]
                            or terms.get(a, False) or truncs.get(a, False)
                            for _m, _l, a in self._env_lanes[ei])
                    for mid, li, a in self._env_lanes[ei]:
                        if a in self._finalized[ei]:
                            continue
                        r = float(rews.get(a, 0.0))
                        a_done = (terms.get(a, False)
                                  or truncs.get(a, False) or env_done)
                        if pre_active[mid][li]:
                            rew[mid][li] = r
                            done[mid][li] = a_done
                            term[mid][li] = terms.get(a, False)
                            last_rec[mid][li] = step_t
                        elif a in rews or a_done:
                            # Turn-based arrival on a non-acting step:
                            # retro-credit the lane's last acted row.
                            lr = last_rec[mid][li]
                            if lr >= 0:
                                b = bufs[mid]
                                b["rewards"][lr][li] += r
                                if a_done:
                                    b["dones"][lr][li] = True
                                    b["terminateds"][lr][li] |= \
                                        terms.get(a, False)
                        else:
                            continue
                        self._env_return[ei] += r
                        self._agent_return[ei][a] += r
                        if a_done:
                            self._finalized[ei].add(a)
                            self._agent_completed[a].append(
                                self._agent_return[ei][a])
                            self._agent_return[ei][a] = 0.0
                    self._ingest_obs(ei, obs)
                    if env_done:
                        self._completed.append(float(self._env_return[ei]))
                        self._env_return[ei] = 0.0
                        self._finalized[ei].clear()
                        # Retro-credit must never cross an episode
                        # boundary: next episode's arrivals can't land on
                        # this episode's rows.
                        for mid, li, _a in self._env_lanes[ei]:
                            last_rec[mid][li] = -1
                        obs, _ = env.reset()
                        self._ingest_obs(ei, obs)

                for mid in self._module_ids:
                    b = bufs[mid]
                    b["rewards"].append(rew[mid])
                    b["dones"].append(done[mid])
                    b["terminateds"].append(term[mid])

            # Bootstrap value of each lane's current obs.
            last_vf = {}
            for mid in self._module_ids:
                self._rng, key = jax.random.split(self._rng)
                out = self._fwd[mid](self._params[mid], self._obs[mid], key)
                last_vf[mid] = np.asarray(out["vf"])

        completed, self._completed = self._completed, []
        agent_completed = {a: v for a, v in self._agent_completed.items()}
        self._agent_completed = {a: [] for a in self._agents}
        return {
            "modules": {
                mid: {**{k: np.stack(v) for k, v in bufs[mid].items()},
                      "last_vf": last_vf[mid]}
                for mid in self._module_ids
            },
            "episode_returns": completed,
            "agent_episode_returns": agent_completed,
        }
