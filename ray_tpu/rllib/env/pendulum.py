"""Pendulum — the classic continuous-control swing-up task (the standard
benchmark SAC/DDPG-family algorithms are smoke-tested on; reference:
RLlib's use of gymnasium Pendulum-v1 in `rllib/algorithms/sac/`).

Physics (textbook inverted-pendulum):
    theta'' = 3g/(2l) sin(theta) + 3/(m l^2) u
Observation: [cos theta, sin theta, theta'], action: torque in
[-2, 2], reward: -(theta^2 + 0.1 theta'^2 + 0.001 u^2), horizon 200.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ray_tpu.rllib.env.spaces import Box

_G, _M, _L, _DT = 10.0, 1.0, 1.0, 0.05
_MAX_SPEED, _MAX_TORQUE, _HORIZON = 8.0, 2.0, 200


def _angle_normalize(x: float) -> float:
    return ((x + np.pi) % (2 * np.pi)) - np.pi


class PendulumEnv:
    observation_space = Box(
        low=np.array([-1.0, -1.0, -_MAX_SPEED], np.float32),
        high=np.array([1.0, 1.0, _MAX_SPEED], np.float32))
    action_space = Box(low=np.array([-_MAX_TORQUE], np.float32),
                       high=np.array([_MAX_TORQUE], np.float32))

    def __init__(self, seed: Optional[int] = None):
        self._rng = np.random.RandomState(seed)
        self._theta = 0.0
        self._thetadot = 0.0
        self._t = 0

    def _obs(self) -> np.ndarray:
        return np.array([np.cos(self._theta), np.sin(self._theta),
                         self._thetadot], np.float32)

    def reset(self, *, seed: Optional[int] = None
              ) -> Tuple[np.ndarray, dict]:
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._theta = self._rng.uniform(-np.pi, np.pi)
        self._thetadot = self._rng.uniform(-1.0, 1.0)
        self._t = 0
        return self._obs(), {}

    def step(self, action) -> Tuple[np.ndarray, float, bool, bool, dict]:
        u = float(np.clip(np.asarray(action).reshape(-1)[0],
                          -_MAX_TORQUE, _MAX_TORQUE))
        th, thdot = self._theta, self._thetadot
        cost = (_angle_normalize(th) ** 2 + 0.1 * thdot ** 2
                + 0.001 * u ** 2)
        thdot = thdot + (3 * _G / (2 * _L) * np.sin(th)
                         + 3.0 / (_M * _L ** 2) * u) * _DT
        thdot = float(np.clip(thdot, -_MAX_SPEED, _MAX_SPEED))
        th = th + thdot * _DT
        self._theta, self._thetadot = th, thdot
        self._t += 1
        truncated = self._t >= _HORIZON
        return self._obs(), -cost, False, truncated, {}
