"""EnvRunner — rollout-collection actors.

Reference: `rllib/env/single_agent_env_runner.py` (vectorized gymnasium
envs + RLModule.forward_exploration) + `rllib/connectors/connector_v2.py`
(the env→module / module→learner pipelines the runner routes through).
Here the runner steps N env copies in lockstep with a batched CPU forward
(jax pinned to the host CPU device so a TPU-holding driver never contends
for the chip); preprocessing lives in the configured connector pipeline,
never hard-coded in the loop.

Two weight paths exist beyond the plain `set_weights` push:

- **Thin-client mode** (Sebulba, `execution="decoupled"`): constructed
  with an `inference_server`, the runner holds no current policy at
  all — `_forward` ships observations to the server's batched jitted
  forward and receives actions plus the weight version that produced
  them, which the runner stamps onto every rollout for downstream
  staleness accounting.
- **Versioned perturbations** (ES/ARS): constructed with a
  `weight_store`, `set_perturbed_weights` pulls the canonical theta
  for a published version from the channel (cached per version, so P
  perturbations cost one fetch) and regenerates its noise row locally
  from the integer seed.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.connectors import build_pipeline
from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.env.cartpole import make_env


@ray_tpu.remote(num_cpus=1)
class EnvRunner:
    def __init__(self, env_spec, module_spec: RLModuleSpec,
                 num_envs: int = 1, seed: int = 0, connectors=None,
                 inference_server=None, weight_store=None):
        import jax

        self._cpu = jax.devices("cpu")[0]
        self._server = inference_server
        self._weight_store = weight_store
        self._weight_version = 0
        self._theta_cache = None
        self._theta_version = -1
        self._envs = [make_env(env_spec, seed=seed * 10007 + i)
                      for i in range(num_envs)]
        from ray_tpu.observability.jit import tracked_jit

        with jax.default_device(self._cpu):
            self._module = module_spec.build()
            self._params = self._module.init(jax.random.key(seed))
            self._fwd = tracked_jit(self._module.forward_exploration,
                                    name="env_runner_fwd")
        self._rng = jax.random.key(seed + 1)
        self._obs = np.stack([e.reset(seed=seed * 31 + i)[0]
                              for i, e in enumerate(self._envs)])
        self._episode_returns = np.zeros(num_envs)
        self._completed: List[float] = []
        # env→module / module→learner pipeline (identity when None).
        self._pipeline = build_pipeline(connectors)
        if self._pipeline is not None:
            self._pipeline.reset(num_envs)
        self._recurrent = (self._pipeline.recurrent_stage
                           if self._pipeline is not None else None)
        if self._server is not None and self._recurrent is not None:
            raise ValueError(
                "thin-client mode cannot carry recurrent state through "
                "a shared inference server; use colocated execution "
                "for recurrent modules")
        # Lanes reset after the PREVIOUS step (carried across fragments
        # so stage state resets line up with episode boundaries).
        self._resets = np.zeros(num_envs, bool)
        self._infer = None          # lazily-jitted greedy inference
        self._seed = seed

    def set_weights(self, weights) -> bool:
        import jax

        with jax.default_device(self._cpu):
            self._params = jax.device_put(weights, self._cpu)
        return True

    def set_perturbed_weights(self, version: int, seed: int, sigma: float,
                              sign: float) -> bool:
        """ES/ARS fast path: install theta(version) + sign*sigma*eps(seed).

        The driver publishes the canonical theta ONCE per iteration
        into the versioned WeightStore channel; each runner fetches it
        once per VERSION (cached across the iteration's perturbations)
        and regenerates its noise row locally from the integer seed —
        so per perturbation only four scalars travel, instead of a
        full perturbed pytree 2*P times."""
        import jax
        from jax.flatten_util import ravel_pytree

        if self._weight_store is None:
            raise ValueError(
                "set_perturbed_weights needs the versioned weight "
                "channel; construct the runner with weight_store=...")
        if int(version) != self._theta_version:
            got, theta = self._weight_store.fetch(int(version))
            if theta is None:
                raise RuntimeError(
                    f"weight version {version} expired from the "
                    f"channel (latest {got})")
            self._theta_cache, self._theta_version = theta, int(version)
        with jax.default_device(self._cpu):
            flat, unravel = ravel_pytree(self._theta_cache)
            flat = np.asarray(flat, np.float32)
            eps = np.random.RandomState(seed).randn(
                flat.size).astype(np.float32)
            self._params = jax.device_put(
                unravel(flat + np.float32(sign * sigma) * eps), self._cpu)
        return True

    def get_connector_state(self) -> Optional[Dict[str, Any]]:
        """Pipeline state (normalizer stats, stack buffers) — for
        evaluation-side parity and checkpoint/restore."""
        return (None if self._pipeline is None
                else self._pipeline.get_state())

    def set_connector_state(self, state: Optional[Dict[str, Any]]) -> bool:
        """Adopt a training runner's pipeline state so evaluation sees the
        same normalization statistics (reference: eval workers share the
        training connectors' state)."""
        if self._pipeline is not None and state is not None:
            self._pipeline.set_state(state)
        return True

    def sample_episodes(self, num_episodes: int, explore: bool = False,
                        max_env_steps: int = 20_000) -> Dict[str, Any]:
        """Run complete fresh episodes and return their returns/lengths —
        the evaluation path (reference: `rllib/evaluation/worker_set.py`
        eval workers sample whole episodes, by default greedily).

        Greedy mode uses `forward_inference`; recurrent modules fall back
        to the exploration forward (their inference needs carried state,
        which the pipeline's recurrent stage manages on the sample path).
        """
        import jax

        n_envs = len(self._envs)
        recurrent = self._recurrent is not None and getattr(
            self._module, "is_recurrent", False)
        returns, lengths = [], []
        with jax.default_device(self._cpu):
            if self._infer is None and not recurrent:
                from ray_tpu.observability.jit import tracked_jit

                self._infer = tracked_jit(
                    self._module.forward_inference,
                    name="env_runner_infer")
            obs = np.stack([
                e.reset(seed=self._seed * 7919 + 1000 + i)[0]
                for i, e in enumerate(self._envs)])
            ep_ret = np.zeros(n_envs)
            ep_len = np.zeros(n_envs, np.int64)
            # Fresh-episode lanes: flush stack/recurrent state everywhere.
            resets = np.ones(n_envs, bool)
            steps = 0
            while len(returns) < num_episodes and steps < max_env_steps:
                if self._pipeline is None:
                    proc = obs.astype(np.float32)
                else:
                    proc = self._pipeline.env_to_module(
                        obs.astype(np.float32), resets)
                if explore or recurrent:
                    self._rng, key = jax.random.split(self._rng)
                    prev_resets, self._resets = self._resets, resets
                    out = self._forward(proc, key)
                    self._resets = prev_resets
                else:
                    out = self._infer(self._params, proc)
                actions = np.asarray(out["actions"])
                discrete = np.issubdtype(actions.dtype, np.integer)
                resets = np.zeros(n_envs, bool)
                for i, env in enumerate(self._envs):
                    act = int(actions[i]) if discrete else actions[i]
                    o, r, term, trunc, _ = env.step(act)
                    ep_ret[i] += r
                    ep_len[i] += 1
                    if term or trunc:
                        returns.append(float(ep_ret[i]))
                        lengths.append(int(ep_len[i]))
                        ep_ret[i] = 0.0
                        ep_len[i] = 0
                        o, _ = env.reset()
                        resets[i] = True
                    obs[i] = o
                steps += n_envs
            # Restore training lanes: next sample() starts from a reset.
            # pipeline.reset drains the recurrent stage's eval-time state
            # trace (else it would grow unboundedly across evaluations and
            # pollute the next training batch's state_in) and flushes
            # stack buffers; stateless stages (normalizer stats) keep
            # their statistics.
            if self._pipeline is not None:
                self._pipeline.reset(n_envs)
            # UNSEEDED resets: reseeding with the construction seeds would
            # restart training from the same few initial states after
            # every evaluation, biasing replay toward them.
            self._obs = np.stack([e.reset()[0] for e in self._envs])
            self._episode_returns[:] = 0.0
            self._resets = np.ones(n_envs, bool)
        return {"episode_returns": returns[:num_episodes],
                "episode_lengths": lengths[:num_episodes]}

    def _module_view(self, raw_obs: np.ndarray) -> np.ndarray:
        if self._pipeline is None:
            return raw_obs.astype(np.float32)
        return self._pipeline.env_to_module(
            raw_obs.astype(np.float32), self._resets)

    def _remote_forward(self, proc_obs: np.ndarray) -> Dict[str, Any]:
        """Thin-client step: one blocking round trip to the inference
        server, which coalesces concurrent runners into one batched
        jitted forward. The reply's weight_version is remembered and
        stamped onto the rollout."""
        server = self._server  # peer actor, not this runner (no self-wait)
        out = ray_tpu.get(server.infer.remote(proc_obs), timeout=300)
        self._weight_version = int(out.get("weight_version", 0))
        return out

    def _forward(self, proc_obs: np.ndarray, key):
        if self._server is not None:
            return self._remote_forward(proc_obs)
        if self._recurrent is not None and getattr(
                self._module, "is_recurrent", False):
            state_in = self._recurrent.state_for_step(
                proc_obs.shape[0], self._resets)
            out = self._fwd(self._params, proc_obs, key,
                            state_in=state_in)
            self._recurrent.observe_state_out(
                np.asarray(out["state_out"]))
            return out
        return self._fwd(self._params, proc_obs, key)

    def sample(self, num_steps: int) -> Dict[str, Any]:
        """Collect `num_steps * num_envs` transitions (fragments allowed:
        episodes are cut at the horizon and bootstrapped by the algorithm
        via the value head)."""
        import jax

        n_envs = len(self._envs)
        obs_buf, act_buf, logp_buf, rew_buf = [], [], [], []
        done_buf, term_buf, next_obs_buf, vf_buf = [], [], [], []

        with jax.default_device(self._cpu):
            for _ in range(num_steps):
                self._rng, key = jax.random.split(self._rng)
                proc_obs = self._module_view(self._obs)
                out = self._forward(proc_obs, key)
                actions = np.asarray(out["actions"])
                # Buffer the module's VIEW: the learner must train on
                # exactly what the policy saw at action time.
                obs_buf.append(proc_obs)
                act_buf.append(actions)
                logp_buf.append(np.asarray(out["logp"]))
                vf_buf.append(np.asarray(out["vf"]))

                rewards = np.zeros(n_envs, np.float32)
                dones = np.zeros(n_envs, bool)
                terms = np.zeros(n_envs, bool)
                next_obs = np.empty_like(self._obs)
                discrete = np.issubdtype(actions.dtype, np.integer)
                for i, env in enumerate(self._envs):
                    act = int(actions[i]) if discrete else actions[i]
                    obs, r, term, trunc, _ = env.step(act)
                    rewards[i] = r
                    self._episode_returns[i] += r
                    # The TRUE successor state, before any auto-reset —
                    # TD targets must bootstrap from this, never from the
                    # next episode's reset obs.
                    next_obs[i] = obs
                    terms[i] = term
                    if term or trunc:
                        dones[i] = True
                        self._completed.append(self._episode_returns[i])
                        self._episode_returns[i] = 0.0
                        obs, _ = env.reset()
                    self._obs[i] = obs
                self._resets = dones.copy()
                rew_buf.append(rewards)
                done_buf.append(dones)
                term_buf.append(terms)
                next_obs_buf.append(next_obs.copy())

            # Bootstrap value for the final observation of each env lane
            # — a PEEK through the pipeline (no stat/stack mutation).
            self._rng, key = jax.random.split(self._rng)
            last_proc = (self._obs.astype(np.float32)
                         if self._pipeline is None
                         else self._pipeline.peek(
                             self._obs.astype(np.float32)))
            if self._server is not None:
                last_out = self._remote_forward(last_proc)
            elif self._recurrent is not None and getattr(
                    self._module, "is_recurrent", False):
                # Current state, WITHOUT advancing the recorded trace.
                last_out = self._fwd(self._params, last_proc, key,
                                     state_in=self._recurrent._state)
            else:
                last_out = self._fwd(self._params, last_proc, key)
            last_vf = np.asarray(last_out["vf"])

        completed, self._completed = self._completed, []
        batch = {
            # [T, N, ...] time-major rollout fragments
            "obs": np.stack(obs_buf),
            "actions": np.stack(act_buf),
            "logp": np.stack(logp_buf),
            "rewards": np.stack(rew_buf),
            # dones = terminated | truncated (episode accounting / GAE
            # cuts); terminateds = env-true termination only (TD targets
            # bootstrap through time-limit truncations).
            "dones": np.stack(done_buf),
            "terminateds": np.stack(term_buf),
            "next_obs": np.stack(next_obs_buf),
            "vf": np.stack(vf_buf),
            "last_vf": last_vf,
            # Final observation per env lane (module view): lets value-
            # based algorithms (DQN) form next_obs for the last
            # transition of the fragment.
            "last_obs": np.asarray(last_proc),
            "episode_returns": completed,
        }
        if self._pipeline is not None:
            batch = self._pipeline.module_to_learner(batch)
        if self._server is not None:
            from ray_tpu.observability.rl import rl_metrics

            # Behavior version for downstream staleness accounting.
            batch["weight_version"] = int(self._weight_version)
            rl_metrics().env_steps.inc(num_steps * n_envs)
        return batch
