"""EnvRunner — rollout-collection actors.

Reference: `rllib/env/single_agent_env_runner.py` (vectorized gymnasium
envs + RLModule.forward_exploration). Here the runner steps N env copies in
lockstep with a batched CPU forward (jax pinned to the host CPU device so a
TPU-holding driver never contends for the chip).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.env.cartpole import make_env
from ray_tpu.rllib.core.rl_module import RLModuleSpec


@ray_tpu.remote(num_cpus=1)
class EnvRunner:
    def __init__(self, env_spec, module_spec: RLModuleSpec,
                 num_envs: int = 1, seed: int = 0):
        import jax

        self._cpu = jax.devices("cpu")[0]
        self._envs = [make_env(env_spec, seed=seed * 10007 + i)
                      for i in range(num_envs)]
        with jax.default_device(self._cpu):
            self._module = module_spec.build()
            self._params = self._module.init(jax.random.key(seed))
            self._fwd = jax.jit(self._module.forward_exploration)
        self._rng = jax.random.key(seed + 1)
        self._obs = np.stack([e.reset(seed=seed * 31 + i)[0]
                              for i, e in enumerate(self._envs)])
        self._episode_returns = np.zeros(num_envs)
        self._completed: List[float] = []

    def set_weights(self, weights) -> bool:
        import jax

        with jax.default_device(self._cpu):
            self._params = jax.device_put(weights, self._cpu)
        return True

    def sample(self, num_steps: int) -> Dict[str, Any]:
        """Collect `num_steps * num_envs` transitions (fragments allowed:
        episodes are cut at the horizon and bootstrapped by the algorithm
        via the value head)."""
        import jax

        n_envs = len(self._envs)
        obs_buf, act_buf, logp_buf, rew_buf = [], [], [], []
        done_buf, term_buf, next_obs_buf, vf_buf = [], [], [], []

        with jax.default_device(self._cpu):
            for _ in range(num_steps):
                self._rng, key = jax.random.split(self._rng)
                out = self._fwd(self._params,
                                self._obs.astype(np.float32), key)
                actions = np.asarray(out["actions"])
                obs_buf.append(self._obs.copy())
                act_buf.append(actions)
                logp_buf.append(np.asarray(out["logp"]))
                vf_buf.append(np.asarray(out["vf"]))

                rewards = np.zeros(n_envs, np.float32)
                dones = np.zeros(n_envs, bool)
                terms = np.zeros(n_envs, bool)
                next_obs = np.empty_like(self._obs)
                discrete = np.issubdtype(actions.dtype, np.integer)
                for i, env in enumerate(self._envs):
                    act = int(actions[i]) if discrete else actions[i]
                    obs, r, term, trunc, _ = env.step(act)
                    rewards[i] = r
                    self._episode_returns[i] += r
                    # The TRUE successor state, before any auto-reset —
                    # TD targets must bootstrap from this, never from the
                    # next episode's reset obs.
                    next_obs[i] = obs
                    terms[i] = term
                    if term or trunc:
                        dones[i] = True
                        self._completed.append(self._episode_returns[i])
                        self._episode_returns[i] = 0.0
                        obs, _ = env.reset()
                    self._obs[i] = obs
                rew_buf.append(rewards)
                done_buf.append(dones)
                term_buf.append(terms)
                next_obs_buf.append(next_obs.copy())

            # Bootstrap value for the final observation of each env lane.
            self._rng, key = jax.random.split(self._rng)
            last_vf = np.asarray(self._fwd(
                self._params, self._obs.astype(np.float32), key)["vf"])

        completed, self._completed = self._completed, []
        return {
            # [T, N, ...] time-major rollout fragments
            "obs": np.stack(obs_buf),
            "actions": np.stack(act_buf),
            "logp": np.stack(logp_buf),
            "rewards": np.stack(rew_buf),
            # dones = terminated | truncated (episode accounting / GAE
            # cuts); terminateds = env-true termination only (TD targets
            # bootstrap through time-limit truncations).
            "dones": np.stack(done_buf),
            "terminateds": np.stack(term_buf),
            "next_obs": np.stack(next_obs_buf),
            "vf": np.stack(vf_buf),
            "last_vf": last_vf,
            # Final observation per env lane: lets value-based algorithms
            # (DQN) form next_obs for the last transition of the fragment.
            "last_obs": self._obs.copy(),
            "episode_returns": completed,
        }
