"""Minimal observation/action spaces (gymnasium-compatible surface).

The environment image has no gymnasium; these carry exactly what the
RLModule/EnvRunner need: shapes, dtypes, and sampling.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np


@dataclasses.dataclass
class Discrete:
    n: int

    @property
    def shape(self) -> Tuple[int, ...]:
        return ()

    dtype = np.int64

    def sample(self, rng: np.random.RandomState):
        return int(rng.randint(self.n))

    def contains(self, x) -> bool:
        return 0 <= int(x) < self.n


@dataclasses.dataclass
class Box:
    low: np.ndarray
    high: np.ndarray

    def __post_init__(self):
        self.low = np.asarray(self.low, np.float32)
        self.high = np.asarray(self.high, np.float32)

    @property
    def shape(self) -> Tuple[int, ...]:
        return self.low.shape

    dtype = np.float32

    def sample(self, rng: np.random.RandomState):
        return rng.uniform(
            np.clip(self.low, -10, 10),
            np.clip(self.high, -10, 10)).astype(np.float32)

    def contains(self, x) -> bool:
        x = np.asarray(x)
        return x.shape == self.shape
