"""MultiAgentEnv — dict-keyed multi-agent environment API.

Reference: `rllib/env/multi_agent_env.py` (obs/reward/termination dicts
keyed by agent id; `possible_agents`, per-agent spaces) and the tuned
test envs `rllib/examples/envs/classes/multi_agent/` (MultiAgentCartPole,
RockPaperScissors). The contract here is the same; the implementation is
numpy-only so env runners stay importable on hosts without gymnasium.

An episode ends when every agent has terminated or truncated (the runner
resets the env then). Agents that terminate early simply stop appearing
in the obs dict; the runner masks their lanes.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ray_tpu.rllib.env.cartpole import CartPoleEnv, register_env
from ray_tpu.rllib.env.spaces import Box, Discrete

AgentID = str


class MultiAgentEnv:
    """Base class. Subclasses define `possible_agents` and per-agent
    spaces, and implement reset()/step() over agent-keyed dicts."""

    possible_agents: List[AgentID] = []

    def __init__(self):
        self.observation_spaces: Dict[AgentID, Box] = {}
        self.action_spaces: Dict[AgentID, Any] = {}

    # Per-agent space accessors (reference: get_observation_space(agent_id))
    def get_observation_space(self, agent_id: AgentID):
        return self.observation_spaces[agent_id]

    def get_action_space(self, agent_id: AgentID):
        return self.action_spaces[agent_id]

    def reset(self, *, seed: Optional[int] = None
              ) -> Tuple[Dict[AgentID, np.ndarray], Dict[AgentID, Any]]:
        raise NotImplementedError

    def step(self, action_dict: Dict[AgentID, Any]) -> Tuple[
            Dict[AgentID, np.ndarray], Dict[AgentID, float],
            Dict[AgentID, bool], Dict[AgentID, bool], Dict[AgentID, Any]]:
        """Returns (obs, rewards, terminateds, truncateds, infos), each
        keyed by the agents that acted.  The special key "__all__" in
        terminateds/truncateds signals episode end for the whole env."""
        raise NotImplementedError


class MultiAgentCartPole(MultiAgentEnv):
    """N independent CartPole lanes, one per agent (the reference's
    standard multi-agent smoke env).  Agents terminate independently; the
    episode ends when all have."""

    def __init__(self, num_agents: int = 2, seed: Optional[int] = None):
        super().__init__()
        self.possible_agents = [f"agent_{i}" for i in range(num_agents)]
        self._envs = {a: CartPoleEnv(seed=None if seed is None else seed + i)
                      for i, a in enumerate(self.possible_agents)}
        for a, e in self._envs.items():
            self.observation_spaces[a] = e.observation_space
            self.action_spaces[a] = e.action_space
        self._done: Dict[AgentID, bool] = {}

    def reset(self, *, seed=None):
        obs = {}
        for i, (a, e) in enumerate(self._envs.items()):
            obs[a], _ = e.reset(seed=None if seed is None else seed + i)
        self._done = {a: False for a in self.possible_agents}
        return obs, {}

    def step(self, action_dict):
        obs, rew, term, trunc, info = {}, {}, {}, {}, {}
        for a, act in action_dict.items():
            if self._done[a]:
                continue
            o, r, tm, tr, _ = self._envs[a].step(act)
            rew[a] = r
            term[a] = tm
            trunc[a] = tr
            if tm or tr:
                self._done[a] = True
            else:
                obs[a] = o
        done_all = all(self._done.values())
        term["__all__"] = done_all
        trunc["__all__"] = False
        return obs, rew, term, trunc, info


class RockPaperScissors(MultiAgentEnv):
    """Two-player repeated rock-paper-scissors, zero-sum (reference:
    `rllib/examples/envs/classes/multi_agent/rock_paper_scissors.py`).

    Observation: one-hot of the opponent's previous move plus a
    first-move flag -> Box(4,).  Episodes last `episode_len` steps.
    `scripted_opponent="rock"` freezes player_1 to a fixed move so tests
    can assert player_0 learns the best response (paper)."""

    WIN = {(0, 2), (1, 0), (2, 1)}   # rock>scissors, paper>rock, scissors>paper

    def __init__(self, episode_len: int = 10,
                 scripted_opponent: Optional[str] = None,
                 seed: Optional[int] = None):
        super().__init__()
        self.possible_agents = ["player_0", "player_1"]
        obs_space = Box(np.zeros(4, np.float32), np.ones(4, np.float32))
        for a in self.possible_agents:
            self.observation_spaces[a] = obs_space
            self.action_spaces[a] = Discrete(3)
        self._len = episode_len
        self._scripted = {"rock": 0, "paper": 1,
                          "scissors": 2}.get(scripted_opponent)
        self._t = 0
        self._last: Dict[AgentID, int] = {}

    def _obs(self) -> Dict[AgentID, np.ndarray]:
        out = {}
        for me, other in (("player_0", "player_1"), ("player_1", "player_0")):
            v = np.zeros(4, np.float32)
            if other in self._last:
                v[self._last[other]] = 1.0
            else:
                v[3] = 1.0
            out[me] = v
        return out

    def reset(self, *, seed=None):
        self._t = 0
        self._last = {}
        return self._obs(), {}

    def step(self, action_dict):
        a0 = int(action_dict["player_0"])
        a1 = (self._scripted if self._scripted is not None
              else int(action_dict["player_1"]))
        self._last = {"player_0": a0, "player_1": a1}
        if (a0, a1) in self.WIN:
            r0 = 1.0
        elif (a1, a0) in self.WIN:
            r0 = -1.0
        else:
            r0 = 0.0
        self._t += 1
        done = self._t >= self._len
        obs = self._obs() if not done else {}
        term = {"player_0": done, "player_1": done, "__all__": done}
        trunc = {"player_0": False, "player_1": False, "__all__": False}
        return obs, {"player_0": r0, "player_1": -r0}, term, trunc, {}


register_env("MultiAgentCartPole", MultiAgentCartPole)
register_env("RockPaperScissors", RockPaperScissors)
