from ray_tpu.rllib.env.cartpole import CartPoleEnv, make_env
from ray_tpu.rllib.env.spaces import Box, Discrete

__all__ = ["Box", "CartPoleEnv", "Discrete", "make_env"]
