"""CartPole-v1 (classic cart-pole balancing, Barto/Sutton/Anderson 1983).

Implemented from the published dynamics (the textbook Euler-integrated
equations); the environment image has no gymnasium, so this is the in-repo
regression env — same physics constants, termination bounds, and 500-step
cap as the public CartPole-v1, so published reward targets (475) apply.
Reference analog: RLlib's tuned-example envs (`rllib/tuned_examples/ppo/`).
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ray_tpu.rllib.env.spaces import Box, Discrete


class CartPoleEnv:
    GRAVITY = 9.8
    MASS_CART = 1.0
    MASS_POLE = 0.1
    HALF_POLE_LEN = 0.5
    FORCE_MAG = 10.0
    TAU = 0.02
    THETA_LIMIT = 12 * 2 * math.pi / 360
    X_LIMIT = 2.4
    MAX_STEPS = 500

    def __init__(self, seed: Optional[int] = None):
        hi = np.array([self.X_LIMIT * 2, np.finfo(np.float32).max,
                       self.THETA_LIMIT * 2, np.finfo(np.float32).max],
                      np.float32)
        self.observation_space = Box(-hi, hi)
        self.action_space = Discrete(2)
        self._rng = np.random.RandomState(seed)
        self._state: Optional[np.ndarray] = None
        self._steps = 0

    def reset(self, *, seed: Optional[int] = None
              ) -> Tuple[np.ndarray, Dict[str, Any]]:
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._state = self._rng.uniform(-0.05, 0.05, 4).astype(np.float32)
        self._steps = 0
        return self._state.copy(), {}

    def step(self, action: int
             ) -> Tuple[np.ndarray, float, bool, bool, Dict[str, Any]]:
        assert self._state is not None, "call reset() first"
        x, x_dot, theta, theta_dot = self._state
        force = self.FORCE_MAG if action == 1 else -self.FORCE_MAG
        cos_t, sin_t = math.cos(theta), math.sin(theta)
        total_mass = self.MASS_CART + self.MASS_POLE
        pole_ml = self.MASS_POLE * self.HALF_POLE_LEN

        temp = (force + pole_ml * theta_dot ** 2 * sin_t) / total_mass
        theta_acc = (self.GRAVITY * sin_t - cos_t * temp) / (
            self.HALF_POLE_LEN
            * (4.0 / 3.0 - self.MASS_POLE * cos_t ** 2 / total_mass))
        x_acc = temp - pole_ml * theta_acc * cos_t / total_mass

        x += self.TAU * x_dot
        x_dot += self.TAU * x_acc
        theta += self.TAU * theta_dot
        theta_dot += self.TAU * theta_acc
        self._state = np.array([x, x_dot, theta, theta_dot], np.float32)
        self._steps += 1

        terminated = bool(abs(x) > self.X_LIMIT
                          or abs(theta) > self.THETA_LIMIT)
        truncated = self._steps >= self.MAX_STEPS
        return self._state.copy(), 1.0, terminated, truncated, {}


def _pendulum(seed=None):
    from ray_tpu.rllib.env.pendulum import PendulumEnv

    return PendulumEnv(seed=seed)


_ENV_REGISTRY = {"CartPole-v1": CartPoleEnv, "Pendulum-v1": _pendulum}


def register_env(name: str, ctor) -> None:
    _ENV_REGISTRY[name] = ctor


def make_env(spec, seed: Optional[int] = None):
    """spec: an env id string, a constructor, or an instance factory.
    Unregistered string ids fall through to gymnasium when it is
    installed (reference: RLlib resolves env strings via gym.make —
    `rllib/env/utils.py`)."""
    if callable(spec):
        return spec()
    ctor = _ENV_REGISTRY.get(spec)
    if ctor is None:
        gym_env, gym_err = _try_gymnasium(spec, seed)
        if gym_env is not None:
            return gym_env
        raise KeyError(f"unknown env '{spec}' "
                       f"(registered: {sorted(_ENV_REGISTRY)}; "
                       f"gymnasium lookup failed: {gym_err})")
    try:
        return ctor(seed=seed)
    except TypeError:
        return ctor()


def _try_gymnasium(env_id: str, seed: Optional[int]):
    try:
        import gymnasium
    except ImportError as e:
        return None, e
    try:
        env = gymnasium.make(env_id)
    except Exception as e:
        # Keep the real reason (missing extra deps, bad version suffix…)
        # for make_env's error message.
        return None, e
    return GymnasiumEnv(env, seed=seed), None


class GymnasiumEnv:
    """Adapter: gymnasium env -> this package's env/space contract (the
    reset/step 5-tuple API is already identical; only spaces translate)."""

    def __init__(self, env, seed: Optional[int] = None):
        self._env = env
        self._seed = seed
        self.observation_space = _convert_space(env.observation_space)
        self.action_space = _convert_space(env.action_space)

    def reset(self, *, seed: Optional[int] = None):
        if seed is None:
            seed, self._seed = self._seed, None
        return self._env.reset(seed=seed)

    def step(self, action):
        return self._env.step(action)

    def close(self):
        self._env.close()


def _convert_space(space):
    from ray_tpu.rllib.env.spaces import Box, Discrete

    name = type(space).__name__
    if name == "Discrete":
        return Discrete(int(space.n))
    if name == "Box":
        return Box(np.asarray(space.low, np.float32),
                   np.asarray(space.high, np.float32))
    raise ValueError(f"unsupported gymnasium space: {space}")
