"""Connector pipelines — composable env→module / module→learner
transformations.

Reference: `rllib/connectors/connector_v2.py:1` (ConnectorV2: every
new-stack algorithm routes observations through an env-to-module
pipeline before the forward pass and batches through a module-to-learner
pipeline before the update; obs normalization, frame stacking, and
recurrent-state handling are pipeline pieces, not runner code).

Redesigned for this runtime's shape:

* A stage sees *batched lanes*: `env_to_module(obs, resets)` gets the
  [N, ...] observation of N vectorized env copies plus the lane-reset
  mask from the previous step, and returns what the RLModule should see.
  The runner buffers the TRANSFORMED observation — the learner trains on
  exactly what the policy saw at action time.
* `module_to_learner(batch)` runs once per rollout fragment on the
  [T, N, ...] time-major batch — it is where `next_obs` gets the same
  view (e.g. the frame-stack shifted by one) and where per-fragment
  statistics are frozen.
* Stages are numpy/host-side: they run in the env loop (between env.step
  and the jitted forward), so they must not trace; anything jit-worthy
  belongs in the RLModule itself.
* `transform_observation_space` lets a stage change the module's input
  space (frame stack widens it) before the module spec is built.

Stages carry state (`get_state`/`set_state`) so evaluation and restored
runners resume with the same normalizer statistics.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

__all__ = ["Connector", "ConnectorPipeline", "ObsNormalizer",
           "FrameStack", "ClipObs", "RecurrentState"]


class Connector:
    """One pipeline stage (reference: ConnectorV2)."""

    def transform_observation_space(self, space):
        return space

    def reset(self, n_envs: int) -> None:
        pass

    def env_to_module(self, obs: np.ndarray,
                      resets: Optional[np.ndarray] = None) -> np.ndarray:
        """Per-step: [N, ...] raw (or upstream-transformed) obs ->
        module view. `resets[i]` True when lane i was reset after the
        previous step."""
        return obs

    def peek(self, obs: np.ndarray) -> np.ndarray:
        """Side-effect-free module view of `obs` (the bootstrap forward
        at fragment end must not advance stacks or normalizer stats)."""
        return obs

    def module_to_learner(self, batch: Dict[str, Any]) -> Dict[str, Any]:
        """Per-fragment: [T, N, ...] time-major batch -> learner view."""
        return batch

    def get_state(self) -> Dict[str, Any]:
        return {}

    def set_state(self, state: Dict[str, Any]) -> None:
        pass


class ConnectorPipeline(Connector):
    """Ordered composition; env→module applies stages left to right,
    module→learner in the same order (each stage sees its upstream's
    output, mirroring the per-step path)."""

    def __init__(self, stages: Sequence[Connector]):
        self.stages: List[Connector] = list(stages)
        # FrameStack's module_to_learner rebuilds next_obs from the
        # ALREADY-transformed obs plus the raw successor frame — a
        # normalizer ordered AFTER the stack would then re-normalize the
        # k-1 older frames a second time (silently skewed TD targets).
        # Enforce the only sound order instead of documenting it.
        stack_idx = next((i for i, s in enumerate(self.stages)
                          if isinstance(s, FrameStack)), None)
        norm_idx = next((i for i, s in enumerate(self.stages)
                         if isinstance(s, ObsNormalizer)), None)
        if (stack_idx is not None and norm_idx is not None
                and norm_idx > stack_idx):
            raise ValueError(
                "ObsNormalizer must come BEFORE FrameStack in the "
                "pipeline: a post-stack normalizer would double-"
                "normalize the stacked history when next_obs is rebuilt")

    def transform_observation_space(self, space):
        for s in self.stages:
            space = s.transform_observation_space(space)
        return space

    def reset(self, n_envs: int) -> None:
        for s in self.stages:
            s.reset(n_envs)

    def env_to_module(self, obs, resets=None):
        for s in self.stages:
            obs = s.env_to_module(obs, resets)
        return obs

    def peek(self, obs):
        for s in self.stages:
            obs = s.peek(obs)
        return obs

    @property
    def recurrent_stage(self) -> Optional["RecurrentState"]:
        for s in self.stages:
            if isinstance(s, RecurrentState):
                return s
        return None

    def module_to_learner(self, batch):
        for s in self.stages:
            batch = s.module_to_learner(batch)
        return batch

    def get_state(self):
        return {i: s.get_state() for i, s in enumerate(self.stages)}

    def set_state(self, state):
        for i, s in enumerate(self.stages):
            if i in state or str(i) in state:
                s.set_state(state.get(i, state.get(str(i))))


class ObsNormalizer(Connector):
    """Running mean/std observation normalization (reference:
    `connectors/env_to_module/mean_std_filter.py`). Welford update on
    every env step; the fragment's `next_obs` is normalized with the
    stats frozen at fragment end."""

    def __init__(self, clip: float = 10.0, eps: float = 1e-8):
        self.clip = clip
        self.eps = eps
        self._count = 0.0
        self._mean: Optional[np.ndarray] = None
        self._m2: Optional[np.ndarray] = None

    def _update(self, x: np.ndarray) -> None:
        # Vectorized parallel-Welford merge (Chan et al.): O(1) numpy
        # calls per step — this runs in the per-step rollout hot path.
        flat = x.reshape(-1, x.shape[-1]).astype(np.float64)
        n_b = float(flat.shape[0])
        if n_b == 0:
            return
        b_mean = flat.mean(axis=0)
        b_m2 = ((flat - b_mean) ** 2).sum(axis=0)
        if self._mean is None:
            self._mean, self._m2, self._count = b_mean, b_m2, n_b
            return
        delta = b_mean - self._mean
        tot = self._count + n_b
        self._mean = self._mean + delta * (n_b / tot)
        self._m2 = self._m2 + b_m2 + delta ** 2 * (self._count * n_b / tot)
        self._count = tot

    def _norm(self, x: np.ndarray) -> np.ndarray:
        if self._mean is None or self._count < 2:
            return np.asarray(x, np.float32)
        std = np.sqrt(self._m2 / (self._count - 1)) + self.eps
        out = (x - self._mean) / std
        return np.clip(out, -self.clip, self.clip).astype(np.float32)

    def env_to_module(self, obs, resets=None):
        self._update(obs)
        return self._norm(obs)

    def peek(self, obs):
        return self._norm(obs)

    def module_to_learner(self, batch):
        # obs was normalized per step already; next_obs gets the
        # end-of-fragment stats (the off-by-a-few-steps drift is noise
        # at normal fragment lengths).
        if "next_obs" in batch:
            batch = dict(batch)
            batch["next_obs"] = self._norm(batch["next_obs"])
        return batch

    def get_state(self):
        return {"count": self._count,
                "mean": None if self._mean is None else self._mean.copy(),
                "m2": None if self._m2 is None else self._m2.copy()}

    def set_state(self, state):
        self._count = state["count"]
        self._mean = state["mean"]
        self._m2 = state["m2"]


class FrameStack(Connector):
    """Stack the last k observations along the feature axis (reference:
    `connectors/env_to_module/frame_stacking.py`). Lane buffers zero-pad
    at episode starts; `next_obs` in the learner batch is the stack
    shifted by one frame — exactly the successor view the policy would
    see."""

    def __init__(self, k: int = 4):
        if k < 2:
            raise ValueError("FrameStack needs k >= 2")
        self.k = k
        self._buf: Optional[np.ndarray] = None     # [N, k, f]
        self._feat: Optional[int] = None

    def transform_observation_space(self, space):
        import dataclasses

        f = int(np.prod(space.shape))
        self._feat = f
        # Stacked layout is frame-major ([frame0 feats, frame1 feats, ...]
        # — buf.reshape(N, k*f) below), so bounds tile whole frames.
        low = np.tile(np.asarray(space.low, np.float32).reshape(-1),
                      self.k)
        high = np.tile(np.asarray(space.high, np.float32).reshape(-1),
                       self.k)
        try:
            return dataclasses.replace(space, low=low, high=high)
        except TypeError:
            return type(space)(low=low, high=high)

    def reset(self, n_envs: int) -> None:
        self._buf = None

    def env_to_module(self, obs, resets=None):
        obs = np.asarray(obs, np.float32)
        N, f = obs.shape[0], int(np.prod(obs.shape[1:]))
        obs = obs.reshape(N, f)
        if self._buf is None or self._buf.shape[0] != N:
            self._buf = np.zeros((N, self.k, f), np.float32)
        elif resets is not None and resets.any():
            self._buf[resets] = 0.0
        self._buf = np.roll(self._buf, -1, axis=1)
        self._buf[:, -1] = obs
        # COPY, not a view: the runner buffers this array for training,
        # and next step's in-place lane-reset zeroing would otherwise
        # retroactively corrupt every episode's final stacked obs.
        return self._buf.reshape(N, self.k * f).copy()

    def peek(self, obs):
        obs = np.asarray(obs, np.float32)
        N, f = obs.shape[0], int(np.prod(obs.shape[1:]))
        obs = obs.reshape(N, f)
        buf = (np.zeros((N, self.k, f), np.float32)
               if self._buf is None or self._buf.shape[0] != N
               else self._buf)
        sim = np.roll(buf, -1, axis=1).copy()
        sim[:, -1] = obs
        return sim.reshape(N, self.k * f)

    def module_to_learner(self, batch):
        if "next_obs" not in batch:
            return batch
        batch = dict(batch)
        stacked = batch["obs"]                     # [T, N, k*f] (module view)
        nxt = np.asarray(batch["next_obs"], np.float32)
        T, N = nxt.shape[:2]
        f = int(np.prod(nxt.shape[2:]))
        nxt = nxt.reshape(T, N, f)
        # successor stack = drop oldest frame, append the true successor.
        batch["next_obs"] = np.concatenate(
            [stacked[..., f:], nxt], axis=-1)
        return batch

    def get_state(self):
        return {"buf": None if self._buf is None else self._buf.copy()}

    def set_state(self, state):
        self._buf = state["buf"]


class ClipObs(Connector):
    """Element-wise observation clipping (the simplest stage; also the
    canonical 'add a transform without touching the runner' example)."""

    def __init__(self, low: float = -10.0, high: float = 10.0):
        self.low, self.high = low, high

    def env_to_module(self, obs, resets=None):
        return np.clip(obs, self.low, self.high).astype(np.float32)

    def peek(self, obs):
        return np.clip(obs, self.low, self.high).astype(np.float32)

    def module_to_learner(self, batch):
        if "next_obs" in batch:
            batch = dict(batch)
            batch["next_obs"] = np.clip(
                batch["next_obs"], self.low, self.high).astype(np.float32)
        return batch


class RecurrentState(Connector):
    """Recurrent-state plumbing (reference: ConnectorV2's STATE_IN /
    STATE_OUT handling for RNN modules). Carries a per-lane state vector
    across steps, zeros it on episode reset, and exposes the time-major
    `state_in` tensor in the learner batch so a recurrent learner can
    replay the exact state sequence the policy acted with.

    Protocol with the module: `forward_exploration` receives the obs
    with the state CONCATENATED on the feature axis is NOT assumed —
    instead the runner consults `pipeline.recurrent_stage`: if present,
    it passes `state_in` as an extra kwarg and reads `state_out` from
    the forward output. A module advertises support via
    ``is_recurrent = True`` and ``state_size``.
    """

    def __init__(self, state_size: int):
        self.state_size = state_size
        self._state: Optional[np.ndarray] = None
        self._trace: List[np.ndarray] = []

    def reset(self, n_envs: int) -> None:
        self._state = np.zeros((n_envs, self.state_size), np.float32)
        self._trace = []

    # Runner hooks (not part of the obs path).
    def state_for_step(self, n_envs: int,
                       resets: Optional[np.ndarray]) -> np.ndarray:
        if self._state is None or self._state.shape[0] != n_envs:
            self.reset(n_envs)
        elif resets is not None and resets.any():
            self._state[resets] = 0.0
        self._trace.append(self._state.copy())
        return self._state

    def observe_state_out(self, state_out: np.ndarray) -> None:
        self._state = np.asarray(state_out, np.float32)

    def module_to_learner(self, batch):
        if self._trace:
            batch = dict(batch)
            batch["state_in"] = np.stack(self._trace)   # [T, N, d]
            self._trace = []
        return batch

    def get_state(self):
        return {"state": None if self._state is None
                else self._state.copy()}

    def set_state(self, state):
        self._state = state["state"]


def build_pipeline(connectors) -> Optional[ConnectorPipeline]:
    """None | list of stages/factories -> pipeline (factories let configs
    stay picklable without sharing stage state across runners)."""
    if not connectors:
        return None
    stages = [c() if callable(c) and not isinstance(c, Connector) else c
              for c in connectors]
    return ConnectorPipeline(stages)
