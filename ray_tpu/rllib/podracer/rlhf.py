"""The RLHF shape on the podracer plumbing: LLM policy + REINFORCE.

RLAX ("Large-Scale, Distributed Reinforcement Learning for LLMs on
TPUs", PAPERS.md) is exactly the Sebulba split with a language model
as the policy: inference servers generate tokens, a scorer assigns
rewards, a learner pool updates, and weights flow back through a
versioned channel. This module provides the minimal tier-1 version of
that loop on the llama stack:

- :class:`LLMPolicyModule` — an RLModule whose observation is a token
  context ``[B, C] int32`` and whose action is the next token over the
  model vocabulary. It drops into the InferenceServer unchanged, which
  is the point: the server batches over *rows*, not over any
  CartPole-specific structure.
- :class:`RLHFLearner` — REINFORCE with a mean-reward baseline; the
  smallest on-policy gradient that exercises sample→score→update.
- :func:`run_rlhf_smoke` — drives prompts through the full podracer
  path (InferenceServer → score → bounded queue → LearnerPool →
  WeightStore) and asserts versions advance and staleness stays
  clipped.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import RLModule, RLModuleSpec
from ray_tpu.rllib.env.spaces import Box, Discrete


class LLMPolicyModule(RLModule):
    """Next-token LLM policy over `models.llama`.

    Observations are fixed-length token contexts; ``forward_train``
    returns the last position's logits as action logits, so the
    inherited categorical ``forward_exploration`` *is* sampling the
    next token.
    """

    def __init__(self, observation_space, action_space, hidden=(),
                 config=None):
        from ray_tpu.models.llama import LlamaConfig

        self.observation_space = observation_space
        self.action_space = action_space
        self.config = config or LlamaConfig.tiny()
        if action_space.n != self.config.vocab_size:
            raise ValueError(
                f"action space ({action_space.n}) must match the model "
                f"vocab ({self.config.vocab_size})")

    def init(self, rng):
        from ray_tpu.models.llama import init_params

        return init_params(self.config, rng)

    def forward_train(self, params, obs):
        import jax.numpy as jnp

        from ray_tpu.models.llama import forward

        tokens = obs.astype(jnp.int32)
        logits = forward(params, tokens, self.config)
        last = logits[:, -1, :].astype(jnp.float32)
        return {"action_logits": last,
                "vf": jnp.zeros((last.shape[0],), jnp.float32)}


class RLHFLearner(Learner):
    """REINFORCE with a mean-reward baseline on the LLM policy."""

    def compute_loss(self, params, batch, rng):
        import jax
        import jax.numpy as jnp

        out = self.module.forward_train(params, batch["obs"])
        logits = out["action_logits"]
        logp = jax.nn.log_softmax(logits)[
            jnp.arange(logits.shape[0]), batch["actions"].astype(jnp.int32)]
        rewards = batch["rewards"].astype(jnp.float32)
        adv = rewards - jnp.mean(rewards)
        loss = -jnp.mean(logp * adv)
        return loss, {"policy_loss": loss,
                      "reward_mean": jnp.mean(rewards)}


def default_score_fn(prompts: np.ndarray, actions: np.ndarray) -> np.ndarray:
    """Stand-in reward model: prefer even token ids — trivially
    learnable, so the smoke can check the loss is live."""
    return (np.asarray(actions) % 2 == 0).astype(np.float32)


def run_rlhf_smoke(num_rounds: int = 3, batch_size: int = 8,
                   ctx_len: int = 8,
                   score_fn: Optional[Callable] = None,
                   seed: int = 0) -> dict:
    """sample→score→update through the full podracer path.

    Requires an initialized ray_tpu cluster. Returns a summary dict and
    asserts the plumbing invariants (weight versions advance, staleness
    stays within the clip, the loss is finite).
    """
    from ray_tpu.models.llama import LlamaConfig
    from ray_tpu.rllib.podracer.inference_server import InferenceServer
    from ray_tpu.rllib.podracer.learner_pool import LearnerPool, feed_queue
    from ray_tpu.rllib.podracer.weight_store import WeightStore
    from ray_tpu.util.queue import Queue

    config = LlamaConfig.tiny(vocab_size=64, dim=32, n_layers=1,
                              n_heads=2, n_kv_heads=1, hidden_dim=64,
                              max_seq_len=max(16, ctx_len))
    spec = RLModuleSpec(
        observation_space=Box(low=np.zeros(ctx_len),
                              high=np.full(ctx_len, config.vocab_size - 1)),
        action_space=Discrete(config.vocab_size),
        module_class=LLMPolicyModule,
        module_kwargs={"config": config})

    score = score_fn or default_score_fn
    staleness_clip = 4
    store = WeightStore(history=4)
    server = InferenceServer.remote(spec, weight_store=store,
                                    max_batch_rows=64,
                                    weight_poll_interval_s=0.05, seed=seed)
    queue = Queue(maxsize=4, actor_options={"max_concurrency": 8})
    pool = LearnerPool(
        RLHFLearner, spec,
        learner_config={"lr": 1e-3, "grad_clip": 1.0},
        queue=queue, weight_store=store, num_workers=1,
        staleness_clip=staleness_clip, seed=seed)

    rng = np.random.RandomState(seed)
    losses, staleness = [], []
    try:
        for _ in range(num_rounds):
            prompts = rng.randint(
                0, config.vocab_size,
                size=(batch_size, ctx_len)).astype(np.int32)
            out = ray_tpu.get(server.infer.remote(prompts), timeout=180)
            actions = np.asarray(out["actions"]).astype(np.int32)
            assert actions.shape == (batch_size,)
            rewards = np.asarray(score(prompts, actions), np.float32)
            kick = pool.kick(1)
            feed_queue(queue, {
                "obs": prompts, "actions": actions, "rewards": rewards,
                "weight_version": int(out["weight_version"]),
            }, timeout_s=5.0)
            stats = pool.join(kick, timeout=300)
            staleness.append(int(stats["max_staleness"]))
            losses.append(float(stats["last_metrics"].get(
                "loss", float("nan"))))
        final_version = store.latest_version()
        assert final_version >= 1 + num_rounds, final_version
        assert all(np.isfinite(l) for l in losses), losses
        assert max(staleness) <= staleness_clip, staleness
    finally:
        try:
            ray_tpu.get(server.shutdown.remote(), timeout=30)
        except Exception:
            pass
        ray_tpu.kill(server)
        pool.shutdown()
        queue.shutdown()
        store.shutdown()
    return {
        "rounds": num_rounds,
        "weight_version": final_version,
        "losses": losses,
        "max_staleness": max(staleness),
        "staleness_clip": staleness_clip,
    }
