"""LearnerPool: queue-fed pjit updates decoupled from acting cadence.

The learning half of the Podracer split. Workers pull sample batches
from a bounded queue and run a ``build_zero_train_step`` update —
gradients ring-reduce-scattered through ``util.collective`` primitives
(Backend.PALLAS on real TPU ICI, lax/interpret on the tier-1 CPU
path) — then publish fresh params into the versioned WeightStore
channel. Acting never waits for learning and vice versa; the queue's
bound is the only coupling (backpressure instead of OOM).

Off-policyness is explicit, IMPALA/APPO-style: each batch is stamped
with the weight version that produced its actions, the worker computes
``staleness = published_version - behavior_version``, and batches past
the configured clip are dropped and counted rather than silently
blended in.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu


def feed_queue(queue, item, timeout_s: float = 5.0,
               max_retries: int = 60) -> int:
    """Bounded blocking put: when the learner falls behind, acting
    throttles here instead of buffering without limit. Returns the
    number of Full waits endured (0 = no backpressure)."""
    from ray_tpu.observability.rl import rl_metrics
    from ray_tpu.util.queue import Full

    waits = 0
    while True:
        try:
            queue.put(item, timeout=timeout_s)
            return waits
        except Full:
            waits += 1
            rl_metrics().backpressure_waits.inc()
            if waits >= max_retries:
                raise


@ray_tpu.remote(num_cpus=1)
class _LearnerWorker:
    """One pool member: local device mesh + zero-sharded train step."""

    def __init__(self, learner_cls, module_spec, learner_config=None,
                 queue=None, weight_store=None, rank: int = 0,
                 staleness_clip: int = 4, publish_interval: int = 0,
                 update_delay_s: float = 0.0, seed: int = 0,
                 collective: str = "auto"):
        import jax

        from ray_tpu.parallel.zero import (build_zero_train_step,
                                           create_zero_state)

        learner = learner_cls(module_spec, dict(learner_config or {}))
        learner.module = module_spec.build()
        self._learner = learner
        self._queue = queue
        self._store = weight_store
        self._rank = int(rank)
        self._clip = int(staleness_clip)
        self._publish_interval = int(publish_interval)
        self._delay = float(update_delay_s)

        self._mesh = jax.make_mesh((jax.device_count(),), ("data",))
        self._n_dev = jax.device_count()
        params = learner.module.init(jax.random.key(int(seed)))
        optimizer = learner._make_optimizer()
        self._state = create_zero_state(params, optimizer, self._mesh)
        loss_rng = jax.random.key(int(seed) + 1)

        def loss_fn(p, batch):
            loss, _ = learner.compute_loss(p, batch, loss_rng)
            return loss

        self._step = build_zero_train_step(
            loss_fn, optimizer, self._mesh, collective=collective)

        self._version = 0
        if self._rank == 0 and weight_store is not None:
            self._version = weight_store.publish(self.get_weights())

        self._applied = 0
        self._dropped = 0
        self._consumed = 0
        self._max_staleness = 0
        self._staleness_hist: Dict[int, int] = {}
        self._last_metrics: Dict[str, float] = {}

        from ray_tpu.observability.goodput import (GoodputLedger,
                                                   goodput_enabled,
                                                   set_active_ledger)

        self._goodput_on = goodput_enabled()
        self._ledger = (GoodputLedger(worker=f"learner-{self._rank}")
                        if self._goodput_on else None)
        if self._ledger is not None:
            set_active_ledger(self._ledger)

    def ready(self) -> int:
        return self._version

    def get_weights(self):
        import jax

        return jax.device_get(self._state.params)

    def run_updates(self, max_updates: int,
                    idle_timeout_s: float = 10.0) -> dict:
        """Consume up to `max_updates` batches from the queue; returns
        this kick's stats. Ends early after `idle_timeout_s` with no
        work (the producer stopped or fell behind)."""
        import time

        from ray_tpu.observability.goodput import (StepPhases,
                                                   goodput_metrics,
                                                   publish_train_done)
        from ray_tpu.observability.rl import rl_metrics
        from ray_tpu.util.queue import Empty

        m = rl_metrics()
        consumed = applied = dropped = 0
        pending: List[Any] = []
        while consumed < max_updates:
            data_wait_s = 0.0
            if not pending:
                t_q = time.perf_counter()
                try:
                    got = self._queue.get(timeout=idle_timeout_s)
                except Empty:
                    break
                data_wait_s = time.perf_counter() - t_q
                # A list item is a chunk of minibatches (producers
                # amortize the queue round trip); a dict is one batch.
                pending = list(got) if isinstance(got, list) else [got]
            item = pending.pop(0)
            consumed += 1
            sp = None
            if self._goodput_on:
                sp = StepPhases(step=self._consumed + consumed,
                                worker=f"learner-{self._rank}",
                                ledger=self._ledger)
                if data_wait_s:
                    sp.add("data_wait", data_wait_s)
            behavior = int(item.pop("weight_version", self._version))
            staleness = max(0, self._version - behavior)
            self._max_staleness = max(self._max_staleness, staleness)
            self._staleness_hist[staleness] = \
                self._staleness_hist.get(staleness, 0) + 1
            m.weight_staleness.set(staleness)
            if staleness > self._clip:
                dropped += 1
                m.dropped_stale.inc()
                if sp is not None:
                    sp.finish()
                continue
            if sp is not None:
                with sp.phase("compute"):
                    if self._delay > 0:
                        time.sleep(self._delay)
                    batch = self._pad_rows(item)
                    rows = len(next(iter(batch.values())))
                    self._state, metrics = self._step(self._state, batch)
                    # np.asarray fences the device work inside the
                    # timed compute section.
                    self._last_metrics = {
                        k: float(np.asarray(v))
                        for k, v in metrics.items()}
            else:
                if self._delay > 0:
                    time.sleep(self._delay)
                batch = self._pad_rows(item)
                rows = len(next(iter(batch.values())))
                self._state, metrics = self._step(self._state, batch)
                self._last_metrics = {
                    k: float(np.asarray(v)) for k, v in metrics.items()}
            applied += 1
            m.samples.inc(rows)
            if (self._store is not None and self._publish_interval > 0
                    and applied % self._publish_interval == 0):
                if sp is not None:
                    with sp.phase("weight_publish"):
                        self._version = self._store.publish(
                            self.get_weights())
                else:
                    self._version = self._store.publish(
                        self.get_weights())
            if sp is not None:
                sp.finish()
        if self._store is not None and applied > 0:
            # End-of-kick publish: one version per kick by default, so
            # staleness counts kicks-behind, not minibatches-behind.
            t_pub = time.perf_counter()
            self._version = self._store.publish(self.get_weights())
            if self._ledger is not None:
                pub_s = time.perf_counter() - t_pub
                goodput_metrics().step_phase_seconds.observe(
                    pub_s, {"phase": "weight_publish"})
                self._ledger.book_phases({"weight_publish": pub_s})
        if self._goodput_on:
            # A kick that ends is idle, not stalled: tell the watchdog
            # to stop expecting heartbeats until the next kick reports.
            publish_train_done(f"learner-{self._rank}")
        self._consumed += consumed
        self._applied += applied
        self._dropped += dropped
        try:
            m.queue_depth.set(self._queue.qsize())
        except Exception:
            pass
        return self.stats(consumed=consumed, applied=applied,
                          dropped=dropped)

    def _pad_rows(self, batch: Dict[str, np.ndarray]):
        """Pad every leading dim up to a multiple of the local device
        count by wrapping rows — the zero step shards the batch over
        the mesh and needs an even split; wrapping keeps every real
        row in the loss."""
        rows = len(next(iter(batch.values())))
        target = int(math.ceil(rows / self._n_dev)) * self._n_dev
        if target == rows:
            return {k: np.asarray(v) for k, v in batch.items()}
        idx = np.arange(target) % rows
        return {k: np.asarray(v)[idx] for k, v in batch.items()}

    def stats(self, **kick) -> dict:
        out = {
            "worker": self._rank,
            "weight_version": self._version,
            "consumed_total": self._consumed,
            "applied_total": self._applied,
            "dropped_stale_total": self._dropped,
            "max_staleness": self._max_staleness,
            "staleness_hist": dict(self._staleness_hist),
            "last_metrics": dict(self._last_metrics),
        }
        if self._ledger is not None:
            out["goodput"] = self._ledger.snapshot()
        out.update(kick)
        return out


class LearnerPool:
    """Driver-side handle on the learner workers.

    The driver kicks a pool run *before* feeding the queue (so
    consumers exist while producers block on the bound), then joins the
    kick for merged stats: kick → feed → join.
    """

    def __init__(self, learner_cls, module_spec, learner_config=None,
                 queue=None, weight_store=None, num_workers: int = 1,
                 staleness_clip: int = 4, publish_interval: int = 0,
                 update_delay_s: float = 0.0, seed: int = 0,
                 collective: str = "auto", idle_timeout_s: float = 10.0):
        if queue is None:
            raise ValueError("LearnerPool needs the bounded sample queue")
        self._idle_timeout = float(idle_timeout_s)
        self._workers = [
            _LearnerWorker.remote(
                learner_cls, module_spec, learner_config=learner_config,
                queue=queue, weight_store=weight_store, rank=i,
                staleness_clip=staleness_clip,
                publish_interval=publish_interval,
                update_delay_s=update_delay_s, seed=seed,
                collective=collective)
            for i in range(max(1, int(num_workers)))
        ]
        ray_tpu.get([w.ready.remote() for w in self._workers], timeout=600)

    @property
    def workers(self) -> List[Any]:
        return list(self._workers)

    def kick(self, num_updates: int) -> List[Any]:
        """Start consuming: each worker takes an even share of
        `num_updates` (stragglers end on idle timeout)."""
        per = int(math.ceil(num_updates / len(self._workers)))
        return [w.run_updates.remote(per, self._idle_timeout)
                for w in self._workers]

    def join(self, refs: List[Any], timeout: float = 600.0) -> dict:
        return self._merge(ray_tpu.get(refs, timeout=timeout))

    def run(self, num_updates: int, timeout: float = 600.0) -> dict:
        return self.join(self.kick(num_updates), timeout=timeout)

    def get_weights(self):
        return ray_tpu.get(self._workers[0].get_weights.remote(),
                           timeout=120)

    def stats(self) -> dict:
        return self._merge(
            ray_tpu.get([w.stats.remote() for w in self._workers],
                        timeout=60))

    @staticmethod
    def _merge(per_worker: List[dict]) -> dict:
        merged = {
            "weight_version": max(s["weight_version"] for s in per_worker),
            "max_staleness": max(s["max_staleness"] for s in per_worker),
            "last_metrics": per_worker[0].get("last_metrics", {}),
            "staleness_hist": {},
            "workers": per_worker,
        }
        for key in ("consumed", "applied", "dropped",
                    "consumed_total", "applied_total",
                    "dropped_stale_total"):
            if any(key in s for s in per_worker):
                merged[key] = sum(s.get(key, 0) for s in per_worker)
        for s in per_worker:
            for k, v in s.get("staleness_hist", {}).items():
                k = int(k)
                merged["staleness_hist"][k] = \
                    merged["staleness_hist"].get(k, 0) + v
        return merged

    def shutdown(self) -> None:
        for w in self._workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
