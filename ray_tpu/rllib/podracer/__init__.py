"""Podracer-style decoupled RL execution (arXiv:2104.06272).

Three pieces, connected only through the object store:

- :class:`InferenceServer` — batches observations from many env
  runners into one jitted policy forward (Sebulba's actor split).
- :class:`WeightStore` — the versioned weight-publication channel:
  learners put weights once per version, subscribers pull at their own
  cadence with bounded, *measured* staleness.
- :class:`LearnerPool` — queue-fed ``build_zero_train_step`` updates
  (gradient collectives via ``util.collective``: Backend.PALLAS on
  TPU, lax/interpret on the tier-1 CPU path), decoupled from acting
  with an IMPALA/APPO-style staleness clip.

``AlgorithmConfig.training(execution="decoupled")`` wires PPO/IMPALA
onto this path; ``podracer.rlhf`` runs the same plumbing with an LLM
policy (the RLHF shape).
"""

from ray_tpu.rllib.podracer.inference_server import (  # noqa: F401
    InferenceServer,
)
from ray_tpu.rllib.podracer.learner_pool import (  # noqa: F401
    LearnerPool,
    feed_queue,
)
from ray_tpu.rllib.podracer.rlhf import (  # noqa: F401
    LLMPolicyModule,
    RLHFLearner,
    run_rlhf_smoke,
)
from ray_tpu.rllib.podracer.weight_store import WeightStore  # noqa: F401

__all__ = [
    "InferenceServer", "LearnerPool", "WeightStore", "feed_queue",
    "LLMPolicyModule", "RLHFLearner", "run_rlhf_smoke",
]
