"""WeightStore: the versioned weight-publication channel.

Podracer (arXiv:2104.06272) decouples acting from learning by letting
weights flow through the object store instead of synchronous
``set_weights`` fan-outs: the learner puts a weight pytree ONCE per
version, registers the (version, ref) pair with a tiny registry actor,
and every subscriber — inference servers, env runners, evaluators —
pulls at its own cadence. Off-policyness stops being implicit: every
consumer knows exactly which version produced its behavior, and the
learner pool can clip on it.

The registry never touches weight bytes. Publishers ``ray_tpu.put``
the pytree and ship the ref wrapped in a list — nested ObjectRefs
serialize portably *without* being resolved (only top-level task args
resolve), so the actor stores a pointer, not a copy. Subscribers fetch
the wrapped ref and resolve it from the object store themselves: the
put-once broadcast ES/ARS used ad hoc, generalized and versioned.
"""

from __future__ import annotations

import collections
import time
from typing import Any, Optional, Tuple

import ray_tpu


# max_concurrency matters: the default of 1 would let a single blocked
# wait_version() hold the actor's only concurrency slot and deadlock
# the publisher it is waiting for.
@ray_tpu.remote(num_cpus=0, max_concurrency=64)
class _WeightStoreActor:
    """Version registry. Stores wrapped ObjectRefs, never weight bytes."""

    def __init__(self, history: int = 4):
        import asyncio

        self._history = max(1, int(history))
        self._wrapped: "collections.OrderedDict[int, Any]" = \
            collections.OrderedDict()
        self._latest = 0
        self._published_total = 0
        self._new_version = asyncio.Event()

    async def publish(self, wrapped, version: Optional[int] = None) -> int:
        import asyncio

        if version is None:
            version = self._latest + 1
        if version <= self._latest:
            # Late publisher lost a race; versions stay monotonic.
            return self._latest
        self._wrapped[version] = wrapped
        self._latest = version
        self._published_total += 1
        while len(self._wrapped) > self._history:
            self._wrapped.popitem(last=False)
        ev, self._new_version = self._new_version, asyncio.Event()
        ev.set()
        return version

    async def wait_version(self, min_version: int,
                           timeout: Optional[float] = None) -> int:
        """Block until latest >= min_version (or timeout); returns the
        latest version either way."""
        import asyncio

        loop = asyncio.get_running_loop()
        deadline = loop.time() + (3600.0 if timeout is None else timeout)
        while self._latest < int(min_version):
            remaining = deadline - loop.time()
            if remaining <= 0:
                break
            try:
                await asyncio.wait_for(self._new_version.wait(), remaining)
            except asyncio.TimeoutError:
                break
        return self._latest

    async def fetch(self, version: Optional[int] = None):
        """(version, wrapped_ref) for an exact version, or the latest
        when version is None. (0, None) if absent/expired."""
        v = self._latest if version is None else int(version)
        wrapped = self._wrapped.get(v)
        if wrapped is None:
            return 0, None
        return v, wrapped

    async def latest_version(self) -> int:
        return self._latest

    async def stats(self) -> dict:
        return {
            "latest_version": self._latest,
            "published_total": self._published_total,
            "history": self._history,
            "versions_held": list(self._wrapped.keys()),
        }


class WeightStore:
    """Client for the versioned weight channel; picklable, so one
    instance can be handed to runners, servers and learners alike.

    Publishers pin their most recent refs locally: the registry holds
    refs it received by value, so the originals here keep the objects
    alive for consumers mid-fetch even after the registry trims its
    history window.
    """

    def __init__(self, history: Optional[int] = None, _actor=None):
        if _actor is not None:
            self._actor = _actor
        else:
            if history is None:
                from ray_tpu._private.config import GlobalConfig

                history = GlobalConfig.rl_weight_history
            self._actor = _WeightStoreActor.remote(int(history))
        self._pinned: collections.deque = collections.deque(maxlen=8)

    @property
    def actor(self):
        return self._actor

    def publish(self, weights: Any, version: Optional[int] = None) -> int:
        """Put `weights` once and advance the channel; returns the
        assigned version."""
        from ray_tpu.observability.rl import rl_metrics

        t0 = time.perf_counter()
        ref = ray_tpu.put(weights)
        self._pinned.append(ref)
        v = ray_tpu.get(self._actor.publish.remote([ref], version),
                        timeout=60)
        m = rl_metrics()
        m.weight_version.set(v)
        m.publish_seconds.observe(time.perf_counter() - t0)
        return int(v)

    def latest_version(self) -> int:
        return int(ray_tpu.get(self._actor.latest_version.remote(),
                               timeout=60))

    def fetch(self, version: Optional[int] = None
              ) -> Tuple[int, Optional[Any]]:
        """(version, weights) — latest when version is None; (0, None)
        when nothing is published or the version expired."""
        v, wrapped = ray_tpu.get(self._actor.fetch.remote(version),
                                 timeout=60)
        if not wrapped:
            return 0, None
        return int(v), ray_tpu.get(wrapped[0], timeout=60)

    def poll(self, have_version: int = 0,
             timeout: Optional[float] = None
             ) -> Tuple[int, Optional[Any]]:
        """Block until a version newer than `have_version` exists (or
        timeout). Returns (new_version, weights), or
        (have_version, None) on timeout."""
        v = ray_tpu.get(
            self._actor.wait_version.remote(int(have_version) + 1, timeout),
            timeout=(timeout or 3600) + 30)
        if v <= have_version:
            return have_version, None
        return self.fetch()

    def stats(self) -> dict:
        return ray_tpu.get(self._actor.stats.remote(), timeout=60)

    def shutdown(self) -> None:
        ray_tpu.kill(self._actor)
