"""InferenceServer: batched policy forwards for many env runners.

Sebulba (Podracer, arXiv:2104.06272) splits the actor half of RL into
cheap environment steppers and a dedicated inference server that owns
accelerator devices: runners ship observations, the server coalesces
them into one jitted ``forward_exploration`` and scatters actions
back. One compiled program amortized over every runner replaces N
per-runner forwards — the same economics as the serve/llm engine's
continuous batching, in miniature.

Batching borrows the engine's two tricks directly: a short gather
window so concurrent submitters land in the same batch, and
power-of-two row buckets so the jit cache stays bounded (the engine
buckets batch slots for the same reason). Because the server only sees
an observation array and an RLModule, an LLM policy module
(``podracer.rlhf.LLMPolicyModule``) drops in unchanged — observations
become token contexts, which is the RLHF shape.

Weights arrive through the versioned WeightStore channel: a jittered
poll loop installs new versions at the server's own cadence and stamps
every reply with the version that produced it, so downstream staleness
accounting is exact.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np

import ray_tpu


# max_concurrency must allow many concurrent infer() awaiters; the
# default of 1 would serialize submissions and nothing would ever
# batch.
@ray_tpu.remote(num_cpus=1, max_concurrency=256)
class InferenceServer:
    def __init__(self, module_spec, weight_store=None,
                 max_batch_rows: int = 256,
                 batch_wait_s: Optional[float] = None,
                 weight_poll_interval_s: Optional[float] = None,
                 seed: int = 0):
        import jax

        from ray_tpu._private.config import GlobalConfig

        self._module = module_spec.build()
        self._params = self._module.init(jax.random.key(seed))
        from ray_tpu.observability.jit import tracked_jit

        self._fwd = tracked_jit(self._module.forward_exploration,
                                name="inference_server_fwd")
        self._rng = jax.random.key(seed + 1)

        self._store = weight_store
        self._version = 0
        if weight_store is not None:
            v, weights = weight_store.fetch()
            if weights is not None:
                self._params, self._version = weights, v

        self._batch_wait = (GlobalConfig.rl_infer_batch_wait_s
                            if batch_wait_s is None else float(batch_wait_s))
        self._poll_interval = (
            GlobalConfig.rl_weight_poll_interval_s
            if weight_poll_interval_s is None
            else float(weight_poll_interval_s))
        self._max_rows = max(1, int(max_batch_rows))
        buckets, b = [], 1
        while b < self._max_rows:
            buckets.append(b)
            b *= 2
        buckets.append(self._max_rows)
        self._buckets = buckets

        self._pending: list = []
        self._last_take = 1  # adaptive gather target (see _batcher_loop)
        self._wake = None  # asyncio.Event; created on the actor loop
        self._tasks: list = []
        self._started = False
        self._stopped = False
        self._stats = {
            "requests": 0, "rows": 0, "batches": 0, "padded_rows": 0,
            "max_requests_per_batch": 0, "max_rows_per_batch": 0,
            "bucket_counts": {}, "weight_pulls": 0, "stale_pulls": 0,
            "poll_errors": 0, "last_poll_error": None,
        }

    # ------------------------------------------------------------------
    # Request path
    # ------------------------------------------------------------------

    async def infer(self, obs) -> dict:
        """Submit one observation batch [n, ...]; resolves to numpy
        {"actions", "logp", "vf", "weight_version"} slices of the
        coalesced forward."""
        import asyncio

        loop = asyncio.get_running_loop()
        self._ensure_started(loop)
        fut = loop.create_future()
        self._pending.append((np.asarray(obs), fut))
        self._wake.set()
        return await fut

    def _ensure_started(self, loop):
        import asyncio

        if self._started:
            return
        self._started = True
        self._wake = asyncio.Event()
        self._tasks.append(loop.create_task(self._batcher_loop()))
        if self._store is not None:
            self._tasks.append(
                loop.create_task(self._weight_poll_control_loop()))

    async def _batcher_loop(self):
        import asyncio

        loop = asyncio.get_running_loop()
        while not self._stopped:
            self._wake.clear()
            if not self._pending:
                try:
                    await asyncio.wait_for(self._wake.wait(), 0.5)
                except asyncio.TimeoutError:
                    pass
                continue
            # Gather window: let concurrent submitters join this batch,
            # but stop as soon as as many requests as the previous batch
            # coalesced have arrived — the steady-state submitter count.
            # A fixed sleep would tax every acting round the full window
            # even after everyone is already here.
            deadline = loop.time() + self._batch_wait
            while len(self._pending) < self._last_take:
                remaining = deadline - loop.time()
                if remaining <= 0:
                    break
                self._wake.clear()
                try:
                    await asyncio.wait_for(self._wake.wait(), remaining)
                except asyncio.TimeoutError:
                    break
            take, rows = [], 0
            while self._pending:
                n = len(self._pending[0][0])
                if take and rows + n > self._max_rows:
                    break
                o, f = self._pending.pop(0)
                take.append((o, f))
                rows += n
            self._last_take = len(take)
            try:
                outs = await loop.run_in_executor(
                    None, self._forward_batch, [o for o, _ in take])
            except Exception as exc:  # surface to every waiter
                for _, fut in take:
                    if not fut.done():
                        fut.set_exception(RuntimeError(str(exc)))
                continue
            for out, (_, fut) in zip(outs, take):
                if not fut.done():
                    fut.set_result(out)

    def _forward_batch(self, obs_list):
        import jax

        from ray_tpu.observability.rl import rl_metrics

        rows = np.concatenate(obs_list, axis=0)
        n = rows.shape[0]
        bucket = next((b for b in self._buckets if b >= n), n)
        if bucket > n:
            pad = np.zeros((bucket - n,) + rows.shape[1:], rows.dtype)
            rows = np.concatenate([rows, pad], axis=0)
        self._rng, key = jax.random.split(self._rng)
        out = self._fwd(self._params, rows, key)
        actions = np.asarray(out["actions"])[:n]
        logp = np.asarray(out["logp"])[:n]
        vf = np.asarray(out["vf"])[:n]

        s = self._stats
        s["requests"] += len(obs_list)
        s["rows"] += n
        s["batches"] += 1
        s["padded_rows"] += bucket - n
        s["max_requests_per_batch"] = max(s["max_requests_per_batch"],
                                          len(obs_list))
        s["max_rows_per_batch"] = max(s["max_rows_per_batch"], n)
        s["bucket_counts"][bucket] = s["bucket_counts"].get(bucket, 0) + 1
        m = rl_metrics()
        m.infer_requests.inc(len(obs_list))
        m.infer_batches.inc()
        m.infer_batch_rows.set(n)

        outs, lo = [], 0
        version = self._version
        for o in obs_list:
            k = len(o)
            outs.append({
                "actions": actions[lo:lo + k],
                "logp": logp[lo:lo + k],
                "vf": vf[lo:lo + k],
                "weight_version": version,
            })
            lo += k
        return outs

    # ------------------------------------------------------------------
    # Weight channel
    # ------------------------------------------------------------------

    async def _weight_poll_control_loop(self):
        import asyncio
        import random

        while not self._stopped:
            await asyncio.sleep(
                self._poll_interval * random.uniform(0.8, 1.2))
            try:
                latest = await self._store.actor.latest_version.remote()
                if latest <= self._version:
                    continue
                v, wrapped = await self._store.actor.fetch.remote(None)
                if not wrapped:
                    continue
                # Nested refs are shipped unresolved; awaiting one
                # resolves it through the in-loop async get path.
                weights = await wrapped[0]
                if v <= self._version:
                    # A direct set_weights() push landed during the
                    # two awaits above: the fetch is stale, drop it.
                    continue
                self._install(weights, v)
            except Exception as exc:
                # Registry restart or transient RPC failure: the next
                # jittered tick retries. Kept visible in stats() so a
                # wedged channel is diagnosable, not silent.
                self._stats["poll_errors"] += 1
                self._stats["last_poll_error"] = repr(exc)
                continue

    def _install(self, weights, version: int) -> bool:
        import jax

        version = int(version)
        if version <= self._version:
            # Versions only move forward: an install racing a newer
            # push (out-of-order RPCs, a poll fetch that lost the race
            # to set_weights) must not roll the server back to stale
            # params stamped with a lower version.
            self._stats["stale_pulls"] += 1
            return False
        self._params = jax.device_put(weights)
        self._version = version
        self._stats["weight_pulls"] += 1
        return True

    async def set_weights(self, weights, version: Optional[int] = None):
        """Direct push path for store-less setups (tests, eval)."""
        self._install(weights, self._version + 1 if version is None
                      else version)
        return self._version

    # ------------------------------------------------------------------
    # Introspection / lifecycle
    # ------------------------------------------------------------------

    def stats(self) -> dict:
        out = dict(self._stats)
        out["bucket_counts"] = dict(self._stats["bucket_counts"])
        out["weight_version"] = self._version
        out["pending"] = len(self._pending)
        return out

    def weight_version(self) -> int:
        return self._version

    async def shutdown(self) -> bool:
        self._stopped = True
        if self._wake is not None:
            self._wake.set()
        for t in self._tasks:
            t.cancel()
        return True
