"""Action distributions (reference: `rllib/models/distributions.py` +
`torch/torch_distributions.py` — Categorical / DiagGaussian behind one
logp/entropy/sample interface so losses are action-space agnostic).

Pure jnp functions over batch-leading arrays — usable inside jit on
either execution tier (TPU learner, CPU env runner).
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp


class Categorical:
    def __init__(self, logits: jax.Array):
        self.logits = logits

    def sample(self, rng: jax.Array) -> jax.Array:
        return jax.random.categorical(rng, self.logits)

    def logp(self, actions: jax.Array) -> jax.Array:
        logp_all = jax.nn.log_softmax(self.logits)
        return jnp.take_along_axis(
            logp_all, actions.astype(jnp.int32)[..., None], -1)[..., 0]

    def entropy(self) -> jax.Array:
        logp_all = jax.nn.log_softmax(self.logits)
        return -(jnp.exp(logp_all) * logp_all).sum(-1)

    def deterministic_sample(self) -> jax.Array:
        return jnp.argmax(self.logits, -1)


class DiagGaussian:
    """Independent normal per action dim; logp sums over dims."""

    def __init__(self, mean: jax.Array, log_std: jax.Array):
        self.mean = mean
        self.log_std = jnp.broadcast_to(log_std, mean.shape)

    def sample(self, rng: jax.Array) -> jax.Array:
        return self.mean + jnp.exp(self.log_std) * \
            jax.random.normal(rng, self.mean.shape)

    def logp(self, actions: jax.Array) -> jax.Array:
        var = jnp.exp(2 * self.log_std)
        ll = -0.5 * ((actions - self.mean) ** 2 / var
                     + 2 * self.log_std + jnp.log(2 * jnp.pi))
        return ll.sum(-1)

    def entropy(self) -> jax.Array:
        return (self.log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e)).sum(-1)

    def deterministic_sample(self) -> jax.Array:
        return self.mean


def dist_from_outputs(out: Dict[str, jax.Array]):
    """Build the right distribution from a module's forward_train output:
    discrete modules emit `action_logits`, continuous ones emit
    `action_mean` + `action_log_std`."""
    if "action_logits" in out:
        return Categorical(out["action_logits"])
    return DiagGaussian(out["action_mean"], out["action_log_std"])
