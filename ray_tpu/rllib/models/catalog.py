"""Model catalog — default network selection from spaces + model_config.

Reference: `rllib/models/catalog.py` (`ModelCatalog.get_model_v2` /
the new-stack `rllib/core/models/catalog.py`: obs space + action space +
model_config -> encoder + heads).  Selection rules mirrored here:

- 3-D Box obs (H, W, C)  -> CNN encoder (`conv_filters`)
- 1-D Box obs            -> MLP encoder (`fcnet_hiddens`)
- Discrete action        -> categorical logits head
- Box action             -> diagonal-Gaussian head (mean + log_std)

All modules are actor-critic (policy head + vf head) so every algorithm
in the repo can consume them.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.core.rl_module import RLModule, RLModuleSpec
from ray_tpu.rllib.env.spaces import Box, Discrete
from ray_tpu.rllib.models.distributions import DiagGaussian

DEFAULT_MODEL_CONFIG: Dict[str, Any] = {
    "fcnet_hiddens": (64, 64),
    # (out_channels, kernel, stride) triples; default = the classic
    # Atari-ish stack scaled for small inputs.
    "conv_filters": ((16, 4, 2), (32, 3, 2)),
    "conv_fc_hidden": 128,
}


class Catalog:
    @staticmethod
    def get_module_spec(observation_space, action_space,
                        model_config: Optional[Dict[str, Any]] = None
                        ) -> RLModuleSpec:
        cfg = {**DEFAULT_MODEL_CONFIG, **(model_config or {})}
        obs_ndim = len(observation_space.shape)
        if obs_ndim == 3:
            cls = (CNNModule if isinstance(action_space, Discrete)
                   else _unsupported(observation_space, action_space))
            builder = lambda o, a, h: cls(o, a, cfg)          # noqa: E731
        elif isinstance(action_space, Discrete):
            from ray_tpu.rllib.core.rl_module import MLPModule

            builder = lambda o, a, h: MLPModule(              # noqa: E731
                o, a, cfg["fcnet_hiddens"])
        elif isinstance(action_space, Box):
            builder = lambda o, a, h: GaussianMLPModule(      # noqa: E731
                o, a, cfg["fcnet_hiddens"])
        else:
            _unsupported(observation_space, action_space)
        return RLModuleSpec(observation_space=observation_space,
                            action_space=action_space,
                            hidden=cfg["fcnet_hiddens"],
                            module_class=_BuilderClass(builder))


def _unsupported(obs_space, act_space):
    raise ValueError(f"no default model for obs={obs_space} "
                     f"act={act_space}")


class _BuilderClass:
    """Adapter: RLModuleSpec.build calls module_class(obs, act, hidden);
    this lets the catalog capture model_config in a closure while staying
    spec-pickleable (cloudpickle serializes the closure)."""

    def __init__(self, builder):
        self._builder = builder

    def __call__(self, obs_space, act_space, hidden):
        return self._builder(obs_space, act_space, hidden)


class CNNModule(RLModule):
    """Conv encoder + categorical policy/vf heads for image observations
    (reference: the catalog's default vision network).  Channels-last
    NHWC — the layout XLA prefers on TPU."""

    def __init__(self, observation_space: Box, action_space: Discrete,
                 cfg: Dict[str, Any]):
        import flax.linen as nn

        h, w, c = observation_space.shape
        n_actions = action_space.n
        filters = tuple(cfg["conv_filters"])
        fc = int(cfg["conv_fc_hidden"])

        class _Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                for (ch, k, s) in filters:
                    x = nn.relu(nn.Conv(ch, (k, k), strides=(s, s))(x))
                x = x.reshape((x.shape[0], -1))
                x = nn.relu(nn.Dense(fc)(x))
                logits = nn.Dense(
                    n_actions,
                    kernel_init=nn.initializers.normal(0.01))(x)
                vf = nn.Dense(1)(x)
                return logits, vf[..., 0]

        self._net = _Net()
        self._shape = (h, w, c)

    def init(self, rng):
        dummy = jnp.zeros((1,) + self._shape, jnp.float32)
        return self._net.init(rng, dummy)

    def forward_train(self, params, obs):
        # Runners flatten obs rows; restore the image layout.
        obs = obs.reshape((obs.shape[0],) + self._shape)
        logits, vf = self._net.apply(params, obs)
        return {"action_logits": logits, "vf": vf}


class GaussianMLPModule(RLModule):
    """MLP actor-critic with a diagonal-Gaussian head for Box actions
    (state-independent log_std parameter, the reference default)."""

    def __init__(self, observation_space: Box, action_space: Box,
                 hidden: Sequence[int] = (64, 64)):
        import flax.linen as nn

        obs_dim = int(np.prod(observation_space.shape))
        act_dim = int(np.prod(action_space.shape))

        class _Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                h = x
                for width in hidden:
                    h = nn.tanh(nn.Dense(width)(h))
                mean = nn.Dense(
                    act_dim,
                    kernel_init=nn.initializers.normal(0.01))(h)
                log_std = self.param(
                    "log_std", nn.initializers.zeros, (act_dim,))
                hv = x
                for width in hidden:
                    hv = nn.tanh(nn.Dense(width)(hv))
                vf = nn.Dense(1)(hv)
                return mean, log_std, vf[..., 0]

        self._net = _Net()
        self._obs_dim = obs_dim

    def init(self, rng):
        dummy = jnp.zeros((1, self._obs_dim), jnp.float32)
        return self._net.init(rng, dummy)

    def forward_train(self, params, obs):
        mean, log_std, vf = self._net.apply(params, obs)
        return {"action_mean": mean, "action_log_std": log_std, "vf": vf}

    def forward_inference(self, params, obs):
        out = self.forward_train(params, obs)
        return {"actions": out["action_mean"]}

    def forward_exploration(self, params, obs, rng):
        out = self.forward_train(params, obs)
        dist = DiagGaussian(out["action_mean"], out["action_log_std"])
        actions = dist.sample(rng)
        return {"actions": actions, "logp": dist.logp(actions),
                "vf": out["vf"]}
