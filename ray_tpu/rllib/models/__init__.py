"""ray_tpu.rllib.models — model catalog + action distributions.

Reference: `rllib/models/` (catalog.py, distributions).
"""

from ray_tpu.rllib.models.catalog import (Catalog, CNNModule,
                                          GaussianMLPModule)
from ray_tpu.rllib.models.distributions import (Categorical, DiagGaussian,
                                                dist_from_outputs)

__all__ = ["Catalog", "CNNModule", "GaussianMLPModule",
           "Categorical", "DiagGaussian", "dist_from_outputs"]
