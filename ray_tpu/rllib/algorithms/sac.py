"""SAC — soft actor-critic for continuous control.

Reference: `rllib/algorithms/sac/sac.py` (off-policy training_step over a
replay buffer) and `sac/sac_learner.py` (twin-Q + squashed-Gaussian actor
+ entropy autotuning). TPU-first shape: actor, both critics, their target
copies, and log_alpha live in ONE state pytree; the whole SAC update —
critic + actor + alpha losses, one optimizer step, polyak target
averaging — is a single jitted, donated call (`post_update_state` runs
the polyak inside the same XLA program, so targets never round-trip to
host).
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.dqn import ReplayBuffer
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import RLModule
from ray_tpu.rllib.env.spaces import Box

_LOG_STD_MIN, _LOG_STD_MAX = -5.0, 2.0


class SACModule(RLModule):
    """Squashed-Gaussian actor + twin Q critics over flax.linen."""

    def __init__(self, observation_space: Box, action_space: Box,
                 hidden: Sequence[int] = (64, 64)):
        import flax.linen as nn

        obs_dim = int(np.prod(observation_space.shape))
        act_dim = int(np.prod(action_space.shape))
        self._act_scale = np.asarray(action_space.high,
                                     np.float32).reshape(-1)

        class _Actor(nn.Module):
            @nn.compact
            def __call__(self, x):
                h = x
                for width in hidden:
                    h = nn.relu(nn.Dense(width)(h))
                mean = nn.Dense(act_dim)(h)
                log_std = jnp.clip(nn.Dense(act_dim)(h),
                                   _LOG_STD_MIN, _LOG_STD_MAX)
                return mean, log_std

        class _Critic(nn.Module):
            @nn.compact
            def __call__(self, obs, act):
                h = jnp.concatenate([obs, act], axis=-1)
                for width in hidden:
                    h = nn.relu(nn.Dense(width)(h))
                return nn.Dense(1)(h)[..., 0]

        self._actor, self._critic = _Actor(), _Critic()
        self._obs_dim, self._act_dim = obs_dim, act_dim

    def init(self, rng: jax.Array) -> Any:
        k_pi, k_q1, k_q2 = jax.random.split(rng, 3)
        obs = jnp.zeros((1, self._obs_dim), jnp.float32)
        act = jnp.zeros((1, self._act_dim), jnp.float32)
        return {
            "actor": self._actor.init(k_pi, obs),
            "q1": self._critic.init(k_q1, obs, act),
            "q2": self._critic.init(k_q2, obs, act),
            "log_alpha": jnp.asarray(0.0, jnp.float32),
        }

    # -------------------------------------------------------------- policy
    def forward_inference(self, params, obs):
        """Deterministic eval action: squashed mean (the base class's
        argmax-over-action_logits default has no meaning for a
        continuous policy)."""
        mean, _ = self._actor.apply(params["actor"], obs)
        return {"actions": jnp.tanh(mean) * self._act_scale}

    def sample_action(self, actor_params, obs, rng):
        """Reparameterized tanh-Gaussian sample -> (action, logp)."""
        mean, log_std = self._actor.apply(actor_params, obs)
        std = jnp.exp(log_std)
        eps = jax.random.normal(rng, mean.shape)
        pre = mean + std * eps
        act = jnp.tanh(pre)
        # logp under the squashed density: N(pre) - log|d tanh/d pre|
        logp_gauss = (-0.5 * (eps ** 2 + 2 * log_std
                              + jnp.log(2 * jnp.pi))).sum(-1)
        logp = logp_gauss - jnp.log1p(-act ** 2 + 1e-6).sum(-1)
        return act * self._act_scale, logp

    def q_values(self, params, obs, act):
        return (self._critic.apply(params["q1"], obs, act),
                self._critic.apply(params["q2"], obs, act))

    # ------------------------------------------------- env-runner protocol
    def forward_exploration(self, params, obs, rng):
        act, logp = self.sample_action(params["actor"], obs, rng)
        return {"actions": act, "logp": logp,
                "vf": jnp.zeros(obs.shape[0], jnp.float32)}

    def forward_train(self, params, obs):
        mean, _ = self._actor.apply(params["actor"], obs)
        act = jnp.tanh(mean) * self._act_scale
        return {"actions": act}


class SACLearner(Learner):
    def init_extra_state(self, params) -> Dict[str, Any]:
        return {"target": {
            "q1": jax.tree.map(jnp.copy, params["q1"]),
            "q2": jax.tree.map(jnp.copy, params["q2"]),
        }}

    def compute_loss_from_state(self, state, batch, rng):
        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        target_entropy = cfg["target_entropy"]
        params, target = state["params"], state["target"]
        m: SACModule = self.module
        k_next, k_pi = jax.random.split(rng)
        alpha = jnp.exp(params["log_alpha"])
        alpha_sg = jax.lax.stop_gradient(alpha)

        # --- critic loss: y = r + gamma (min target-Q(s', a') - a logp')
        a_next, logp_next = m.sample_action(
            jax.lax.stop_gradient(params["actor"]), batch["next_obs"],
            k_next)
        tq1 = m._critic.apply(target["q1"], batch["next_obs"], a_next)
        tq2 = m._critic.apply(target["q2"], batch["next_obs"], a_next)
        y = batch["rewards"] + gamma * (
            1.0 - batch["dones"].astype(jnp.float32)) * (
            jnp.minimum(tq1, tq2) - alpha_sg * logp_next)
        y = jax.lax.stop_gradient(y)
        q1, q2 = m.q_values(params, batch["obs"], batch["actions"])
        critic_loss = ((q1 - y) ** 2).mean() + ((q2 - y) ** 2).mean()

        # --- actor loss: alpha logp - min Q (critic frozen)
        a_pi, logp_pi = m.sample_action(params["actor"], batch["obs"], k_pi)
        frozen = jax.lax.stop_gradient(
            {"q1": params["q1"], "q2": params["q2"]})
        fq1, fq2 = m.q_values(frozen, batch["obs"], a_pi)
        actor_loss = (alpha_sg * logp_pi - jnp.minimum(fq1, fq2)).mean()

        # --- alpha loss: autotune toward target entropy
        alpha_loss = -(params["log_alpha"] * jax.lax.stop_gradient(
            logp_pi + target_entropy)).mean()

        loss = critic_loss + actor_loss + alpha_loss
        return loss, {"critic_loss": critic_loss,
                      "actor_loss": actor_loss,
                      "alpha": alpha,
                      "entropy": -logp_pi.mean(),
                      "q1_mean": q1.mean()}

    def post_update_state(self, state):
        tau = self.config.get("tau", 0.005)
        polyak = lambda t, o: (1.0 - tau) * t + tau * o  # noqa: E731
        new_target = {
            "q1": jax.tree.map(polyak, state["target"]["q1"],
                               state["params"]["q1"]),
            "q2": jax.tree.map(polyak, state["target"]["q2"],
                               state["params"]["q2"]),
        }
        return {**state, "target": new_target}


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.env = "Pendulum-v1"
        self.lr = 3e-4
        self.grad_clip = 10.0
        self.tau = 0.005
        self.buffer_capacity = 100_000
        self.learning_starts = 1000
        self.train_batch_size = 256
        self.rollout_fragment_length = 32
        self.num_updates_per_iteration = 64
        self.target_entropy = None     # default: -act_dim

    algo_class = property(lambda self: SAC)


class SAC(Algorithm):
    learner_class = SACLearner
    rl_module_class = SACModule

    def __init__(self, config: SACConfig):
        super().__init__(config)
        act_space = self.module_spec.action_space
        self._buffer = ReplayBuffer(
            config.buffer_capacity,
            self.module_spec.observation_space.shape,
            action_shape=act_space.shape, action_dtype=np.float32)
        self._rng = np.random.RandomState(config.seed)
        self._env_steps = 0
        self._updates = 0

    def _learner_config(self) -> Dict[str, Any]:
        out = super()._learner_config()
        cfg = self.config
        act_dim = int(np.prod(self.module_spec.action_space.shape))
        out["gamma"] = cfg.gamma
        out["tau"] = cfg.tau
        out["target_entropy"] = (cfg.target_entropy
                                 if cfg.target_entropy is not None
                                 else -float(act_dim))
        return out

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        rollouts = self.sample_batch(cfg.rollout_fragment_length)
        for ro in rollouts:
            T, N = ro["actions"].shape[:2]
            self._env_steps += T * N
            flat = lambda a: a.reshape(T * N, *a.shape[2:])  # noqa: E731
            # terminateds (not dones): TD targets bootstrap through
            # time-limit truncations; next_obs is the true successor.
            self._buffer.add_batch(flat(ro["obs"]), flat(ro["actions"]),
                                   flat(ro["rewards"]),
                                   flat(ro["next_obs"]),
                                   flat(ro["terminateds"]))

        metrics: Dict[str, Any] = {"env_steps": self._env_steps,
                                   "buffer_size": len(self._buffer)}
        if len(self._buffer) >= cfg.learning_starts:
            for _ in range(cfg.num_updates_per_iteration):
                batch = self._buffer.sample(cfg.train_batch_size, self._rng)
                metrics.update(self.learner_group.update(batch))
                self._updates += 1
        self._sync_weights()
        metrics["num_gradient_updates"] = self._updates
        return metrics
