"""Algorithm base + config builder.

Reference: `rllib/algorithms/algorithm.py` (Algorithm is a Tune Trainable
whose `train()` runs one `training_step`) and `algorithm_config.py` (fluent
builder: .environment().training().env_runners().learners()).
"""

from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.env.cartpole import make_env
from ray_tpu.rllib.env.env_runner import EnvRunner
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.jax_backend import JaxConfig


class AlgorithmConfig:
    def __init__(self):
        self.env = "CartPole-v1"
        self.lr = 3e-4
        self.gamma = 0.99
        self.grad_clip = 0.5
        self.train_batch_size = 2048
        self.num_env_runners = 1
        self.num_envs_per_runner = 4
        # env->module / module->learner connector pipeline: a list of
        # Connector stages or zero-arg factories (reference:
        # config.env_runners(env_to_module_connector=...) over
        # ConnectorV2). Factories keep the config picklable and give
        # each runner its own stage state.
        self.connectors = None
        self.num_learners = 1
        self.jax_platform: Optional[str] = None
        self.module_hidden = (64, 64)
        # Extra catalog model_config (conv_filters etc.,
        # `models/catalog.py`); None -> defaults.
        self.model_config = None
        self.seed = 0
        # Episode-return smoothing window (reference:
        # metrics_num_episodes_for_smoothing).
        self.metrics_episode_window = 100
        # Multi-agent (reference: algorithm_config.multi_agent()).
        # policies: {module_id: RLModuleSpec | None} — None means "probe
        # spaces from an agent mapped to this module".
        self.policies = None
        self.policy_mapping_fn = None
        # Evaluation workers (reference: algorithm_config.evaluation() +
        # `rllib/evaluation/worker_set.py`): a dedicated runner fleet
        # samples whole episodes greedily every `evaluation_interval`
        # training iterations.
        self.evaluation_interval = None
        self.evaluation_num_env_runners = 1
        self.evaluation_duration = 5          # episodes per evaluation
        self.evaluation_explore = False
        # Decoupled (Podracer/Sebulba) execution — "colocated" keeps
        # the classic per-runner forward + synchronous LearnerGroup;
        # "decoupled" splits acting onto InferenceServers and learning
        # onto a queue-fed LearnerPool joined by the versioned
        # WeightStore channel. None-valued knobs fall back to the
        # GlobalConfig rl_* entries at build time.
        self.execution = "colocated"
        self.num_inference_servers = 1
        self.inference_max_batch_rows = 256
        self.inference_batch_wait_s = None
        self.weight_poll_interval_s = None
        self.sample_queue_maxsize = None
        self.staleness_clip = None
        self.weight_publish_interval = 0      # 0 = once per learner kick
        self.learner_update_delay_s = 0.0     # test hook: slow learner
        self.weight_history = None

    # fluent builder sections (reference algorithm_config.py style)
    def environment(self, env) -> "AlgorithmConfig":
        self.env = env
        return self

    def training(self, **kwargs) -> "AlgorithmConfig":
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise AttributeError(f"unknown training option '{k}'")
            setattr(self, k, v)
        return self

    def env_runners(self, num_env_runners: int = None,
                    num_envs_per_runner: int = None,
                    connectors=None) -> "AlgorithmConfig":
        if connectors is not None:
            self.connectors = connectors
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_runner is not None:
            self.num_envs_per_runner = num_envs_per_runner
        return self

    def learners(self, num_learners: int = None,
                 jax_platform: str = None) -> "AlgorithmConfig":
        if num_learners is not None:
            self.num_learners = num_learners
        if jax_platform is not None:
            self.jax_platform = jax_platform
        return self

    def multi_agent(self, policies=None,
                    policy_mapping_fn=None) -> "AlgorithmConfig":
        """Reference: `algorithm_config.py` AlgorithmConfig.multi_agent().
        `policies` may be a dict {module_id: RLModuleSpec|None} or an
        iterable of module ids; `policy_mapping_fn(agent_id) -> module_id`
        must be picklable (top-level function / functools.partial)."""
        if policies is not None:
            if isinstance(policies, str):
                policies = [policies]
            if not isinstance(policies, dict):
                policies = {mid: None for mid in policies}
            self.policies = policies
        if policy_mapping_fn is not None:
            self.policy_mapping_fn = policy_mapping_fn
        return self

    def evaluation(self, evaluation_interval=None,
                   evaluation_num_env_runners=None,
                   evaluation_duration=None,
                   evaluation_explore=None) -> "AlgorithmConfig":
        """Reference: `algorithm_config.py` AlgorithmConfig.evaluation()."""
        if evaluation_interval is not None:
            self.evaluation_interval = evaluation_interval
        if evaluation_num_env_runners is not None:
            self.evaluation_num_env_runners = evaluation_num_env_runners
        if evaluation_duration is not None:
            self.evaluation_duration = evaluation_duration
        if evaluation_explore is not None:
            self.evaluation_explore = evaluation_explore
        return self

    def rl_module(self, hidden=None,
                  model_config=None) -> "AlgorithmConfig":
        if hidden is not None:
            self.module_hidden = tuple(hidden)
        if model_config is not None:
            self.model_config = dict(model_config)
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def build(self) -> "Algorithm":
        return self.algo_class(self)


class Algorithm:
    """Owns the env-runner fleet + learner group; `train()` = one iteration.

    Subclasses set `learner_class` and implement `training_step()`.
    """

    learner_class = None
    ma_learner_class = None   # multi-agent learner (None -> unsupported)
    rl_module_class = None    # None -> default actor-critic MLP
    # ES/ARS publish theta through the versioned channel even when
    # colocated; they flip this on to get a WeightStore regardless of
    # config.execution.
    needs_weight_channel = False

    def __init__(self, config: AlgorithmConfig):
        from ray_tpu._private.config import GlobalConfig
        from ray_tpu.rllib.core.learner_group import LearnerGroup

        self.config = config
        self.multi_agent = config.policies is not None
        self.execution = getattr(config, "execution", "colocated")
        if self.execution not in ("colocated", "decoupled"):
            raise ValueError(
                f"execution must be 'colocated' or 'decoupled', got "
                f"{self.execution!r}")
        decoupled = self.execution == "decoupled"
        if decoupled and self.multi_agent:
            raise NotImplementedError(
                "execution='decoupled' supports single-agent algorithms")
        self._staleness_clip = int(
            GlobalConfig.rl_staleness_clip
            if getattr(config, "staleness_clip", None) is None
            else config.staleness_clip)
        self.weight_store = None
        self.inference_servers: List[Any] = []
        self.sample_queue = None
        self.learner_pool = None
        self._inflight_samples: Dict[Any, Any] = {}
        if decoupled or self.needs_weight_channel:
            from ray_tpu.rllib.podracer import WeightStore

            self.weight_store = WeightStore(
                history=getattr(config, "weight_history", None))
        probe_env = make_env(config.env)
        learner_class = self.learner_class
        if self.multi_agent:
            from ray_tpu.rllib.core.multi_rl_module import (
                MultiRLModuleSpec, default_policy_mapping_fn)
            from ray_tpu.rllib.env.multi_agent_env_runner import (
                MultiAgentEnvRunner)

            if self.ma_learner_class is None:
                raise ValueError(
                    f"{type(self).__name__} has no multi-agent learner")
            mapping = config.policy_mapping_fn or default_policy_mapping_fn
            specs = {}
            for mid, spec in config.policies.items():
                if spec is None:
                    # Probe spaces from any agent routed to this module.
                    agent = next(
                        (a for a in probe_env.possible_agents
                         if mapping(a) == mid), None)
                    if agent is None:
                        raise ValueError(
                            f"policy '{mid}' has no RLModuleSpec and "
                            f"policy_mapping_fn maps no agent of "
                            f"{probe_env.possible_agents} to it")
                    spec = self._default_module_spec(
                        probe_env.get_observation_space(agent),
                        probe_env.get_action_space(agent))
                specs[mid] = spec
            for a in probe_env.possible_agents:
                if mapping(a) not in specs:
                    raise ValueError(
                        f"policy_mapping_fn routes agent '{a}' to "
                        f"'{mapping(a)}', which is not in "
                        f"policies={sorted(specs)}")
            self.module_spec = MultiRLModuleSpec(specs)
            self.env_runners = [
                MultiAgentEnvRunner.remote(
                    config.env, self.module_spec,
                    policy_mapping_fn=config.policy_mapping_fn,
                    num_envs=config.num_envs_per_runner,
                    seed=config.seed + i)
                for i in range(config.num_env_runners)
            ]
            learner_class = self.ma_learner_class
        else:
            obs_space = probe_env.observation_space
            if config.connectors:
                # The pipeline may widen the module's input (frame
                # stacking); build the spec from the TRANSFORMED space.
                from ray_tpu.rllib.connectors import build_pipeline

                obs_space = build_pipeline(
                    config.connectors).transform_observation_space(
                        obs_space)
            self.module_spec = self._default_module_spec(
                obs_space, probe_env.action_space)
            if decoupled:
                from ray_tpu.rllib.podracer import InferenceServer

                self.inference_servers = [
                    InferenceServer.remote(
                        self.module_spec,
                        weight_store=self.weight_store,
                        max_batch_rows=config.inference_max_batch_rows,
                        batch_wait_s=config.inference_batch_wait_s,
                        weight_poll_interval_s=(
                            config.weight_poll_interval_s),
                        seed=config.seed + 90_000 + i)
                    for i in range(max(1, config.num_inference_servers))
                ]
            self.env_runners = [
                EnvRunner.remote(
                    config.env, self.module_spec,
                    num_envs=config.num_envs_per_runner,
                    seed=config.seed + i,
                    connectors=config.connectors,
                    inference_server=(
                        self.inference_servers[
                            i % len(self.inference_servers)]
                        if decoupled else None),
                    weight_store=self.weight_store)
                for i in range(config.num_env_runners)
            ]
        self.eval_runners: List[Any] = []
        if config.evaluation_interval:
            if self.multi_agent:
                raise NotImplementedError(
                    "evaluation workers support single-agent algorithms; "
                    "sample multi-agent eval episodes via the runners "
                    "directly")
            self.eval_runners = [
                EnvRunner.remote(config.env, self.module_spec,
                                 num_envs=config.num_envs_per_runner,
                                 seed=config.seed + 10_000 + i,
                                 connectors=config.connectors)
                for i in range(config.evaluation_num_env_runners)
            ]
        if decoupled:
            from ray_tpu._private.config import GlobalConfig
            from ray_tpu.rllib.podracer import LearnerPool
            from ray_tpu.util.queue import Queue

            maxsize = int(
                GlobalConfig.rl_sample_queue_maxsize
                if config.sample_queue_maxsize is None
                else config.sample_queue_maxsize)
            # The queue actor must serve a blocked get() and a put()
            # concurrently; the default concurrency of 1 would make
            # every get(timeout) stall puts for its full timeout.
            self.sample_queue = Queue(
                maxsize=maxsize,
                actor_options={"max_concurrency": 8})
            self.learner_group = None
            self.learner_pool = LearnerPool(
                learner_class, self.module_spec,
                learner_config=self._learner_config(),
                queue=self.sample_queue,
                weight_store=self.weight_store,
                num_workers=config.num_learners,
                staleness_clip=self._staleness_clip,
                publish_interval=config.weight_publish_interval,
                update_delay_s=config.learner_update_delay_s,
                seed=config.seed)
        else:
            self.learner_group = LearnerGroup(
                learner_class, self.module_spec,
                learner_config=self._learner_config(),
                scaling_config=ScalingConfig(
                    num_workers=config.num_learners),
                jax_config=JaxConfig(platform=config.jax_platform))
        self._iteration = 0
        self._recent_returns: List[float] = []
        self._agent_returns: Dict[str, List[float]] = {}
        if not decoupled:
            # Decoupled runners have no local policy to sync: version 1
            # is already in the WeightStore channel (published by the
            # learner pool) and the servers pull it.
            self._sync_weights()

    def _default_module_spec(self, obs_space, act_space) -> RLModuleSpec:
        """Algorithms with a fixed module keep it (DQN's QModule, SAC's
        SACModule); otherwise the catalog picks by spaces (MLP / CNN /
        Gaussian — `models/catalog.py`, reference `rllib/models/
        catalog.py`)."""
        if self.rl_module_class is not None:
            return RLModuleSpec(observation_space=obs_space,
                                action_space=act_space,
                                hidden=self.config.module_hidden,
                                module_class=self.rl_module_class,
                                module_kwargs=self._module_kwargs())
        from ray_tpu.rllib.models.catalog import Catalog

        model_config = {"fcnet_hiddens": self.config.module_hidden,
                        **(self.config.model_config or {})}
        return Catalog.get_module_spec(obs_space, act_space, model_config)

    def _module_kwargs(self) -> Dict[str, Any]:
        """Extra ctor kwargs for a fixed `rl_module_class` (TD3's twin_q,
        exploration sigma, ...); merged into the RLModuleSpec."""
        return {}

    def _learner_config(self) -> Dict[str, Any]:
        return {"lr": self.config.lr, "grad_clip": self.config.grad_clip,
                "seed": self.config.seed}

    # ------------------------------------------------------------------ train
    def train(self) -> Dict[str, Any]:
        self._iteration += 1
        metrics = self.training_step()
        metrics["training_iteration"] = self._iteration
        if self._recent_returns:
            window = self._recent_returns[
                -getattr(self.config, "metrics_episode_window", 100):]
            metrics["episode_return_mean"] = float(np.mean(window))
            metrics["num_episodes"] = len(window)
        win = getattr(self.config, "metrics_episode_window", 100)
        for agent, rets in self._agent_returns.items():
            if rets:
                metrics[f"episode_return_mean/{agent}"] = float(
                    np.mean(rets[-win:]))
        interval = getattr(self.config, "evaluation_interval", None)
        if self.eval_runners and interval and \
                self._iteration % interval == 0:
            metrics["evaluation"] = self.evaluate()
        return metrics

    def _eval_weights(self, weights):
        """Hook: adjust raw learner weights for evaluation runners (DQN
        overrides the in-pytree epsilon, which gets zero gradient and
        would otherwise ship at its init value)."""
        return weights

    def evaluate(self) -> Dict[str, Any]:
        """Run `evaluation_duration` full episodes on the dedicated eval
        fleet with current weights (greedy by default) and aggregate
        (reference: `Algorithm.evaluate` over the eval WorkerSet)."""
        if not self.eval_runners:
            raise ValueError(
                "no evaluation workers; set config.evaluation("
                "evaluation_interval=...) before build()")
        weights = self._eval_weights(self.get_policy_weights())
        ref = ray_tpu.put(weights)
        syncs = [r.set_weights.remote(ref) for r in self.eval_runners]
        if self.config.connectors:
            state = ray_tpu.get(
                self.env_runners[0].get_connector_state.remote(),
                timeout=600)
            syncs += [r.set_connector_state.remote(state)
                      for r in self.eval_runners]
        ray_tpu.get(syncs, timeout=600)
        total = int(self.config.evaluation_duration)
        n = len(self.eval_runners)
        per = [total // n + (1 if i < total % n else 0) for i in range(n)]
        refs = [r.sample_episodes.remote(
                    k, explore=self.config.evaluation_explore)
                for r, k in zip(self.eval_runners, per) if k]
        results = ray_tpu.get(refs, timeout=600)
        returns = [r for res in results for r in res["episode_returns"]]
        lengths = [l for res in results for l in res["episode_lengths"]]
        return {
            "episode_return_mean": float(np.mean(returns)) if returns
            else float("nan"),
            "episode_return_min": float(np.min(returns)) if returns
            else float("nan"),
            "episode_return_max": float(np.max(returns)) if returns
            else float("nan"),
            "episode_len_mean": float(np.mean(lengths)) if lengths
            else float("nan"),
            "num_episodes": len(returns),
        }

    def training_step(self) -> Dict[str, Any]:
        raise NotImplementedError

    # ------------------------------------------------------------------ utils
    def get_policy_weights(self):
        """Current policy weights, wherever learning happens."""
        if self.learner_pool is not None:
            return self.learner_pool.get_weights()
        return self.learner_group.get_weights()

    def sample_batch(self, num_steps_per_runner: int
                     ) -> List[Dict[str, np.ndarray]]:
        """Parallel rollouts from all runners, time-major fragments."""
        refs = [r.sample.remote(num_steps_per_runner)
                for r in self.env_runners]
        rollouts = ray_tpu.get(refs, timeout=600)
        for ro in rollouts:
            self._recent_returns.extend(ro.pop("episode_returns"))
            for agent, rets in ro.pop("agent_episode_returns", {}).items():
                self._agent_returns.setdefault(agent, []).extend(rets)
        return rollouts

    def sample_batch_decoupled(self, num_steps_per_runner: int
                               ) -> List[Dict[str, np.ndarray]]:
        """Continuous sampling for decoupled execution: keep one
        sample() outstanding per runner, harvest the completed round,
        and resubmit BEFORE processing — so iteration i+1's acting
        overlaps iteration i's learning (the Podracer overlap)."""
        if not self._inflight_samples:
            self._inflight_samples = {
                r.sample.remote(num_steps_per_runner): r
                for r in self.env_runners}
        rollouts = ray_tpu.get(list(self._inflight_samples), timeout=600)
        self._inflight_samples = {
            r.sample.remote(num_steps_per_runner): r
            for r in self.env_runners}
        for ro in rollouts:
            self._recent_returns.extend(ro.pop("episode_returns"))
        return rollouts

    def _sync_weights(self, weights=None) -> None:
        if weights is None:
            weights = self.learner_group.get_weights()
        ref = ray_tpu.put(weights)
        ray_tpu.get([r.set_weights.remote(ref) for r in self.env_runners],
                    timeout=600)

    def stop(self) -> None:
        if self.learner_group is not None:
            self.learner_group.shutdown()
        if self.learner_pool is not None:
            self.learner_pool.shutdown()
        for s in self.inference_servers:
            try:
                ray_tpu.get(s.shutdown.remote(), timeout=30)
            except Exception:
                pass
        for r in self.env_runners + self.eval_runners \
                + self.inference_servers:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        if self.sample_queue is not None:
            try:
                self.sample_queue.shutdown()
            except Exception:
                pass
        if self.weight_store is not None:
            try:
                self.weight_store.shutdown()
            except Exception:
                pass

    def as_trainable(self):
        """Function-trainable for the Tuner (reference: Algorithm IS a
        Trainable; here the function API wraps the loop)."""
        algo_config = self.config

        def _trainable(config: Dict[str, Any]):
            from ray_tpu import tune

            cfg = algo_config.copy()
            for k, v in config.items():
                if hasattr(cfg, k):
                    setattr(cfg, k, v)
            algo = cfg.build()
            try:
                for _ in range(int(config.get("iterations", 10))):
                    tune.report(algo.train())
            finally:
                algo.stop()

        return _trainable
