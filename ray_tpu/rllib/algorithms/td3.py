"""TD3 and DDPG — deterministic-policy continuous control.

Reference: `rllib/algorithms/td3/td3.py` (twin critics, delayed policy
updates, target policy smoothing over DDPG) and
`rllib/algorithms/ddpg/ddpg.py`. TPU-first shape mirrors our SAC: actor,
critics, their target copies, and the update-step counter live in ONE
state pytree, and the whole update — critic + (masked) actor losses, one
optimizer step, delayed polyak averaging — is a single jitted, donated
call. The policy delay is a traced mask on the actor loss + target
polyak (step % d), not a host-side branch, so 1 learner or 64 run the
same XLA program.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.algorithms.dqn import ReplayBuffer
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import RLModule
from ray_tpu.rllib.env.spaces import Box


class TD3Module(RLModule):
    """Deterministic tanh actor + (optionally twin) Q critics."""

    def __init__(self, observation_space: Box, action_space: Box,
                 hidden: Sequence[int] = (64, 64), twin_q: bool = True,
                 exploration_sigma: float = 0.1):
        import flax.linen as nn

        obs_dim = int(np.prod(observation_space.shape))
        act_dim = int(np.prod(action_space.shape))
        # Affine low/high map: tanh lands in [-1, 1], the bounds need not
        # be symmetric around zero. center + tanh(mu) * scale covers any
        # bounded Box; validated here so a bad space fails at
        # construction, not as NaN actions mid-training.
        low = np.asarray(action_space.low, np.float32).reshape(-1)
        high = np.asarray(action_space.high, np.float32).reshape(-1)
        if not (np.isfinite(low).all() and np.isfinite(high).all()):
            raise ValueError(
                f"TD3/DDPG require a bounded action Box; got low={low} "
                f"high={high}")
        if not (high > low).all():
            raise ValueError(
                f"degenerate action Box: high must exceed low per "
                f"dimension (low={low}, high={high})")
        self._act_center = (high + low) / 2.0
        self._act_scale = (high - low) / 2.0
        self.twin_q = bool(twin_q)
        self.exploration_sigma = float(exploration_sigma)

        class _Actor(nn.Module):
            @nn.compact
            def __call__(self, x):
                h = x
                for width in hidden:
                    h = nn.relu(nn.Dense(width)(h))
                return nn.Dense(act_dim)(h)

        class _Critic(nn.Module):
            @nn.compact
            def __call__(self, obs, act):
                h = jnp.concatenate([obs, act], axis=-1)
                for width in hidden:
                    h = nn.relu(nn.Dense(width)(h))
                return nn.Dense(1)(h)[..., 0]

        self._actor, self._critic = _Actor(), _Critic()
        self._obs_dim, self._act_dim = obs_dim, act_dim

    def init(self, rng: jax.Array) -> Any:
        k_pi, k_q1, k_q2 = jax.random.split(rng, 3)
        obs = jnp.zeros((1, self._obs_dim), jnp.float32)
        act = jnp.zeros((1, self._act_dim), jnp.float32)
        params = {"actor": self._actor.init(k_pi, obs),
                  "q1": self._critic.init(k_q1, obs, act)}
        if self.twin_q:
            params["q2"] = self._critic.init(k_q2, obs, act)
        return params

    # -------------------------------------------------------------- policy
    def policy_action(self, actor_params, obs):
        """Deterministic bounded action: center + tanh(mu(s)) * scale."""
        return (self._act_center
                + jnp.tanh(self._actor.apply(actor_params, obs))
                * self._act_scale)

    def forward_inference(self, params, obs):
        return {"actions": self.policy_action(params["actor"], obs)}

    def q_values(self, params, obs, act):
        q1 = self._critic.apply(params["q1"], obs, act)
        if not self.twin_q:
            return q1, q1
        return q1, self._critic.apply(params["q2"], obs, act)

    # ------------------------------------------------- env-runner protocol
    def forward_exploration(self, params, obs, rng):
        """Gaussian action-space noise around the deterministic policy
        (TD3/DDPG explore in action space, not parameter space)."""
        act = self.policy_action(params["actor"], obs)
        noise = self.exploration_sigma * self._act_scale * jax.random.normal(
            rng, act.shape)
        act = jnp.clip(act + noise, self._act_center - self._act_scale,
                       self._act_center + self._act_scale)
        return {"actions": act,
                "logp": jnp.zeros(obs.shape[0], jnp.float32),
                "vf": jnp.zeros(obs.shape[0], jnp.float32)}

    def forward_train(self, params, obs):
        return {"actions": self.policy_action(params["actor"], obs)}


def _interval_update(inner, period: int):
    """optax transform applying `inner` only every `period`-th step.

    Masking the actor LOSS alone is not enough for delayed policy
    updates: zero grads still advance Adam — the count steps, first/second
    moments decay, and the stale momentum moves the actor parameters on
    every skipped step. Here skipped steps emit zero updates AND keep the
    inner optimizer state (count, mu, nu) frozen, so the actor's Adam
    trajectory is exactly what it would be updating once per `period`
    steps. Both branches are computed each call (fixed XLA program);
    `where` selects. The step counter starts at 0 and increments once per
    update, in lockstep with the learner's `state["step"]`, so the apply
    steps coincide with `_actor_mask`'s unmasked steps.
    """
    import optax

    def init(params):
        return (jnp.zeros((), jnp.int32), inner.init(params))

    def update(updates, state, params=None):
        count, inner_state = state
        apply = (count % period == 0)
        new_updates, new_inner = inner.update(updates, inner_state, params)
        out = jax.tree.map(
            lambda n: jnp.where(apply, n, jnp.zeros_like(n)), new_updates)
        kept = jax.tree.map(
            lambda n, o: jnp.where(apply, n, o), new_inner, inner_state)
        return out, (count + 1, kept)

    return optax.GradientTransformation(init, update)


class TD3Learner(Learner):
    """One jitted update = critic step + delay-masked actor step +
    delay-masked polyak; the delay counter is learner state."""

    def init_extra_state(self, params) -> Dict[str, Any]:
        return {"target": jax.tree.map(jnp.copy, params),
                "step": jnp.asarray(0, jnp.int32)}

    def _make_optimizer(self):
        """Partition the optimizer by parameter group: the critics step
        every update, the actor's whole optimizer (not just its loss)
        runs on the policy-delay interval. delay <= 1 (DDPG) keeps the
        base single chain."""
        import optax

        def base():
            return optax.chain(
                optax.clip_by_global_norm(
                    self.config.get("grad_clip", 0.5)),
                optax.adam(self.config.get("lr", 3e-4)),
            )

        delay = int(self.config.get("policy_delay", 2))
        if delay <= 1:
            return base()

        def labels(params):
            return {k: jax.tree.map(
                        lambda _: "actor" if k == "actor" else "critic", v)
                    for k, v in params.items()}

        return optax.multi_transform(
            {"actor": _interval_update(base(), delay), "critic": base()},
            labels)

    def _actor_mask(self, state):
        delay = int(self.config.get("policy_delay", 2))
        if delay <= 1:
            return jnp.asarray(1.0, jnp.float32)
        return (state["step"] % delay == 0).astype(jnp.float32)

    def compute_loss_from_state(self, state, batch, rng):
        cfg = self.config
        gamma = cfg.get("gamma", 0.99)
        target_noise = cfg.get("target_noise", 0.2)
        noise_clip = cfg.get("target_noise_clip", 0.5)
        params, target = state["params"], state["target"]
        m: TD3Module = self.module
        scale = jnp.asarray(m._act_scale)
        center = jnp.asarray(m._act_center)

        # --- critic loss: y = r + gamma min Q_targ(s', pi_targ(s') + eps)
        a_next = m.policy_action(target["actor"], batch["next_obs"])
        if target_noise > 0:
            eps = jnp.clip(
                target_noise * jax.random.normal(rng, a_next.shape),
                -noise_clip, noise_clip) * scale
            a_next = jnp.clip(a_next + eps, center - scale, center + scale)
        tq1, tq2 = m.q_values(target, batch["next_obs"], a_next)
        y = jax.lax.stop_gradient(
            batch["rewards"] + gamma
            * (1.0 - batch["dones"].astype(jnp.float32))
            * jnp.minimum(tq1, tq2))
        q1, q2 = m.q_values(params, batch["obs"], batch["actions"])
        critic_loss = ((q1 - y) ** 2).mean()
        if m.twin_q:
            critic_loss = critic_loss + ((q2 - y) ** 2).mean()

        # --- actor loss: -Q1(s, pi(s)) with critics frozen, masked by the
        # policy delay (zero loss => zero actor grads on skipped steps).
        frozen = jax.lax.stop_gradient(
            {k: v for k, v in params.items() if k != "actor"})
        a_pi = m.policy_action(params["actor"], batch["obs"])
        actor_obj = -self.module._critic.apply(
            frozen["q1"], batch["obs"], a_pi).mean()
        mask = self._actor_mask(state)
        loss = critic_loss + mask * actor_obj
        return loss, {"critic_loss": critic_loss,
                      "actor_loss": actor_obj,
                      "q1_mean": q1.mean(),
                      "target_q_mean": y.mean()}

    def post_update_state(self, state):
        tau = self.config.get("tau", 0.005)
        mask = self._actor_mask(state)
        polyak = lambda t, o: t + mask * tau * (o - t)  # noqa: E731
        new_target = jax.tree.map(polyak, state["target"], state["params"])
        return {**state, "target": new_target, "step": state["step"] + 1}


class TD3Config(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.env = "Pendulum-v1"
        self.lr = 1e-3
        self.grad_clip = 10.0
        self.tau = 0.005
        self.twin_q = True
        self.policy_delay = 2
        self.target_noise = 0.2
        self.target_noise_clip = 0.5
        self.exploration_sigma = 0.1
        self.buffer_capacity = 100_000
        self.learning_starts = 1000
        self.train_batch_size = 256
        self.rollout_fragment_length = 32
        self.num_updates_per_iteration = 64

    algo_class = property(lambda self: TD3)


class DDPGConfig(TD3Config):
    """DDPG = TD3 minus the three addenda: single critic, no policy
    delay, no target smoothing (reference `ddpg/ddpg.py`)."""

    def __init__(self):
        super().__init__()
        self.twin_q = False
        self.policy_delay = 1
        self.target_noise = 0.0

    algo_class = property(lambda self: DDPG)


class TD3(Algorithm):
    learner_class = TD3Learner
    rl_module_class = TD3Module

    def __init__(self, config: TD3Config):
        super().__init__(config)
        act_space = self.module_spec.action_space
        self._buffer = ReplayBuffer(
            config.buffer_capacity,
            self.module_spec.observation_space.shape,
            action_shape=act_space.shape, action_dtype=np.float32)
        self._rng = np.random.RandomState(config.seed)
        self._env_steps = 0
        self._updates = 0

    def _module_kwargs(self) -> Dict[str, Any]:
        out = super()._module_kwargs()
        out["twin_q"] = self.config.twin_q
        out["exploration_sigma"] = self.config.exploration_sigma
        return out

    def _learner_config(self) -> Dict[str, Any]:
        out = super()._learner_config()
        cfg = self.config
        out.update(gamma=cfg.gamma, tau=cfg.tau,
                   policy_delay=cfg.policy_delay,
                   target_noise=cfg.target_noise,
                   target_noise_clip=cfg.target_noise_clip)
        return out

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        rollouts = self.sample_batch(cfg.rollout_fragment_length)
        for ro in rollouts:
            T, N = ro["actions"].shape[:2]
            self._env_steps += T * N
            flat = lambda a: a.reshape(T * N, *a.shape[2:])  # noqa: E731
            self._buffer.add_batch(flat(ro["obs"]), flat(ro["actions"]),
                                   flat(ro["rewards"]),
                                   flat(ro["next_obs"]),
                                   flat(ro["terminateds"]))

        metrics: Dict[str, Any] = {"env_steps": self._env_steps,
                                   "buffer_size": len(self._buffer)}
        if len(self._buffer) >= cfg.learning_starts:
            for _ in range(cfg.num_updates_per_iteration):
                batch = self._buffer.sample(cfg.train_batch_size, self._rng)
                metrics.update(self.learner_group.update(batch))
                self._updates += 1
        self._sync_weights()
        metrics["num_gradient_updates"] = self._updates
        return metrics


class DDPG(TD3):
    pass
