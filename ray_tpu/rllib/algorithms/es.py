"""ES / ARS — distributed gradient-free policy optimization.

Reference: `rllib/algorithms/es/es.py` (OpenAI-ES: antithetic Gaussian
perturbations, centered-rank fitness shaping, Adam on the master) and
`rllib/algorithms/ars/ars.py` (ARS: top-k perturbation selection,
reward-std normalization). Both bypass the gradient Learner entirely —
the "update" is a fitness-weighted combination of noise vectors.

Architecture here vs the reference: the reference ships a shared noise
table + offsets to dedicated ES workers because its policies are large.
Our runners are the ordinary `EnvRunner` fleet (the same actors every
other algorithm uses): the canonical theta ships ONCE per iteration via
`ray_tpu.put`, then per perturbation the driver enqueues an ordered
`set_perturbed_weights(theta_ref, seed, sigma, sign)` then
`sample_episodes(...)` pair on a runner — the runner regenerates its
noise row from the seed locally, actor-call ordering guarantees the
rollout sees its perturbation, and N pairs pipeline across the fleet in
parallel. The combine step `w @ eps / (P*sigma)` is one jitted matmul
(MXU-shaped: P x dim), with Adam on the flat parameter vector.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner


class _WeightHolderLearner(Learner):
    """ES never takes gradients; the learner group only holds/ships the
    canonical params (and keeps checkpoints/state uniform with every
    other algorithm)."""

    def compute_loss(self, params, batch, rng):
        import jax.numpy as jnp

        return jnp.asarray(0.0, jnp.float32), {}


def _centered_ranks(x: np.ndarray) -> np.ndarray:
    """Fitness shaping: map returns to ranks in [-0.5, 0.5] (reference
    `es/utils.py` compute_centered_ranks) — scale-free, outlier-proof."""
    ranks = np.empty(x.size, np.float32)
    ranks[x.ravel().argsort()] = np.arange(x.size, dtype=np.float32)
    return (ranks / max(x.size - 1, 1) - 0.5).reshape(x.shape)


class ESConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.env = "CartPole-v1"
        self.lr = 0.02
        self.noise_stdev = 0.05
        self.num_perturbations = 16      # antithetic pairs per iteration
        self.episodes_per_perturbation = 1
        self.weight_decay = 0.005
        # ARS-style top-k selection: keep the best fraction of pairs
        # (by max(r+, r-)); 1.0 = plain ES over all pairs.
        self.top_fraction = 1.0
        self.fitness_shaping = "centered_rank"   # or "std" (ARS)

    algo_class = property(lambda self: ES)


class ARSConfig(ESConfig):
    """Augmented Random Search (reference `ars/ars.py`): ES with top-k
    direction selection and reward-std scaling instead of rank shaping."""

    def __init__(self):
        super().__init__()
        self.top_fraction = 0.5
        self.fitness_shaping = "std"

    algo_class = property(lambda self: ARS)


class ES(Algorithm):
    learner_class = _WeightHolderLearner
    # Theta rides the versioned WeightStore channel (one publish per
    # iteration, one fetch per runner per version) instead of a bespoke
    # put-once ObjectRef broadcast.
    needs_weight_channel = True

    def __init__(self, config: ESConfig):
        super().__init__(config)
        import jax
        import optax
        from jax.flatten_util import ravel_pytree

        self._np_rng = np.random.RandomState(config.seed)
        theta = self.learner_group.get_weights()
        flat, self._unravel = ravel_pytree(theta)
        self._flat = np.asarray(flat, np.float32)
        self._opt = optax.adam(config.lr)
        self._opt_state = self._opt.init(flat)

        def _combine(flat, opt_state, w, eps, sigma, denom):
            # g ~ E[f(theta + sigma eps) eps] / sigma; Adam ascends it.
            g = (w @ eps) / (denom * sigma)
            g = g - config.weight_decay * flat
            updates, new_opt = self._opt.update(-g, opt_state, flat)
            return optax.apply_updates(flat, updates), new_opt

        from ray_tpu.observability.jit import tracked_jit

        self._combine = tracked_jit(_combine, name="es_combine")
        self._total_episodes = 0

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        P = cfg.num_perturbations
        sigma = cfg.noise_stdev
        dim = self._flat.size
        # Per-perturbation noise SEEDS, not noise vectors: each runner
        # regenerates its eps row locally (set_perturbed_weights), the
        # driver regenerates the same rows for the combine matmul.
        seeds = self._np_rng.randint(0, 2 ** 31 - 1, size=P)
        eps = np.stack([np.random.RandomState(int(s)).randn(dim)
                        .astype(np.float32) for s in seeds])

        # Publish theta ONCE into the versioned WeightStore channel:
        # each runner fetches it once per version (cached across this
        # iteration's perturbations), so the 2*P actor calls carry only
        # (version, seed, sigma, sign) scalars instead of 2*P full
        # perturbed pytrees. Antithetic twins share the noise seed.
        version = self.weight_store.publish(self._unravel(self._flat))
        refs: List[Any] = []
        set_refs: List[Any] = []
        n_runners = len(self.env_runners)
        for i in range(P):
            for s, signed in ((0, 1.0), (1, -1.0)):
                runner = self.env_runners[(2 * i + s) % n_runners]
                set_refs.append(runner.set_perturbed_weights.remote(
                    version, int(seeds[i]), float(sigma), signed))
                refs.append(runner.sample_episodes.remote(
                    cfg.episodes_per_perturbation, explore=False))
        # Per-actor ordering already serializes install-then-sample, but
        # a dropped install ref would swallow its exception and the
        # rollout would silently sample stale weights — resolve them.
        ray_tpu.get(set_refs, timeout=600)
        results = ray_tpu.get(refs, timeout=600)
        # Guard: a rollout can return ZERO completed episodes (hard
        # max_env_steps truncation) — np.mean([]) is NaN, and one NaN
        # return would ride the combine matmul straight into theta.
        # Invalid rollouts zero their slot and invalidate the pair.
        means, valid = [], []
        for r in results:
            er = r["episode_returns"]
            valid.append(len(er) > 0)
            means.append(float(np.mean(er)) if len(er) else 0.0)
        rets = np.asarray(means, np.float32).reshape(P, 2)
        pair_valid = np.asarray(valid, bool).reshape(P, 2).all(axis=1)
        self._total_episodes += sum(
            len(r["episode_returns"]) for r in results)
        valid_rets = rets.reshape(-1)[np.asarray(valid, bool)]
        self._recent_returns.extend(valid_rets.tolist())
        metrics = {
            "perturbed_return_mean": float(valid_rets.mean())
            if valid_rets.size else 0.0,
            "perturbed_return_max": float(valid_rets.max())
            if valid_rets.size else 0.0,
            "num_perturbations": int(P),
            "invalid_pairs": int(P - int(pair_valid.sum())),
            "total_episodes": self._total_episodes,
        }

        keep = np.nonzero(pair_valid)[0]
        if cfg.top_fraction < 1.0 and keep.size:
            k = max(1, int(round(P * cfg.top_fraction)))
            keep = keep[np.argsort(-rets[keep].max(axis=1))[:k]]
        if keep.size == 0:
            # Every pair came back empty: skip the update entirely
            # rather than stepping Adam on a zero/garbage gradient.
            metrics.update(directions_kept=0,
                           update_norm=float(np.linalg.norm(self._flat)))
            return metrics
        sel = rets[keep]
        if cfg.fitness_shaping == "centered_rank":
            shaped = _centered_ranks(sel)
        else:                                    # ARS: std normalization
            shaped = sel / max(float(sel.std()), 1e-8)
        w = shaped[:, 0] - shaped[:, 1]          # antithetic difference

        new_flat, self._opt_state = self._combine(
            self._flat, self._opt_state, w, eps[keep], sigma,
            float(keep.size))
        self._flat = np.asarray(new_flat)

        theta = self._unravel(self._flat)
        self.learner_group.set_weights(theta)
        self._sync_weights(theta)
        metrics.update(directions_kept=int(keep.size),
                       update_norm=float(np.linalg.norm(self._flat)))
        return metrics


class ARS(ES):
    pass
