"""ES / ARS — distributed gradient-free policy optimization.

Reference: `rllib/algorithms/es/es.py` (OpenAI-ES: antithetic Gaussian
perturbations, centered-rank fitness shaping, Adam on the master) and
`rllib/algorithms/ars/ars.py` (ARS: top-k perturbation selection,
reward-std normalization). Both bypass the gradient Learner entirely —
the "update" is a fitness-weighted combination of noise vectors.

Architecture here vs the reference: the reference ships a shared noise
table + offsets to dedicated ES workers because its policies are large.
Our runners are the ordinary `EnvRunner` fleet (the same actors every
other algorithm uses): per perturbation the driver enqueues an ordered
`set_weights(theta ± sigma*eps)` then `sample_episodes(...)` pair on a
runner — actor-call ordering guarantees the rollout sees its
perturbation, and N pairs pipeline across the fleet in parallel. The
combine step `w @ eps / (P*sigma)` is one jitted matmul (MXU-shaped:
P x dim), with Adam on the flat parameter vector.
"""

from __future__ import annotations

from typing import Any, Dict, List

import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner


class _WeightHolderLearner(Learner):
    """ES never takes gradients; the learner group only holds/ships the
    canonical params (and keeps checkpoints/state uniform with every
    other algorithm)."""

    def compute_loss(self, params, batch, rng):
        import jax.numpy as jnp

        return jnp.asarray(0.0, jnp.float32), {}


def _centered_ranks(x: np.ndarray) -> np.ndarray:
    """Fitness shaping: map returns to ranks in [-0.5, 0.5] (reference
    `es/utils.py` compute_centered_ranks) — scale-free, outlier-proof."""
    ranks = np.empty(x.size, np.float32)
    ranks[x.ravel().argsort()] = np.arange(x.size, dtype=np.float32)
    return (ranks / max(x.size - 1, 1) - 0.5).reshape(x.shape)


class ESConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.env = "CartPole-v1"
        self.lr = 0.02
        self.noise_stdev = 0.05
        self.num_perturbations = 16      # antithetic pairs per iteration
        self.episodes_per_perturbation = 1
        self.weight_decay = 0.005
        # ARS-style top-k selection: keep the best fraction of pairs
        # (by max(r+, r-)); 1.0 = plain ES over all pairs.
        self.top_fraction = 1.0
        self.fitness_shaping = "centered_rank"   # or "std" (ARS)

    algo_class = property(lambda self: ES)


class ARSConfig(ESConfig):
    """Augmented Random Search (reference `ars/ars.py`): ES with top-k
    direction selection and reward-std scaling instead of rank shaping."""

    def __init__(self):
        super().__init__()
        self.top_fraction = 0.5
        self.fitness_shaping = "std"

    algo_class = property(lambda self: ARS)


class ES(Algorithm):
    learner_class = _WeightHolderLearner

    def __init__(self, config: ESConfig):
        super().__init__(config)
        import jax
        import optax
        from jax.flatten_util import ravel_pytree

        self._np_rng = np.random.RandomState(config.seed)
        theta = self.learner_group.get_weights()
        flat, self._unravel = ravel_pytree(theta)
        self._flat = np.asarray(flat, np.float32)
        self._opt = optax.adam(config.lr)
        self._opt_state = self._opt.init(flat)

        def _combine(flat, opt_state, w, eps, sigma, denom):
            # g ~ E[f(theta + sigma eps) eps] / sigma; Adam ascends it.
            g = (w @ eps) / (denom * sigma)
            g = g - config.weight_decay * flat
            updates, new_opt = self._opt.update(-g, opt_state, flat)
            return optax.apply_updates(flat, updates), new_opt

        self._combine = jax.jit(_combine)
        self._total_episodes = 0

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        P = cfg.num_perturbations
        sigma = cfg.noise_stdev
        dim = self._flat.size
        eps = self._np_rng.randn(P, dim).astype(np.float32)

        # Enqueue ordered (set_weights -> sample_episodes) pairs, striped
        # over the runner fleet; antithetic twins share the noise row.
        refs: List[Any] = []
        n_runners = len(self.env_runners)
        for i in range(P):
            for s, signed in ((0, 1.0), (1, -1.0)):
                runner = self.env_runners[(2 * i + s) % n_runners]
                w = self._unravel(self._flat + signed * sigma * eps[i])
                runner.set_weights.remote(w)
                refs.append(runner.sample_episodes.remote(
                    cfg.episodes_per_perturbation, explore=False))
        results = ray_tpu.get(refs, timeout=600)
        rets = np.asarray([float(np.mean(r["episode_returns"]))
                           for r in results], np.float32).reshape(P, 2)
        self._total_episodes += sum(
            len(r["episode_returns"]) for r in results)

        keep = np.arange(P)
        if cfg.top_fraction < 1.0:
            k = max(1, int(round(P * cfg.top_fraction)))
            keep = np.argsort(-rets.max(axis=1))[:k]
        sel = rets[keep]
        if cfg.fitness_shaping == "centered_rank":
            shaped = _centered_ranks(sel)
        else:                                    # ARS: std normalization
            shaped = sel / max(float(sel.std()), 1e-8)
        w = shaped[:, 0] - shaped[:, 1]          # antithetic difference

        new_flat, self._opt_state = self._combine(
            self._flat, self._opt_state, w, eps[keep], sigma,
            float(len(keep)))
        self._flat = np.asarray(new_flat)

        theta = self._unravel(self._flat)
        self.learner_group.set_weights(theta)
        self._sync_weights(theta)
        self._recent_returns.extend(rets.reshape(-1).tolist())
        return {"perturbed_return_mean": float(rets.mean()),
                "perturbed_return_max": float(rets.max()),
                "num_perturbations": int(P),
                "directions_kept": int(len(keep)),
                "update_norm": float(np.linalg.norm(self._flat)),
                "total_episodes": self._total_episodes}


class ARS(ES):
    pass
