"""PPO — clipped-surrogate policy optimization.

Reference: `rllib/algorithms/ppo/ppo.py:403` (training_step: sample →
learner_group.update_from_episodes → sync_weights) and
`ppo/ppo_learner.py` (clipped surrogate + clipped value loss + entropy
bonus, minibatch SGD epochs). GAE computed driver-side in numpy; the
update is the Learner's single pjit'd SPMD step.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner


def _ppo_loss(module, params, batch, cfg):
    """Clipped-surrogate loss on one module's flat batch (shared by the
    single-agent and multi-agent learners).  Distribution-agnostic:
    discrete modules emit `action_logits`, continuous ones emit
    `action_mean`/`action_log_std` (`models/distributions.py`)."""
    from ray_tpu.rllib.models.distributions import dist_from_outputs

    clip = cfg.get("clip_param", 0.2)
    vf_clip = cfg.get("vf_clip_param", 10.0)
    vf_coeff = cfg.get("vf_loss_coeff", 0.5)
    ent_coeff = cfg.get("entropy_coeff", 0.0)

    out = module.forward_train(params, batch["obs"])
    dist = dist_from_outputs(out)
    logp = dist.logp(batch["actions"])

    # Multi-agent batches keep inactive-lane rows (static shapes -> the
    # update jits once); `mask` turns means into masked means.
    if "mask" in batch:
        w = batch["mask"]
        denom = jnp.maximum(w.sum(), 1.0)
        wmean = lambda x: (x * w).sum() / denom          # noqa: E731
    else:
        wmean = jnp.mean

    ratio = jnp.exp(logp - batch["logp_old"])
    adv = batch["advantages"]
    surrogate = jnp.minimum(
        ratio * adv,
        jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * adv)
    policy_loss = -wmean(surrogate)

    vf_err = jnp.clip((out["vf"] - batch["value_targets"]) ** 2,
                      0.0, vf_clip ** 2)
    vf_loss = wmean(vf_err)

    entropy = wmean(dist.entropy())
    total = policy_loss + vf_coeff * vf_loss - ent_coeff * entropy
    return total, {
        "policy_loss": policy_loss,
        "vf_loss": vf_loss,
        "entropy": entropy,
        "mean_kl": wmean(batch["logp_old"] - logp),
    }


class PPOLearner(Learner):
    def compute_loss(self, params, batch, rng):
        return _ppo_loss(self.module, params, batch, self.config)


class MultiAgentPPOLearner(Learner):
    """Multi-agent PPO: params = {module_id: subparams}, batch =
    {module_id: flat batch}.  The per-module losses sum into ONE scalar,
    so a single jitted value_and_grad covers every policy — disjoint
    param subtrees give each module its own gradients with no masking.
    Reference analogue: `rllib/core/learner/learner.py` looping
    update_for_module per module_id (a dispatch per policy per step);
    here XLA fuses all policies into one program."""

    def _make_optimizer(self):
        """Clip each module's gradients by ITS OWN global norm (reference
        RLlib clips per module) — a shared clip_by_global_norm over the
        combined tree would let one policy's gradient spike rescale every
        other policy's healthy gradients, and would shrink the effective
        per-module threshold as ~sqrt(num_policies)."""
        import optax

        clip = self.config.get("grad_clip", 0.5)

        def _clip_update(updates, state, params=None):
            def one(u):
                g = optax.global_norm(u)
                scale = jnp.minimum(1.0, clip / (g + 1e-9))
                return jax.tree.map(lambda x: x * scale, u)

            return {mid: one(u) for mid, u in updates.items()}, state

        per_module_clip = optax.GradientTransformation(
            lambda params: optax.EmptyState(), _clip_update)
        return optax.chain(per_module_clip,
                           optax.adam(self.config.get("lr", 3e-4)))

    def compute_loss(self, params, batch, rng):
        total = 0.0
        metrics = {}
        for mid in sorted(batch):
            loss, m = _ppo_loss(self.module[mid], params[mid],
                                batch[mid], self.config)
            total = total + loss
            for k, v in m.items():
                metrics[f"{mid}/{k}"] = v
        return total, metrics


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        # Clips the squared value error; keep high — tight clips saturate
        # the vf gradient on environments with returns in the hundreds
        # (measured: vf_clip=10 stalls CartPole at ~300 return).
        self.vf_clip_param = 1000.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.gae_lambda = 0.95
        self.num_epochs = 8
        self.minibatch_size = 256
        self.lr = 3e-4

    algo_class = property(lambda self: PPO)


class PPO(Algorithm):
    learner_class = PPOLearner
    ma_learner_class = MultiAgentPPOLearner

    def _learner_config(self) -> Dict[str, Any]:
        cfg = super()._learner_config()
        cfg.update(clip_param=self.config.clip_param,
                   vf_clip_param=self.config.vf_clip_param,
                   vf_loss_coeff=self.config.vf_loss_coeff,
                   entropy_coeff=self.config.entropy_coeff)
        return cfg

    # -------------------------------------------------------------- step
    def training_step(self) -> Dict[str, Any]:
        if self.multi_agent:
            return self._multi_agent_step()
        if self.execution == "decoupled":
            return self._decoupled_step()
        c = self.config
        lanes = c.num_env_runners * c.num_envs_per_runner
        steps_per_runner = max(1, c.train_batch_size // lanes)

        rollouts = self.sample_batch(steps_per_runner)
        batch = _build_ppo_batch(rollouts, c.gamma, c.gae_lambda)

        n = len(batch["obs"])
        mb = min(c.minibatch_size, n)
        # Keep minibatches even across learners (SPMD lockstep), but never
        # round down to zero.
        n_learners = max(1, self.learner_group.num_learners)
        mb = max(n_learners, mb - mb % n_learners)
        rng = np.random.RandomState(self._iteration)
        metrics: Dict[str, float] = {}
        for _ in range(c.num_epochs):
            perm = rng.permutation(n)
            for lo in range(0, n - mb + 1, mb):
                idx = perm[lo:lo + mb]
                metrics = self.learner_group.update(
                    {k: v[idx] for k, v in batch.items()})
        self._sync_weights()
        metrics["num_env_steps_sampled"] = n
        return metrics

    def _decoupled_step(self) -> Dict[str, Any]:
        """Podracer execution: runners act through inference servers
        while the learner pool consumes stamped minibatches from the
        bounded queue; weights return via the WeightStore channel.

        Every minibatch is the SAME fixed size (last partial slice of
        each epoch dropped, exactly like the colocated path), so the
        pool's zero-sharded step compiles once."""
        from ray_tpu.rllib.podracer import feed_queue

        c = self.config
        lanes = c.num_env_runners * c.num_envs_per_runner
        steps_per_runner = max(1, c.train_batch_size // lanes)

        rollouts = self.sample_batch_decoupled(steps_per_runner)
        # Behavior version: the freshest weights any rollout acted with
        # (per-step versions differ only around a publish boundary).
        behavior = max(int(ro.pop("weight_version", 0))
                       for ro in rollouts)
        batch = _build_ppo_batch(rollouts, c.gamma, c.gae_lambda)

        n = len(batch["obs"])
        mb = max(1, min(c.minibatch_size, n))
        rng = np.random.RandomState(self._iteration)
        planned = []
        for _ in range(c.num_epochs):
            perm = rng.permutation(n)
            for lo in range(0, n - mb + 1, mb):
                idx = perm[lo:lo + mb]
                planned.append({k: v[idx] for k, v in batch.items()})
        # Kick consumers BEFORE feeding: producers may block on the
        # queue bound, and that backpressure must drain somewhere.
        kick = self.learner_pool.kick(len(planned))
        throttled = 0
        for mbatch in planned:
            mbatch["weight_version"] = behavior
        # One queue item (chunk of minibatches) per learner worker: the
        # round trip to the queue actor costs more than a minibatch
        # update, so feeding singly would serialize the pool on RPC
        # latency instead of compute — and more chunks than consumers
        # just buys extra round trips.
        n_chunks = max(1, len(self.learner_pool.workers))
        per_chunk = max(1, -(-len(planned) // n_chunks))
        for lo in range(0, len(planned), per_chunk):
            throttled += feed_queue(self.sample_queue,
                                    planned[lo:lo + per_chunk],
                                    timeout_s=5.0)
        stats = self.learner_pool.join(kick)
        metrics = dict(stats.get("last_metrics", {}))
        metrics.update(
            num_env_steps_sampled=n,
            weight_version=stats["weight_version"],
            weight_staleness_max=stats["max_staleness"],
            dropped_stale=stats.get("dropped", 0),
            backpressure_waits=throttled,
            num_updates_applied=stats.get("applied", 0),
        )
        return metrics

    def _multi_agent_step(self) -> Dict[str, Any]:
        c = self.config
        lanes = c.num_env_runners * c.num_envs_per_runner
        steps_per_runner = max(1, c.train_batch_size // lanes)

        rollouts = self.sample_batch(steps_per_runner)
        batches = _build_multi_agent_ppo_batch(rollouts, c.gamma,
                                               c.gae_lambda)

        n_learners = max(1, self.learner_group.num_learners)
        counts = {mid: len(b["obs"]) for mid, b in batches.items()}
        # One shared number of minibatches, sized off the smallest module
        # (every module must appear in every update — the jitted loss
        # traces over all module ids).
        n_min = min(counts.values())
        n_mb = max(1, n_min // min(c.minibatch_size, n_min))
        rng = np.random.RandomState(self._iteration)
        metrics: Dict[str, float] = {}
        for _ in range(c.num_epochs):
            perms = {mid: rng.permutation(n) for mid, n in counts.items()}
            for j in range(n_mb):
                mb = {}
                for mid, b in batches.items():
                    size = counts[mid] // n_mb
                    size = max(n_learners, size - size % n_learners)
                    idx = perms[mid][j * size:(j + 1) * size]
                    mb[mid] = {k: v[idx] for k, v in b.items()}
                metrics = self.learner_group.update(mb)
        self._sync_weights()
        # Honest accounting: env steps = what the runners stepped;
        # agent steps = active (mask=1) rows actually trained on.
        metrics["num_env_steps_sampled"] = (
            steps_per_runner * c.num_env_runners * c.num_envs_per_runner)
        metrics["num_agent_steps_sampled"] = int(sum(
            b["mask"].sum() for b in batches.values()))
        return metrics


def _gae(rew: np.ndarray, vf: np.ndarray, dones: np.ndarray,
         last_vf: np.ndarray, gamma: float, lam: float,
         mask: Optional[np.ndarray] = None) -> np.ndarray:
    """Backward GAE over time-major [T, N] lanes.

    With `mask`, rows where mask==0 (agent not acting that step — allowed
    by the MultiAgentEnv contract for turn-based envs) are transparent:
    the (next_v, next_adv) carry passes through unchanged, so an agent's
    advantage bootstraps from its own NEXT acted step, never from the
    stale vf recorded during the gap."""
    T, N = rew.shape
    adv = np.zeros((T, N), np.float32)
    next_adv = np.zeros(N, np.float32)
    next_v = np.asarray(last_vf, np.float32)
    for t in reversed(range(T)):
        nonterm = 1.0 - dones[t].astype(np.float32)
        delta = rew[t] + gamma * next_v * nonterm - vf[t]
        new_adv = delta + gamma * lam * nonterm * next_adv
        if mask is None:
            next_adv = new_adv
            next_v = vf[t]
            adv[t] = new_adv
        else:
            m = mask[t]
            next_adv = m * new_adv + (1.0 - m) * next_adv
            next_v = m * vf[t] + (1.0 - m) * next_v
            adv[t] = new_adv * m
    return adv


def _build_ppo_batch(rollouts: List[Dict[str, np.ndarray]], gamma: float,
                     lam: float) -> Dict[str, np.ndarray]:
    """GAE over time-major fragments, flattened + advantage-normalized."""
    obs, actions, logp, adv_all, targets_all = [], [], [], [], []
    for ro in rollouts:
        rew, vf, dones = ro["rewards"], ro["vf"], ro["dones"]
        T, N = rew.shape
        adv = _gae(rew, vf, dones, ro["last_vf"], gamma, lam)
        targets = adv + vf
        obs.append(ro["obs"].reshape(T * N, -1))
        act = ro["actions"]
        actions.append(act.reshape((T * N,) + act.shape[2:]))
        logp.append(ro["logp"].reshape(T * N))
        adv_all.append(adv.reshape(T * N))
        targets_all.append(targets.reshape(T * N))

    advantages = np.concatenate(adv_all)
    advantages = (advantages - advantages.mean()) / (advantages.std() + 1e-8)
    return {
        "obs": np.concatenate(obs).astype(np.float32),
        "actions": _cast_actions(np.concatenate(actions)),
        "logp_old": np.concatenate(logp).astype(np.float32),
        "advantages": advantages.astype(np.float32),
        "value_targets": np.concatenate(targets_all).astype(np.float32),
    }


def _cast_actions(a: np.ndarray) -> np.ndarray:
    """int32 for discrete, float32 for continuous (Box) actions."""
    return a.astype(np.int32 if np.issubdtype(a.dtype, np.integer)
                    else np.float32)


def _build_multi_agent_ppo_batch(rollouts, gamma: float, lam: float
                                 ) -> Dict[str, Dict[str, np.ndarray]]:
    """Per-module GAE over masked rectangular lanes.

    Masked (inactive-lane) rows stay in the batch with mask=0 so every
    minibatch has a static shape; `_gae` carries the bootstrap through
    masked gaps so turn-based agents bootstrap from their own next acted
    step."""
    out: Dict[str, Dict[str, np.ndarray]] = {}
    per_module: Dict[str, List[Dict[str, np.ndarray]]] = {}
    for ro in rollouts:
        for mid, frag in ro["modules"].items():
            per_module.setdefault(mid, []).append(frag)

    for mid, frags in per_module.items():
        obs, actions, logp, adv_all, targets_all, masks = [], [], [], [], [], []
        for fr in frags:
            rew, vf, dones, mask = (fr["rewards"], fr["vf"], fr["dones"],
                                    fr["mask"])
            T, L = rew.shape
            adv = _gae(rew, vf, dones, fr["last_vf"], gamma, lam, mask=mask)
            targets = adv + vf
            # A lane inactive at the fragment end (turn-based gap) has no
            # successor value for its last acted row — last_vf is V(that
            # same obs), a biased bootstrap.  Drop that one row from
            # training rather than train on it (mask copy: the GAE above
            # already used the true mask for carry transparency).
            mask = mask.copy()
            for lane in range(L):
                col = mask[:, lane]
                if col[-1] == 0 and col.any():
                    t_star = int(np.nonzero(col)[0][-1])
                    if not dones[t_star, lane]:
                        mask[t_star, lane] = 0.0
            obs.append(fr["obs"].reshape(T * L, -1))
            act = fr["actions"]
            actions.append(act.reshape((T * L,) + act.shape[2:]))
            logp.append(fr["logp"].reshape(T * L))
            adv_all.append((adv * mask).reshape(T * L))
            targets_all.append(targets.reshape(T * L))
            masks.append(mask.reshape(T * L))
        m = np.concatenate(masks).astype(np.float32)
        advantages = np.concatenate(adv_all)
        denom = max(m.sum(), 1.0)
        mean = (advantages * m).sum() / denom
        std = np.sqrt(((advantages - mean) ** 2 * m).sum() / denom) + 1e-8
        out[mid] = {
            "obs": np.concatenate(obs).astype(np.float32),
            "actions": _cast_actions(np.concatenate(actions)),
            "logp_old": np.concatenate(logp).astype(np.float32),
            "advantages": ((advantages - mean) / std * m).astype(np.float32),
            "value_targets": np.concatenate(targets_all).astype(np.float32),
            "mask": m,
        }
    return out
