"""PPO — clipped-surrogate policy optimization.

Reference: `rllib/algorithms/ppo/ppo.py:403` (training_step: sample →
learner_group.update_from_episodes → sync_weights) and
`ppo/ppo_learner.py` (clipped surrogate + clipped value loss + entropy
bonus, minibatch SGD epochs). GAE computed driver-side in numpy; the
update is the Learner's single pjit'd SPMD step.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner


class PPOLearner(Learner):
    def compute_loss(self, params, batch, rng):
        cfg = self.config
        clip = cfg.get("clip_param", 0.2)
        vf_clip = cfg.get("vf_clip_param", 10.0)
        vf_coeff = cfg.get("vf_loss_coeff", 0.5)
        ent_coeff = cfg.get("entropy_coeff", 0.0)

        out = self.module.forward_train(params, batch["obs"])
        logits = out["action_logits"]
        logp_all = jax.nn.log_softmax(logits)
        actions = batch["actions"].astype(jnp.int32)
        logp = jnp.take_along_axis(
            logp_all, actions[:, None], axis=-1)[:, 0]

        ratio = jnp.exp(logp - batch["logp_old"])
        adv = batch["advantages"]
        surrogate = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * adv)
        policy_loss = -surrogate.mean()

        vf_err = jnp.clip((out["vf"] - batch["value_targets"]) ** 2,
                          0.0, vf_clip ** 2)
        vf_loss = vf_err.mean()

        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        total = policy_loss + vf_coeff * vf_loss - ent_coeff * entropy
        return total, {
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "mean_kl": (batch["logp_old"] - logp).mean(),
        }


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        # Clips the squared value error; keep high — tight clips saturate
        # the vf gradient on environments with returns in the hundreds
        # (measured: vf_clip=10 stalls CartPole at ~300 return).
        self.vf_clip_param = 1000.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.gae_lambda = 0.95
        self.num_epochs = 8
        self.minibatch_size = 256
        self.lr = 3e-4

    algo_class = property(lambda self: PPO)


class PPO(Algorithm):
    learner_class = PPOLearner

    def _learner_config(self) -> Dict[str, Any]:
        cfg = super()._learner_config()
        cfg.update(clip_param=self.config.clip_param,
                   vf_clip_param=self.config.vf_clip_param,
                   vf_loss_coeff=self.config.vf_loss_coeff,
                   entropy_coeff=self.config.entropy_coeff)
        return cfg

    # -------------------------------------------------------------- step
    def training_step(self) -> Dict[str, Any]:
        c = self.config
        lanes = c.num_env_runners * c.num_envs_per_runner
        steps_per_runner = max(1, c.train_batch_size // lanes)

        rollouts = self.sample_batch(steps_per_runner)
        batch = _build_ppo_batch(rollouts, c.gamma, c.gae_lambda)

        n = len(batch["obs"])
        mb = min(c.minibatch_size, n)
        # Keep minibatches even across learners (SPMD lockstep), but never
        # round down to zero.
        n_learners = max(1, self.learner_group.num_learners)
        mb = max(n_learners, mb - mb % n_learners)
        rng = np.random.RandomState(self._iteration)
        metrics: Dict[str, float] = {}
        for _ in range(c.num_epochs):
            perm = rng.permutation(n)
            for lo in range(0, n - mb + 1, mb):
                idx = perm[lo:lo + mb]
                metrics = self.learner_group.update(
                    {k: v[idx] for k, v in batch.items()})
        self._sync_weights()
        metrics["num_env_steps_sampled"] = n
        return metrics


def _build_ppo_batch(rollouts: List[Dict[str, np.ndarray]], gamma: float,
                     lam: float) -> Dict[str, np.ndarray]:
    """GAE over time-major fragments, flattened + advantage-normalized."""
    obs, actions, logp, adv_all, targets_all = [], [], [], [], []
    for ro in rollouts:
        rew, vf, dones = ro["rewards"], ro["vf"], ro["dones"]
        T, N = rew.shape
        adv = np.zeros((T, N), np.float32)
        next_adv = np.zeros(N, np.float32)
        next_v = ro["last_vf"]
        for t in reversed(range(T)):
            nonterm = 1.0 - dones[t].astype(np.float32)
            delta = rew[t] + gamma * next_v * nonterm - vf[t]
            next_adv = delta + gamma * lam * nonterm * next_adv
            adv[t] = next_adv
            next_v = vf[t]
        targets = adv + vf
        obs.append(ro["obs"].reshape(T * N, -1))
        actions.append(ro["actions"].reshape(T * N))
        logp.append(ro["logp"].reshape(T * N))
        adv_all.append(adv.reshape(T * N))
        targets_all.append(targets.reshape(T * N))

    advantages = np.concatenate(adv_all)
    advantages = (advantages - advantages.mean()) / (advantages.std() + 1e-8)
    return {
        "obs": np.concatenate(obs).astype(np.float32),
        "actions": np.concatenate(actions).astype(np.int32),
        "logp_old": np.concatenate(logp).astype(np.float32),
        "advantages": advantages.astype(np.float32),
        "value_targets": np.concatenate(targets_all).astype(np.float32),
    }
