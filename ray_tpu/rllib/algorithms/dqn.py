"""DQN — double Q-learning with a replay buffer and target network.

Reference: `rllib/algorithms/dqn/dqn.py` (training_step: sample →
replay-buffer add → N TD updates → periodic target sync) and
`dqn/dqn_rainbow_learner.py` (double-Q TD loss). TPU-first shape: the
target network is an extra entry in the learner's jitted state pytree,
the TD update is one pjit'd step, and epsilon rides inside the weight
pytree so env runners need no extra plumbing.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import RLModule
from ray_tpu.rllib.env.spaces import Box, Discrete


class QModule(RLModule):
    """Q-network: forward_train returns {"q": [B, A]}; exploration is
    epsilon-greedy with epsilon carried IN the param pytree (the driver
    anneals it, weight sync ships it to runners for free)."""

    def __init__(self, observation_space: Box, action_space: Discrete,
                 hidden: Sequence[int] = (64, 64)):
        import flax.linen as nn

        obs_dim = int(np.prod(observation_space.shape))
        n_actions = action_space.n

        class _Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                h = x
                for width in hidden:
                    h = nn.relu(nn.Dense(width)(h))
                return nn.Dense(n_actions)(h)

        self._net = _Net()
        self._obs_dim = obs_dim
        self._n_actions = n_actions

    def init(self, rng: jax.Array) -> Any:
        dummy = jnp.zeros((1, self._obs_dim), jnp.float32)
        return {"net": self._net.init(rng, dummy),
                "epsilon": jnp.asarray(1.0, jnp.float32)}

    def forward_train(self, params, obs):
        q = self._net.apply(params["net"], obs)
        return {"q": q, "action_logits": q, "vf": q.max(axis=-1)}

    def forward_exploration(self, params, obs, rng):
        q = self._net.apply(params["net"], obs)
        greedy = jnp.argmax(q, axis=-1)
        k_eps, k_act = jax.random.split(rng)
        random_a = jax.random.randint(k_act, greedy.shape, 0,
                                      self._n_actions)
        explore = jax.random.uniform(k_eps, greedy.shape) < params["epsilon"]
        actions = jnp.where(explore, random_a, greedy)
        return {"actions": actions,
                "logp": jnp.zeros_like(q[..., 0]),
                "vf": q.max(axis=-1)}


class DQNLearner(Learner):
    def init_extra_state(self, params) -> Dict[str, Any]:
        # Distinct buffers: the update donates the whole state, and XLA
        # rejects donating one buffer twice (params aliasing target).
        return {"target": jax.tree.map(jnp.copy, params)}

    def sync_target(self) -> bool:
        """Snapshot online params as the target network."""
        self._state["target"] = jax.tree.map(jnp.copy,
                                             self._state["params"])
        return True

    def compute_loss_from_state(self, state, batch, rng):
        gamma = self.config.get("gamma", 0.99)
        q_all = self.module.forward_train(state["params"],
                                          batch["obs"])["q"]
        q = jnp.take_along_axis(
            q_all, batch["actions"].astype(jnp.int32)[:, None], -1)[:, 0]

        # Double DQN: online net picks the argmax, target net scores it.
        q_next_online = self.module.forward_train(
            state["params"], batch["next_obs"])["q"]
        a_star = jnp.argmax(q_next_online, axis=-1)
        q_next_target = self.module.forward_train(
            state["target"], batch["next_obs"])["q"]
        q_star = jnp.take_along_axis(q_next_target, a_star[:, None], -1)[:, 0]
        td_target = batch["rewards"] + gamma * (
            1.0 - batch["dones"].astype(jnp.float32)
        ) * jax.lax.stop_gradient(q_star)

        err = q - jax.lax.stop_gradient(td_target)
        huber = jnp.where(jnp.abs(err) <= 1.0, 0.5 * err * err,
                          jnp.abs(err) - 0.5)
        loss = huber.mean()
        return loss, {"td_loss": loss, "q_mean": q.mean()}


class ReplayBuffer:
    """Uniform ring buffer over flat transitions (driver-side numpy;
    reference: `utils/replay_buffers/`)."""

    def __init__(self, capacity: int, obs_shape, action_shape=(),
                 action_dtype=np.int32):
        self._cap = capacity
        self._obs = np.zeros((capacity, *obs_shape), np.float32)
        self._next_obs = np.zeros((capacity, *obs_shape), np.float32)
        self._actions = np.zeros((capacity, *action_shape), action_dtype)
        self._rewards = np.zeros((capacity,), np.float32)
        self._dones = np.zeros((capacity,), np.float32)
        self._idx = 0
        self._size = 0

    def __len__(self) -> int:
        return self._size

    def add_batch(self, obs, actions, rewards, next_obs, dones) -> None:
        n = len(obs)
        if n > self._cap:    # keep only the newest capacity-full
            obs, actions = obs[-self._cap:], actions[-self._cap:]
            rewards, next_obs = rewards[-self._cap:], next_obs[-self._cap:]
            dones = dones[-self._cap:]
            n = self._cap
        idx = (self._idx + np.arange(n)) % self._cap
        self._obs[idx] = obs
        self._next_obs[idx] = next_obs
        self._actions[idx] = actions
        self._rewards[idx] = rewards
        self._dones[idx] = dones
        self._idx = int((self._idx + n) % self._cap)
        self._size = min(self._size + n, self._cap)

    def sample(self, n: int, rng: np.random.RandomState
               ) -> Dict[str, np.ndarray]:
        idx = rng.randint(0, self._size, n)
        return {
            "obs": self._obs[idx], "next_obs": self._next_obs[idx],
            "actions": self._actions[idx], "rewards": self._rewards[idx],
            "dones": self._dones[idx],
        }


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.buffer_capacity = 50_000
        self.learning_starts = 500
        self.train_batch_size = 64
        self.rollout_fragment_length = 16
        self.num_updates_per_iteration = 32
        self.target_update_freq = 200       # in gradient updates
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_decay_steps = 4000     # in env steps

    algo_class = property(lambda self: DQN)


class DQN(Algorithm):
    learner_class = DQNLearner
    rl_module_class = QModule

    def __init__(self, config: DQNConfig):
        super().__init__(config)
        self._buffer = self._make_buffer()
        self._rng = np.random.RandomState(config.seed)
        self._env_steps = 0
        self._updates = 0

    def _make_buffer(self):
        """Factory hook (Rainbow swaps in prioritized replay; a hook, not
        allocate-then-replace — capacity-sized arrays are too big to
        build twice)."""
        return ReplayBuffer(self.config.buffer_capacity,
                            self.module_spec.observation_space.shape)

    def _learner_config(self) -> Dict[str, Any]:
        out = super()._learner_config()
        out["gamma"] = self.config.gamma
        return out

    def _eval_weights(self, weights):
        """Eval runners explore with the CURRENT annealed epsilon (when
        evaluation_explore=True); the raw learner pytree still carries the
        untrained init value 1.0 — shipping that would evaluate a
        uniformly random policy."""
        weights = dict(weights)
        weights["epsilon"] = np.asarray(self._epsilon(), np.float32)
        return weights

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._env_steps / max(cfg.epsilon_decay_steps, 1))
        return float(cfg.epsilon_initial
                     + frac * (cfg.epsilon_final - cfg.epsilon_initial))

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        rollouts = self.sample_batch(cfg.rollout_fragment_length)
        for ro in rollouts:
            T, N = ro["actions"].shape
            self._env_steps += T * N
            flat = lambda a: a.reshape(T * N, *a.shape[2:])  # noqa: E731
            # True successor states + env-true terminations: bootstraps
            # through time-limit truncations and never aliases a reset
            # obs as next_obs (see EnvRunner.sample).
            self._buffer.add_batch(flat(ro["obs"]), flat(ro["actions"]),
                                   flat(ro["rewards"]),
                                   flat(ro["next_obs"]),
                                   flat(ro["terminateds"]))

        metrics: Dict[str, Any] = {"env_steps": self._env_steps,
                                   "buffer_size": len(self._buffer),
                                   "epsilon": self._epsilon()}
        if len(self._buffer) >= cfg.learning_starts:
            for _ in range(cfg.num_updates_per_iteration):
                batch = self._buffer.sample(cfg.train_batch_size, self._rng)
                metrics.update(self.learner_group.update(batch))
                self._updates += 1
                if self._updates % cfg.target_update_freq == 0:
                    self.learner_group.foreach_learner("sync_target")
        # Ship annealed epsilon with the weights (same override as eval).
        self._sync_weights(
            self._eval_weights(self.learner_group.get_weights()))
        metrics["num_gradient_updates"] = self._updates
        return metrics
