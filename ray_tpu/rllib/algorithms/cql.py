"""CQL — Conservative Q-Learning, offline RL for continuous control.

Reference: `rllib/algorithms/cql/cql.py:1` + `cql/cql_learner.py` (SAC
trained purely from a fixed dataset, with the CQL(H) conservative
regularizer pushing Q down on out-of-distribution actions and up on
dataset actions, so the squashed-Gaussian actor cannot exploit Q-value
extrapolation error). TPU-first shape reuses SAC's single-pytree state:
the whole update — twin-critic TD loss + CQL penalty over N sampled
actions + actor + alpha losses + polyak targets — is one jitted,
donated XLA call; the N-action Q evaluations batch as one big matmul
(B*3N rows through the critic) instead of a Python loop.

Offline ingestion streams from `ray_tpu.data` (parquet shards via
`offline.DatasetReader`) or an in-memory row list — closing the
JSONL-only gap (VERDICT r4 weak-7).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import AlgorithmConfig
from ray_tpu.rllib.algorithms.sac import SACLearner, SACModule
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.learner_group import LearnerGroup
from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.env.cartpole import make_env
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.jax_backend import JaxConfig


class CQLLearner(SACLearner):
    """SAC losses + the CQL(H) penalty on both critics."""

    def compute_loss_from_state(self, state, batch, rng):
        cfg = self.config
        n = cfg.get("cql_n_actions", 10)
        cql_alpha = cfg.get("cql_alpha", 5.0)
        m: SACModule = self.module
        params = state["params"]

        k_sac, k_rand, k_pi, k_pi_next = jax.random.split(rng, 4)
        sac_loss, metrics = super().compute_loss_from_state(
            state, batch, k_sac)

        obs, acts = batch["obs"], batch["actions"]
        B = obs.shape[0]
        act_dim = acts.shape[-1]
        scale = jnp.asarray(m._act_scale)

        def q_of(a_flat, obs_rep):
            q1, q2 = m.q_values(params, obs_rep, a_flat)
            return q1.reshape(B, n), q2.reshape(B, n)

        obs_rep = jnp.repeat(obs, n, axis=0)
        next_rep = jnp.repeat(batch["next_obs"], n, axis=0)

        # (a) uniform random actions; density 1/(2*scale)^d.
        a_rand = jax.random.uniform(
            k_rand, (B * n, act_dim), minval=-1.0, maxval=1.0) * scale
        logp_rand = -act_dim * jnp.log(2.0) - jnp.log(scale).sum()
        # (b) current-policy actions at s and s' with their log-probs
        # (importance-corrected logsumexp, the CQL(H) estimator).
        # sample_action's logp is the density BEFORE the `* act_scale`
        # stretch; subtract the Jacobian so all three families measure
        # the SCALED action (same measure as logp_rand).
        log_scale_jac = jnp.log(scale).sum()
        actor_sg = jax.lax.stop_gradient(params["actor"])
        a_pi, logp_pi = m.sample_action(actor_sg, obs_rep, k_pi)
        a_pin, logp_pin = m.sample_action(actor_sg, next_rep, k_pi_next)
        logp_pi = logp_pi - log_scale_jac
        logp_pin = logp_pin - log_scale_jac

        cat_q1, cat_q2 = [], []
        for a_flat, logp in ((a_rand, logp_rand), (a_pi, logp_pi),
                             (a_pin, logp_pin)):
            q1, q2 = q_of(a_flat, obs_rep)
            lp = (jnp.broadcast_to(logp, (B * n,)).reshape(B, n)
                  if jnp.ndim(logp) else jnp.full((B, n), logp))
            cat_q1.append(q1 - lp)
            cat_q2.append(q2 - lp)
        cat_q1 = jnp.concatenate(cat_q1, axis=1)
        cat_q2 = jnp.concatenate(cat_q2, axis=1)

        q1_data, q2_data = m.q_values(params, obs, acts)
        gap1 = jax.nn.logsumexp(cat_q1, axis=1) - q1_data
        gap2 = jax.nn.logsumexp(cat_q2, axis=1) - q2_data
        cql_loss = cql_alpha * (gap1.mean() + gap2.mean())

        metrics = dict(metrics)
        metrics["cql_loss"] = cql_loss
        metrics["cql_gap"] = (gap1.mean() + gap2.mean()) / 2.0
        return sac_loss + cql_loss, metrics


class ContinuousBCLearner(Learner):
    """MSE behavior cloning over the SAC actor — the offline baseline
    CQL is measured against (discrete BC lives in `bc.py`)."""

    def compute_loss(self, params, batch, rng):
        m: SACModule = self.module
        mean, _ = m._actor.apply(params["actor"], batch["obs"])
        pred = jnp.tanh(mean) * jnp.asarray(m._act_scale)
        loss = ((pred - batch["actions"]) ** 2).mean()
        return loss, {"bc_mse": loss}


class CQLConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.env = "Pendulum-v1"
        self.lr = 3e-4
        self.grad_clip = 10.0
        self.tau = 0.005
        self.train_batch_size = 256
        self.num_batches_per_iteration = 64
        self.cql_alpha = 5.0
        self.cql_n_actions = 10
        self.target_entropy = None
        self.dataset = None   # ray_tpu.data.Dataset | path | list of rows

    def offline_data(self, dataset) -> "CQLConfig":
        self.dataset = dataset
        return self

    algo_class = property(lambda self: CQL)


class CQL:
    """Offline algorithm: no env runners; `train()` consumes the
    configured dataset (parquet path, Data pipeline, or rows)."""

    learner_class = CQLLearner

    def __init__(self, config: CQLConfig):
        if config.dataset is None:
            raise ValueError("CQLConfig.offline_data(dataset) is required")
        if isinstance(config.dataset, str):
            from ray_tpu.rllib.offline.io import DatasetReader

            config.dataset = DatasetReader(config.dataset).dataset
        probe_env = make_env(config.env)
        self.config = config
        self.module_spec = RLModuleSpec(
            observation_space=probe_env.observation_space,
            action_space=probe_env.action_space,
            hidden=config.module_hidden,
            module_class=SACModule)
        self.learner_group = LearnerGroup(
            self.learner_class, self.module_spec,
            learner_config=self._learner_config(),
            scaling_config=ScalingConfig(num_workers=config.num_learners),
            jax_config=JaxConfig(platform=config.jax_platform))
        self._iteration = 0
        self._batch_iter: Optional[Iterator] = None

    def _learner_config(self) -> Dict[str, Any]:
        cfg = self.config
        act_dim = int(np.prod(self.module_spec.action_space.shape))
        return {"lr": cfg.lr, "grad_clip": cfg.grad_clip,
                "seed": cfg.seed, "gamma": cfg.gamma, "tau": cfg.tau,
                "cql_alpha": cfg.cql_alpha,
                "cql_n_actions": cfg.cql_n_actions,
                "target_entropy": (cfg.target_entropy
                                   if cfg.target_entropy is not None
                                   else -float(act_dim))}

    # ------------------------------------------------------------ ingestion
    _batch_columns = (("obs", np.float32), ("actions", np.float32),
                      ("rewards", np.float32), ("next_obs", np.float32),
                      ("terminateds", np.float32))

    def _batches(self) -> Iterator[Dict[str, np.ndarray]]:
        ds = self.config.dataset
        bs = self.config.train_batch_size
        cols = self._batch_columns

        def clean(batch):
            out = {}
            for k, dt in cols:
                if k not in batch:
                    raise ValueError(f"CQL needs a '{k}' column "
                                     f"(got {sorted(batch)})")
                v = batch[k]
                if getattr(v, "dtype", None) == object:
                    v = np.stack([np.asarray(x, dt) for x in v])
                out[k] = np.asarray(v, dt)
            # SAC's TD target keys.
            out["dones"] = out.pop("terminateds")
            if out["actions"].ndim == 1:
                out["actions"] = out["actions"][:, None]
            return out

        if hasattr(ds, "iter_batches"):       # ray_tpu.data.Dataset
            epoch = 0
            while True:
                # Local shuffle: without it, parquet-backed training
                # would see temporally-correlated consecutive
                # transitions each epoch while the rows path samples
                # i.i.d. — results must not differ by ingestion format.
                for batch in ds.iter_batches(
                        batch_size=bs, batch_format="numpy",
                        drop_last=True,
                        local_shuffle_buffer_size=max(4 * bs, 1024),
                        local_shuffle_seed=self.config.seed + epoch):
                    yield clean(batch)
                epoch += 1
        else:
            rows = list(ds)
            arrays = {k: [r[k] for r in rows] for k, _ in cols}
            rng = np.random.RandomState(self.config.seed)
            while True:
                idx = rng.randint(0, len(rows), bs)
                yield clean({k: np.asarray(v, object)[idx]
                             if isinstance(v[0], (list, np.ndarray))
                             else np.asarray(v)[idx]
                             for k, v in arrays.items()})

    # ------------------------------------------------------------ training
    def train(self) -> Dict[str, Any]:
        self._iteration += 1
        if self._batch_iter is None:
            self._batch_iter = self._batches()
        metrics: Dict[str, Any] = {}
        for _ in range(self.config.num_batches_per_iteration):
            metrics.update(self.learner_group.update(
                next(self._batch_iter)))
        metrics["training_iteration"] = self._iteration
        return metrics

    def get_policy_params(self):
        return self.learner_group.get_weights()

    def evaluate(self, num_episodes: int = 10) -> Dict[str, float]:
        """Deterministic (tanh-mean) rollouts in the probe env."""
        module = self.module_spec.build()
        params = self.get_policy_params()
        from ray_tpu.observability.jit import tracked_jit

        fwd = tracked_jit(module.forward_train, name="cql_eval_fwd")
        returns = []
        env = make_env(self.config.env, seed=self.config.seed + 999)
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=self.config.seed + ep)
            total, done = 0.0, False
            while not done:
                out = fwd(params, obs[None].astype(np.float32))
                act = np.asarray(out["actions"])[0]
                obs, r, term, trunc, _ = env.step(act)
                total += r
                done = term or trunc
            returns.append(total)
        return {"episode_return_mean": float(np.mean(returns)),
                "num_episodes": num_episodes}


class ContinuousBC(CQL):
    """beta-0 baseline: pure MSE cloning on the same offline pipeline
    (reference: BC over `MARWILConfig(beta=0)`)."""

    learner_class = ContinuousBCLearner

    def _learner_config(self) -> Dict[str, Any]:
        return {"lr": self.config.lr, "grad_clip": self.config.grad_clip,
                "seed": self.config.seed}
