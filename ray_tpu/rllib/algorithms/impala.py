"""IMPALA — asynchronous actor-learner with V-trace off-policy correction.

Reference: `rllib/algorithms/impala/impala.py:667` (training_step: async
sampling + learner updates) and the V-trace returns of `impala/vtrace.py`.
TPU-first shape: env runners sample continuously (futures resubmitted as
they land, never a barrier), the learner consumes whatever rollouts are
ready, and the staleness between behavior and target policy is exactly
what the V-trace rho/c clipping corrects. The V-trace recursion is a
`lax.scan` over reversed time inside the jitted update — no Python loop.
"""

from __future__ import annotations

from typing import Any, Dict, List

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm, AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner


def vtrace(behavior_logp, target_logp, rewards, dones, values,
           bootstrap_value, gamma: float,
           rho_bar: float = 1.0, c_bar: float = 1.0):
    """V-trace targets (Espeholt et al. 2018, eqs. 1-2). All inputs
    time-major [T, B]; returns (vs [T, B], pg_advantages [T, B])."""
    rho = jnp.minimum(jnp.exp(target_logp - behavior_logp), rho_bar)
    c = jnp.minimum(jnp.exp(target_logp - behavior_logp), c_bar)
    discounts = gamma * (1.0 - dones.astype(jnp.float32))

    values_next = jnp.concatenate(
        [values[1:], bootstrap_value[None]], axis=0)
    deltas = rho * (rewards + discounts * values_next - values)

    def backwards(acc, t):
        acc = deltas[t] + discounts[t] * c[t] * acc
        return acc, acc

    T = rewards.shape[0]
    _, vs_minus_v = jax.lax.scan(
        backwards, jnp.zeros_like(bootstrap_value),
        jnp.arange(T - 1, -1, -1))
    vs_minus_v = vs_minus_v[::-1]
    vs = values + vs_minus_v

    vs_next = jnp.concatenate([vs[1:], bootstrap_value[None]], axis=0)
    pg_adv = rho * (rewards + discounts * vs_next - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


class IMPALALearner(Learner):
    def _vtrace_prep(self, params, batch):
        """Shared forward + V-trace plumbing (also the base of APPO's
        clipped loss): returns time-major (behavior_logp, target_logp,
        values, vs, pg_adv) plus logp_all for the entropy term."""
        cfg = self.config
        gamma = cfg.get("gamma", 0.99)

        # Batch arrives batch-major [B, T, ...]: dim 0 is sharded over the
        # mesh, so the network flattens (B*T) keeping the sharded dim
        # major (a [T,B]->[T*B] merge would be an illegal sharded
        # reshape); only the small per-step tensors transpose to
        # time-major for the V-trace scan.
        obs = batch["obs"]                                   # [B, T, obs]
        actions = batch["actions"].astype(jnp.int32)         # [B, T]
        B, T = actions.shape
        out = self.module.forward_train(params, obs.reshape(B * T, -1))
        logits = out["action_logits"].reshape(B, T, -1)
        values_bt = out["vf"].reshape(B, T)
        logp_all = jax.nn.log_softmax(logits)
        target_logp_bt = jnp.take_along_axis(
            logp_all, actions[..., None], axis=-1)[..., 0]

        behavior_logp = batch["logp"].T                      # [T, B]
        target_logp = target_logp_bt.T
        values = values_bt.T
        vs, pg_adv = vtrace(
            behavior_logp, target_logp, batch["rewards"].T,
            batch["dones"].T, values, batch["bootstrap_value"], gamma,
            cfg.get("rho_bar", 1.0), cfg.get("c_bar", 1.0))
        return behavior_logp, target_logp, values, vs, pg_adv, logp_all

    def compute_loss(self, params, batch, rng):
        cfg = self.config
        vf_coeff = cfg.get("vf_loss_coeff", 0.5)
        ent_coeff = cfg.get("entropy_coeff", 0.01)
        (behavior_logp, target_logp, values, vs, pg_adv,
         logp_all) = self._vtrace_prep(params, batch)

        policy_loss = -(target_logp * pg_adv).mean()
        vf_loss = 0.5 * ((values - vs) ** 2).mean()
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        total = policy_loss + vf_coeff * vf_loss - ent_coeff * entropy
        return total, {
            "policy_loss": policy_loss, "vf_loss": vf_loss,
            "entropy": entropy,
            "mean_rho": jnp.exp(target_logp - behavior_logp).mean(),
        }


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 5e-4
        self.rollout_fragment_length = 32
        self.num_rollouts_per_iteration = 8
        # Rollouts concatenated per SGD step: the batch-major dim (total
        # env lanes) must divide the learner mesh's device count.
        self.num_rollouts_per_update = 2
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.rho_bar = 1.0
        self.c_bar = 1.0

    algo_class = property(lambda self: IMPALA)


class IMPALA(Algorithm):
    learner_class = IMPALALearner

    def __init__(self, config: IMPALAConfig):
        super().__init__(config)
        if config.num_rollouts_per_update > config.num_rollouts_per_iteration:
            raise ValueError(
                "num_rollouts_per_update must be <= "
                "num_rollouts_per_iteration or no update ever fires")
        # Continuous sampling: one outstanding sample() per runner.
        self._inflight: Dict[Any, Any] = {}
        # Rollouts awaiting an SGD step; carried ACROSS iterations so a
        # partial group is never dropped.
        self._pending: List[Dict[str, np.ndarray]] = []
        for runner in self.env_runners:
            self._inflight[runner.sample.remote(
                config.rollout_fragment_length)] = runner

    def _learner_config(self) -> Dict[str, Any]:
        out = super()._learner_config()
        out.update(gamma=self.config.gamma,
                   vf_loss_coeff=self.config.vf_loss_coeff,
                   entropy_coeff=self.config.entropy_coeff,
                   rho_bar=self.config.rho_bar,
                   c_bar=self.config.c_bar)
        return out

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        decoupled = self.execution == "decoupled"
        metrics: Dict[str, Any] = {}
        consumed = 0
        throttled = 0
        behavior = 0
        kick = None
        if decoupled:
            # Kick consumers first; groups formed this iteration can
            # never undershoot this (the carried partial group only
            # adds), and any extra stays queued for the next kick.
            expected = max(1, cfg.num_rollouts_per_iteration
                           // cfg.num_rollouts_per_update)
            kick = self.learner_pool.kick(expected)
        pending = self._pending
        while consumed < cfg.num_rollouts_per_iteration:
            ready, _ = ray_tpu.wait(list(self._inflight),
                                    num_returns=1, timeout=120)
            if not ready:
                raise TimeoutError("no rollout arrived within 120s")
            ref = ready[0]
            runner = self._inflight.pop(ref)
            rollout = ray_tpu.get(ref, timeout=60)
            self._recent_returns.extend(rollout.pop("episode_returns"))
            behavior = max(behavior,
                           int(rollout.pop("weight_version", 0)))
            # Immediately resubmit — sampling never waits on learning.
            self._inflight[runner.sample.remote(
                cfg.rollout_fragment_length)] = runner

            pending.append({
                # [T, N, ...] -> batch-major [N, T, ...] for mesh sharding.
                "obs": np.swapaxes(rollout["obs"], 0, 1),
                "actions": np.swapaxes(rollout["actions"], 0, 1),
                "logp": np.swapaxes(rollout["logp"], 0, 1),
                "rewards": np.swapaxes(rollout["rewards"], 0, 1),
                "dones": np.swapaxes(rollout["dones"], 0, 1),
                "bootstrap_value": rollout["last_vf"],
            })
            consumed += 1
            if len(pending) >= cfg.num_rollouts_per_update:
                batch = {k: np.concatenate([p[k] for p in pending])
                         for k in pending[0]}
                pending.clear()
                if decoupled:
                    from ray_tpu.rllib.podracer import feed_queue

                    batch["weight_version"] = behavior
                    throttled += feed_queue(self.sample_queue, batch,
                                            timeout_s=5.0)
                else:
                    metrics.update(self.learner_group.update(batch))
        if decoupled:
            stats = self.learner_pool.join(kick)
            metrics.update(stats.get("last_metrics", {}))
            metrics.update(
                weight_version=stats["weight_version"],
                weight_staleness_max=stats["max_staleness"],
                dropped_stale=stats.get("dropped", 0),
                backpressure_waits=throttled)
        else:
            # Weight sync once per iteration: the gap IS the
            # off-policyness V-trace corrects. (Decoupled: the
            # WeightStore channel carries it instead, and the learner
            # pool's staleness clip bounds it.)
            self._sync_weights()
        metrics["num_rollouts"] = consumed
        return metrics

    def stop(self) -> None:
        self._inflight.clear()
        super().stop()
