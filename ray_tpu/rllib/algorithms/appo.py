"""APPO — asynchronous PPO: IMPALA's actor-learner architecture with the
PPO clipped-surrogate objective on V-trace advantages.

Reference: `rllib/algorithms/appo/appo.py` (+ `appo_learner.py` for the
clip-on-vtrace loss). Everything about sampling, batching, and weight
sync is inherited from the IMPALA implementation; only the policy loss
changes — ratio clipping bounds the update where V-trace's rho clipping
alone would still allow large steps on near-on-policy data.
"""

from __future__ import annotations

import jax.numpy as jnp

from ray_tpu.rllib.algorithms.impala import (
    IMPALA, IMPALAConfig, IMPALALearner,
)


class APPOLearner(IMPALALearner):
    def compute_loss(self, params, batch, rng):
        cfg = self.config
        vf_coeff = cfg.get("vf_loss_coeff", 0.5)
        ent_coeff = cfg.get("entropy_coeff", 0.01)
        clip = cfg.get("clip_param", 0.2)

        (behavior_logp, target_logp, values, vs, pg_adv,
         logp_all) = self._vtrace_prep(params, batch)
        adv = (pg_adv - pg_adv.mean()) / (pg_adv.std() + 1e-8)

        ratio = jnp.exp(target_logp - behavior_logp)
        surrogate = jnp.minimum(
            ratio * adv, jnp.clip(ratio, 1.0 - clip, 1.0 + clip) * adv)
        policy_loss = -surrogate.mean()
        vf_loss = 0.5 * ((values - vs) ** 2).mean()
        entropy = -(jnp.exp(logp_all) * logp_all).sum(-1).mean()
        total = policy_loss + vf_coeff * vf_loss - ent_coeff * entropy
        return total, {
            "policy_loss": policy_loss, "vf_loss": vf_loss,
            "entropy": entropy, "mean_ratio": ratio.mean(),
            "clip_frac": (jnp.abs(ratio - 1.0) > clip).mean(),
        }


class APPOConfig(IMPALAConfig):
    def __init__(self):
        super().__init__()
        self.clip_param = 0.2

    algo_class = property(lambda self: APPO)


class APPO(IMPALA):
    learner_class = APPOLearner

    def _learner_config(self):
        out = super()._learner_config()
        out["clip_param"] = self.config.clip_param
        return out
