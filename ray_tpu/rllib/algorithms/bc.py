"""BC — offline behavior cloning from a transition dataset.

Reference: `rllib/algorithms/bc/bc.py` (+ `marwil/marwil.py`, of which BC
is the beta=0 special case: plain negative-log-likelihood on the expert's
actions, no advantage weighting) and `rllib/offline/` for dataset-backed
training. Here the offline input is a `ray_tpu.data.Dataset` of
{"obs", "actions"} batches — the Data library streams/shuffles it and
the learner does supervised NLL updates; no env runners exist at all.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.learner_group import LearnerGroup
from ray_tpu.rllib.core.rl_module import RLModuleSpec
from ray_tpu.rllib.env.cartpole import make_env
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.jax_backend import JaxConfig


class BCLearner(Learner):
    def compute_loss(self, params, batch, rng):
        logits = self.module.forward_train(params,
                                           batch["obs"])["action_logits"]
        logp = jax.nn.log_softmax(logits)
        act = batch["actions"].astype(jnp.int32)
        nll = -jnp.take_along_axis(logp, act[:, None], -1)[:, 0]
        loss = nll.mean()
        acc = (jnp.argmax(logits, -1) == act).mean()
        return loss, {"bc_nll": loss, "bc_accuracy": acc}


class BCConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.train_batch_size = 256
        self.num_batches_per_iteration = 32
        self.dataset = None        # ray_tpu.data.Dataset | list of dicts

    def offline_data(self, dataset) -> "BCConfig":
        self.dataset = dataset
        return self

    algo_class = property(lambda self: BC)


class BC:
    """Offline algorithm: no env-runner fleet — `train()` consumes the
    configured dataset. The env is probed only for spaces."""

    learner_class = BCLearner

    def __init__(self, config: BCConfig):
        if config.dataset is None:
            raise ValueError("BCConfig.offline_data(dataset) is required")
        probe_env = make_env(config.env)
        self.config = config
        self.module_spec = RLModuleSpec(
            observation_space=probe_env.observation_space,
            action_space=probe_env.action_space,
            hidden=config.module_hidden)
        self.learner_group = LearnerGroup(
            self.learner_class, self.module_spec,
            learner_config=self._learner_config(),
            scaling_config=ScalingConfig(num_workers=config.num_learners),
            jax_config=JaxConfig(platform=config.jax_platform))
        self._iteration = 0
        self._batch_iter: Optional[Iterator] = None

    def _learner_config(self) -> Dict[str, Any]:
        return {"lr": self.config.lr, "grad_clip": self.config.grad_clip,
                "seed": self.config.seed}

    # ------------------------------------------------------------ ingestion
    # Columns each batch carries: (key, dtype); dtype None = keep as-is.
    # Subclasses extend (MARWIL adds "returns") instead of re-implementing
    # the two ingestion paths.
    _batch_columns = (("obs", np.float32), ("actions", None))

    def _batches(self) -> Iterator[Dict[str, np.ndarray]]:
        ds = self.config.dataset
        bs = self.config.train_batch_size
        cols = self._batch_columns
        if hasattr(ds, "iter_batches"):       # ray_tpu.data.Dataset
            while True:                        # epoch loop
                for batch in ds.iter_batches(batch_size=bs):
                    for k, _ in cols:
                        if k not in batch:
                            raise ValueError(
                                f"{type(self).__name__} over a Dataset "
                                f"needs a '{k}' column")
                    yield {k: np.asarray(batch[k], dt) if dt else
                           np.asarray(batch[k]) for k, dt in cols}
        else:                                  # in-memory list of rows
            rows = list(ds)
            arrays = {k: (np.asarray([r[k] for r in rows], dt) if dt else
                          np.asarray([r[k] for r in rows]))
                      for k, dt in cols}
            rng = np.random.RandomState(self.config.seed)
            while True:
                idx = rng.randint(0, len(rows), bs)
                yield {k: v[idx] for k, v in arrays.items()}

    # ------------------------------------------------------------ training
    def train(self) -> Dict[str, Any]:
        self._iteration += 1
        if self._batch_iter is None:
            self._batch_iter = self._batches()
        metrics: Dict[str, Any] = {}
        for _ in range(self.config.num_batches_per_iteration):
            metrics.update(self.learner_group.update(
                next(self._batch_iter)))
        metrics["training_iteration"] = self._iteration
        return metrics

    def get_policy_params(self):
        return self.learner_group.get_weights()

    def evaluate(self, num_episodes: int = 10) -> Dict[str, float]:
        """Greedy rollouts of the cloned policy in the probe env."""
        module = self.module_spec.build()
        params = self.get_policy_params()
        from ray_tpu.observability.jit import tracked_jit

        fwd = tracked_jit(module.forward_inference, name="bc_eval_fwd")
        returns: List[float] = []
        env = make_env(self.config.env, seed=self.config.seed + 999)
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=self.config.seed + ep)
            total, done = 0.0, False
            while not done:
                out = fwd(params, obs[None].astype(np.float32))
                obs, r, term, trunc, _ = env.step(
                    int(np.asarray(out["actions"])[0]))
                total += r
                done = term or trunc
            returns.append(total)
        return {"episode_return_mean": float(np.mean(returns)),
                "num_episodes": num_episodes}

    def stop(self) -> None:
        self.learner_group.shutdown()
