"""MARWIL — Monotonic Advantage Re-Weighted Imitation Learning.

Reference: `rllib/algorithms/marwil/marwil.py` + `marwil_learner` (an
offline algorithm: exponentially advantage-weighted behavior cloning with
a value head regressed on monte-carlo returns; beta=0 reduces it to plain
BC).  Deviation from the reference: the advantage normalizer is the
per-batch RMS instead of a persistent moving average — one line simpler
and equivalent in steady state for the shuffled offline batches the
trainer feeds.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.bc import BC, BCConfig
from ray_tpu.rllib.core.learner import Learner


class MARWILLearner(Learner):
    def compute_loss(self, params, batch, rng):
        beta = self.config.get("beta", 1.0)
        vf_coeff = self.config.get("vf_coeff", 1.0)

        out = self.module.forward_train(params, batch["obs"])
        logits = out["action_logits"]
        logp = jax.nn.log_softmax(logits)
        act = batch["actions"].astype(jnp.int32)
        nll = -jnp.take_along_axis(logp, act[:, None], -1)[:, 0]

        returns = batch["returns"]
        adv = returns - out["vf"]
        adv_sg = jax.lax.stop_gradient(adv)
        c = jnp.sqrt(jnp.mean(adv_sg ** 2)) + 1e-8
        # Exp-clip keeps one lucky episode from dominating the batch
        # (reference clips the weight at e^{~3}).
        weights = jnp.clip(jnp.exp(beta * adv_sg / c), 0.0, 20.0)

        policy_loss = jnp.mean(weights * nll)
        vf_loss = jnp.mean(adv ** 2)
        total = policy_loss + vf_coeff * vf_loss
        acc = (jnp.argmax(logits, -1) == act).mean()
        return total, {"policy_loss": policy_loss, "vf_loss": vf_loss,
                       "mean_weight": weights.mean(), "bc_accuracy": acc}


class MARWILConfig(BCConfig):
    def __init__(self):
        super().__init__()
        self.beta = 1.0
        self.vf_coeff = 1.0
        self.gamma = 0.99

    algo_class = property(lambda self: MARWIL)


class MARWIL(BC):
    """Offline advantage-weighted cloning.  Accepts the same inputs as BC
    plus reward signals: rows may carry a precomputed "returns", or
    "rewards" + "eps_id" (return-to-go computed here with config.gamma,
    matching `JsonReader.with_returns`)."""

    learner_class = MARWILLearner

    def __init__(self, config: MARWILConfig):
        ds = config.dataset
        if isinstance(ds, (list, tuple)) and ds and "returns" not in ds[0]:
            from ray_tpu.rllib.offline.io import compute_returns

            # Raises if rows carry neither rewards nor returns — silent
            # all-zero returns would degenerate the advantage weights.
            config.dataset = compute_returns(
                [dict(r) for r in ds], config.gamma)
        super().__init__(config)

    def _learner_config(self) -> Dict[str, Any]:
        return {"lr": self.config.lr, "grad_clip": self.config.grad_clip,
                "seed": self.config.seed, "beta": self.config.beta,
                "vf_coeff": self.config.vf_coeff}

    # ------------------------------------------------------------ ingestion
    # BC's two ingestion paths, plus the return-to-go column
    # (precompute via JsonReader.with_returns for Dataset inputs).
    _batch_columns = BC._batch_columns + (("returns", np.float32),)
