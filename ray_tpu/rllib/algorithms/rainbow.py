"""Rainbow DQN — distributional (C51) double-Q with dueling heads,
n-step returns and prioritized replay.

Reference: `rllib/algorithms/dqn/dqn.py` (the reference's DQN *is*
Rainbow-capable: `num_atoms`/`v_min`/`v_max`/`n_step`/`noisy`/dueling all
live on DQNConfig), `dqn/dqn_rainbow_learner.py` (categorical projection
loss) and `rllib/utils/replay_buffers/prioritized_episode_buffer.py`.
TPU-first shape: the categorical projection is a fully vectorized jitted
scatter-add (no per-atom Python loop), the dueling/C51 head is one flax
module, and per-sample priorities flow back from the jitted update as an
array metric so the driver-side PER buffer can be updated without a second
forward pass.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.dqn import DQN, DQNConfig, DQNLearner
from ray_tpu.rllib.core.rl_module import RLModule
from ray_tpu.rllib.env.spaces import Box, Discrete

PRIORITY_KEY = "per_sample_priorities"


def categorical_projection(next_probs: jax.Array, rewards: jax.Array,
                           not_terminal: jax.Array, discounts: jax.Array,
                           z: jax.Array, v_min: float,
                           v_max: float) -> jax.Array:
    """Project the Bellman-updated atom support back onto the fixed grid.

    C51 (Bellamare et al.): Tz = r + gamma^n * z, clipped to [v_min, v_max],
    with each atom's mass split linearly between its two neighbouring grid
    points. `discounts` carries the per-sample effective gamma^k (n-step
    fragments near an episode cut use fewer than n rewards).

    next_probs: [B, K] target distribution at the double-Q argmax action.
    Returns m: [B, K], the projected target distribution (rows sum to 1).
    """
    k = z.shape[0]
    delta = (v_max - v_min) / (k - 1)
    tz = jnp.clip(
        rewards[:, None] + not_terminal[:, None] * discounts[:, None] * z[None, :],
        v_min, v_max)
    b = (tz - v_min) / delta                      # fractional atom index
    # Dense triangle-kernel contraction instead of a scatter-add: source
    # atom k puts max(0, 1 - |b_k - j|) of its mass on grid atom j — the
    # exact linear split, with the on-grid case falling out naturally.
    # [B,K]x[B,K,K] einsum: batch-shardable, no gather/scatter, MXU-sized.
    kernel = jnp.clip(
        1.0 - jnp.abs(b[:, :, None] - jnp.arange(k)[None, None, :]),
        0.0, 1.0)
    return jnp.einsum("bk,bkj->bj", next_probs, kernel)


class RainbowModule(RLModule):
    """Dueling C51 head: value stream [K] + advantage stream [A, K],
    combined per-atom; Q(s,a) = sum_k p_k(s,a) * z_k. Exploration is
    epsilon-greedy over expected Q with epsilon carried in the param
    pytree (same weight-sync trick as QModule)."""

    def __init__(self, observation_space: Box, action_space: Discrete,
                 hidden: Sequence[int] = (64, 64), num_atoms: int = 51,
                 v_min: float = -10.0, v_max: float = 10.0,
                 dueling: bool = True):
        import flax.linen as nn

        obs_dim = int(np.prod(observation_space.shape))
        n_actions = action_space.n

        class _Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                h = x
                for width in hidden:
                    h = nn.relu(nn.Dense(width)(h))
                adv = nn.Dense(n_actions * num_atoms)(h).reshape(
                    (*h.shape[:-1], n_actions, num_atoms))
                if not dueling:
                    return adv
                val = nn.Dense(num_atoms)(h)[..., None, :]
                return val + adv - adv.mean(axis=-2, keepdims=True)

        self._net = _Net()
        self._obs_dim = obs_dim
        self._n_actions = n_actions
        self.v_min = float(v_min)
        self.v_max = float(v_max)
        self.z = jnp.linspace(v_min, v_max, num_atoms)

    def init(self, rng: jax.Array) -> Any:
        dummy = jnp.zeros((1, self._obs_dim), jnp.float32)
        return {"net": self._net.init(rng, dummy),
                "epsilon": jnp.asarray(1.0, jnp.float32)}

    def _dist_q(self, params, obs) -> Tuple[jax.Array, jax.Array]:
        logits = self._net.apply(params["net"], obs)    # [B, A, K]
        probs = jax.nn.softmax(logits, axis=-1)
        q = (probs * self.z).sum(-1)                    # [B, A]
        return logits, q

    def forward_train(self, params, obs):
        logits, q = self._dist_q(params, obs)
        return {"logits": logits, "q": q, "action_logits": q,
                "vf": q.max(axis=-1)}

    def forward_inference(self, params, obs):
        _, q = self._dist_q(params, obs)
        return {"actions": jnp.argmax(q, axis=-1)}

    def forward_exploration(self, params, obs, rng):
        _, q = self._dist_q(params, obs)
        greedy = jnp.argmax(q, axis=-1)
        k_eps, k_act = jax.random.split(rng)
        random_a = jax.random.randint(k_act, greedy.shape, 0,
                                      self._n_actions)
        explore = jax.random.uniform(k_eps, greedy.shape) < params["epsilon"]
        return {"actions": jnp.where(explore, random_a, greedy),
                "logp": jnp.zeros_like(q[..., 0]),
                "vf": q.max(axis=-1)}


class RainbowLearner(DQNLearner):
    """Categorical TD loss with double-Q action selection; emits per-sample
    priorities (the cross-entropy, Rainbow's proxy for |TD|) as an array
    metric the driver feeds back into the PER buffer."""

    def compute_loss_from_state(self, state, batch, rng):
        out = self.module.forward_train(state["params"], batch["obs"])

        # take_along_axis, not advanced indexing: the batch axis is sharded
        # over the learner mesh and a gather's output sharding is ambiguous.
        def _at_action(dist_logits, actions):
            idx = actions.astype(jnp.int32)[:, None, None]
            idx = jnp.broadcast_to(
                idx, (idx.shape[0], 1, dist_logits.shape[-1]))
            return jnp.take_along_axis(dist_logits, idx, axis=1)[:, 0]

        chosen_logp = jax.nn.log_softmax(
            _at_action(out["logits"], batch["actions"]), axis=-1)  # [B, K]

        # Double-Q: online net picks a*, target net's DISTRIBUTION scores it.
        q_next_online = self.module.forward_train(
            state["params"], batch["next_obs"])["q"]
        a_star = jnp.argmax(q_next_online, axis=-1)
        next_logits = self.module.forward_train(
            state["target"], batch["next_obs"])["logits"]
        next_probs = jax.nn.softmax(_at_action(next_logits, a_star),
                                    axis=-1)

        z = self.module.z
        m = categorical_projection(
            jax.lax.stop_gradient(next_probs), batch["rewards"],
            1.0 - batch["dones"].astype(jnp.float32),
            batch["discounts"], z, self.module.v_min, self.module.v_max)
        ce = -(jax.lax.stop_gradient(m) * chosen_logp).sum(-1)   # [B]
        weights = batch.get("weights")
        loss = (ce * weights).mean() if weights is not None else ce.mean()
        q_taken = jnp.take_along_axis(
            out["q"], batch["actions"].astype(jnp.int32)[:, None], -1)[:, 0]
        return loss, {"td_loss": loss, "q_mean": q_taken.mean(),
                      PRIORITY_KEY: ce}


class PrioritizedReplayBuffer:
    """Proportional PER over flat n-step transitions (driver-side numpy;
    reference: `rllib/utils/replay_buffers/prioritized_episode_buffer.py`).
    Sampling is cumsum + searchsorted over p^alpha; importance weights are
    (N * P(i))^-beta normalized by their batch max."""

    def __init__(self, capacity: int, obs_shape, alpha: float = 0.6,
                 eps: float = 1e-6):
        self._cap = capacity
        self._alpha = alpha
        self._eps = eps
        self._obs = np.zeros((capacity, *obs_shape), np.float32)
        self._next_obs = np.zeros((capacity, *obs_shape), np.float32)
        self._actions = np.zeros((capacity,), np.int32)
        self._rewards = np.zeros((capacity,), np.float32)
        self._dones = np.zeros((capacity,), np.float32)
        self._discounts = np.ones((capacity,), np.float32)
        self._prio = np.zeros((capacity,), np.float64)
        self._max_prio = 1.0
        self._idx = 0
        self._size = 0

    def __len__(self) -> int:
        return self._size

    def add_batch(self, obs, actions, rewards, next_obs, dones,
                  discounts) -> None:
        n = len(obs)
        if n > self._cap:
            obs, actions = obs[-self._cap:], actions[-self._cap:]
            rewards, next_obs = rewards[-self._cap:], next_obs[-self._cap:]
            dones, discounts = dones[-self._cap:], discounts[-self._cap:]
            n = self._cap
        idx = (self._idx + np.arange(n)) % self._cap
        self._obs[idx] = obs
        self._next_obs[idx] = next_obs
        self._actions[idx] = actions
        self._rewards[idx] = rewards
        self._dones[idx] = dones
        self._discounts[idx] = discounts
        self._prio[idx] = self._max_prio ** self._alpha  # fresh = max urgency
        self._idx = int((self._idx + n) % self._cap)
        self._size = min(self._size + n, self._cap)

    def sample(self, n: int, rng: np.random.RandomState, beta: float
               ) -> Tuple[Dict[str, np.ndarray], np.ndarray]:
        p = self._prio[:self._size]
        csum = np.cumsum(p)
        idx = np.searchsorted(
            csum, rng.random_sample(n) * csum[-1], side="right")
        idx = np.minimum(idx, self._size - 1)
        probs = p[idx] / csum[-1]
        w = (self._size * probs) ** (-beta)
        w /= w.max()
        batch = {
            "obs": self._obs[idx], "next_obs": self._next_obs[idx],
            "actions": self._actions[idx], "rewards": self._rewards[idx],
            "dones": self._dones[idx], "discounts": self._discounts[idx],
            "weights": w.astype(np.float32),
        }
        return batch, idx

    def update_priorities(self, idx: np.ndarray,
                          priorities: np.ndarray) -> None:
        pr = np.abs(np.asarray(priorities, np.float64)) + self._eps
        self._prio[idx] = pr ** self._alpha
        self._max_prio = max(self._max_prio, float(pr.max()))


def nstep_from_fragment(rollout: Dict[str, np.ndarray], n_step: int,
                        gamma: float) -> Dict[str, np.ndarray]:
    """Compose flat n-step transitions from a time-major [T, N] fragment.

    For each (t, lane): R = sum_{k} gamma^k r_{t+k}, accumulating until the
    episode ends (done) or the fragment runs out; next_obs is the TRUE
    successor at the stopping step, `dones` is env-true termination there
    (TD bootstraps through time-limit truncation), and `discounts` is the
    effective gamma^(steps used) for the projection.
    """
    rewards = rollout["rewards"]
    dones = rollout["dones"].astype(bool)
    terms = rollout["terminateds"].astype(np.float32)
    T, N = rewards.shape
    lanes = np.arange(N)

    R = np.zeros((T, N), np.float32)
    end = np.zeros((T, N), np.int64)
    disc = np.zeros((T, N), np.float32)
    for t in range(T):
        acc = np.zeros(N, np.float32)
        g = np.ones(N, np.float32)
        active = np.ones(N, bool)
        stop = np.full(N, t)
        for k in range(n_step):
            tk = t + k
            if tk >= T:
                break
            acc = np.where(active, acc + g * rewards[tk], acc)
            stop = np.where(active, tk, stop)
            g *= gamma
            active &= ~dones[tk]
        R[t] = acc
        end[t] = stop
        disc[t] = gamma ** (stop - t + 1)

    flat = lambda a: a.reshape(T * N, *a.shape[2:])  # noqa: E731
    return {
        "obs": flat(rollout["obs"]),
        "actions": flat(rollout["actions"]).astype(np.int32),
        "rewards": flat(R),
        "next_obs": flat(rollout["next_obs"][end, lanes[None, :]]),
        "dones": flat(terms[end, lanes[None, :]]),
        "discounts": flat(disc),
    }


class RainbowConfig(DQNConfig):
    def __init__(self):
        super().__init__()
        self.n_step = 3
        self.num_atoms = 51
        self.v_min = -10.0
        self.v_max = 10.0
        self.dueling = True
        self.per_alpha = 0.6
        self.per_beta_initial = 0.4
        self.per_beta_final = 1.0
        self.per_beta_decay_steps = 20_000   # in env steps

    algo_class = property(lambda self: Rainbow)


class Rainbow(DQN):
    learner_class = RainbowLearner
    rl_module_class = RainbowModule

    def _make_buffer(self):
        return PrioritizedReplayBuffer(
            self.config.buffer_capacity,
            self.module_spec.observation_space.shape,
            alpha=self.config.per_alpha)

    def _default_module_spec(self, obs_space, act_space):
        spec = super()._default_module_spec(obs_space, act_space)
        cfg = self.config

        def _build(observation_space, action_space, hidden,
                   _cfg=cfg) -> RainbowModule:
            return RainbowModule(
                observation_space, action_space, hidden,
                num_atoms=_cfg.num_atoms, v_min=_cfg.v_min,
                v_max=_cfg.v_max, dueling=_cfg.dueling)

        # RLModuleSpec calls module_class(obs, act, hidden); close over the
        # distributional geometry so learners and runners build identically.
        spec.module_class = _build
        return spec

    def _beta(self) -> float:
        cfg = self.config
        frac = min(1.0, self._env_steps / max(cfg.per_beta_decay_steps, 1))
        return float(cfg.per_beta_initial
                     + frac * (cfg.per_beta_final - cfg.per_beta_initial))

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        rollouts = self.sample_batch(cfg.rollout_fragment_length)
        for ro in rollouts:
            T, N = ro["actions"].shape
            self._env_steps += T * N
            flat = nstep_from_fragment(ro, cfg.n_step, cfg.gamma)
            self._buffer.add_batch(
                flat["obs"], flat["actions"], flat["rewards"],
                flat["next_obs"], flat["dones"], flat["discounts"])

        metrics: Dict[str, Any] = {"env_steps": self._env_steps,
                                   "buffer_size": len(self._buffer),
                                   "epsilon": self._epsilon(),
                                   "per_beta": self._beta()}
        if len(self._buffer) >= cfg.learning_starts:
            for _ in range(cfg.num_updates_per_iteration):
                batch, idx = self._buffer.sample(
                    cfg.train_batch_size, self._rng, self._beta())
                update = self.learner_group.update(batch)
                prios = update.pop(PRIORITY_KEY, None)
                if prios is not None:
                    self._buffer.update_priorities(idx, prios)
                metrics.update(update)
                self._updates += 1
                if self._updates % cfg.target_update_freq == 0:
                    self.learner_group.foreach_learner("sync_target")
        self._sync_weights(
            self._eval_weights(self.learner_group.get_weights()))
        metrics["num_gradient_updates"] = self._updates
        return metrics
