"""MultiRLModule — a dict of RLModules, one per policy.

Reference: `rllib/core/rl_module/multi_rl_module.py` (MultiRLModuleSpec
builds {module_id: RLModule}; forward passes are dispatched per module).
TPU-first shape: the multi-module's params are a single pytree
{module_id: params}, so a learner jits ONE update over all policies —
disjoint subtrees mean XLA computes each policy's gradients in the same
program with no cross-talk, and adding a policy never adds a dispatch.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional

import jax

from ray_tpu.rllib.core.rl_module import RLModule, RLModuleSpec

ModuleID = str


@dataclasses.dataclass
class MultiRLModuleSpec:
    """module_specs: {module_id: RLModuleSpec}."""

    module_specs: Dict[ModuleID, RLModuleSpec]

    def build(self) -> "MultiRLModule":
        return MultiRLModule({mid: spec.build()
                              for mid, spec in self.module_specs.items()})

    @property
    def module_ids(self) -> List[ModuleID]:
        return sorted(self.module_specs)


class MultiRLModule:
    """Holds per-policy submodules; params = {module_id: subparams}."""

    def __init__(self, modules: Dict[ModuleID, RLModule]):
        self._modules = dict(modules)

    def __getitem__(self, module_id: ModuleID) -> RLModule:
        return self._modules[module_id]

    def keys(self) -> List[ModuleID]:
        return sorted(self._modules)

    def init(self, rng: jax.Array) -> Dict[ModuleID, Any]:
        keys = jax.random.split(rng, len(self._modules))
        return {mid: self._modules[mid].init(k)
                for mid, k in zip(self.keys(), keys)}

    def forward_train(self, params, obs_by_module):
        return {mid: self._modules[mid].forward_train(params[mid], obs)
                for mid, obs in obs_by_module.items()}

    def forward_exploration(self, params, obs_by_module, rng):
        keys = jax.random.split(rng, len(obs_by_module))
        return {mid: self._modules[mid].forward_exploration(
                    params[mid], obs, k)
                for (mid, obs), k in zip(sorted(obs_by_module.items()), keys)}


def default_policy_mapping_fn(agent_id: str) -> ModuleID:
    """Reference default: every agent maps to one shared policy."""
    return "default_policy"
