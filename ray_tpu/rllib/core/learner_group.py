"""LearnerGroup — the distributed fleet of Learner actors.

Reference: `rllib/core/learner/learner_group.py:39,149-169` — which builds
its learner actors by REUSING Ray Train's BackendExecutor. This does the
same: the executor creates the placement group + worker gang and the
JaxBackend rendezvouses `jax.distributed` across it, so the learners form
one global mesh and every `update()` is a lockstep SPMD step.
"""

from __future__ import annotations

import tempfile
from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu
from ray_tpu.train._internal.backend_executor import BackendExecutor
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.jax_backend import JaxConfig
from ray_tpu.rllib.core.rl_module import RLModuleSpec

_LEARNER = None  # worker-process singleton


def _build_learner(learner_cls, module_spec, config):
    global _LEARNER
    _LEARNER = learner_cls(module_spec, config)
    _LEARNER.build()
    return True


def _learner_update(batch, rng_seed):
    return _LEARNER.update(batch, rng_seed)


def _learner_get_weights():
    return _LEARNER.get_weights()


def _learner_set_weights(w):
    _LEARNER.set_weights(w)
    return True


def _learner_call(method, *args, **kwargs):
    return getattr(_LEARNER, method)(*args, **kwargs)


def _learner_get_state():
    return _LEARNER.get_state()


def _learner_set_state(s):
    _LEARNER.set_state(s)
    return True


class LearnerGroup:
    def __init__(self, learner_cls, module_spec: RLModuleSpec,
                 learner_config: Optional[Dict[str, Any]] = None,
                 scaling_config: Optional[ScalingConfig] = None,
                 jax_config: Optional[JaxConfig] = None):
        self._scaling = scaling_config or ScalingConfig(num_workers=1)
        self._executor = BackendExecutor(
            jax_config or JaxConfig(), self._scaling, RunConfig(),
            tempfile.mkdtemp(prefix="rtpu-learners-"))
        self._executor.start()
        self._group = self._executor.worker_group
        self._group.execute(_build_learner, learner_cls, module_spec,
                            dict(learner_config or {}))
        self._step = 0

    @property
    def num_learners(self) -> int:
        return self._group.num_workers

    # ----------------------------------------------------------------- update
    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """One lockstep SPMD gradient step: the global batch is split evenly;
        each learner feeds its process-local shard into the shared mesh."""
        import time

        from ray_tpu.observability import learner_metrics
        from ray_tpu.observability.goodput import (StepPhases,
                                                   goodput_enabled)
        from ray_tpu.util.tracing import span

        n = self.num_learners
        self._step += 1
        # Driver-side decomposition only: publish=False keeps the
        # coordinator's rows out of the GCS step matrix so the
        # straggler median is computed over actual learners.
        sp = (StepPhases(step=self._step, worker="learner-group")
              if goodput_enabled() else None)
        t0 = time.perf_counter()
        with span("learner_group.update",
                  attrs={"learners": n, "step": self._step}):
            t_split = time.perf_counter()
            shards = _split_batch(batch, n)
            if sp is not None:
                sp.add("data_wait", time.perf_counter() - t_split)
            t_run = time.perf_counter()
            refs = [w.execute.remote(_learner_update, shards[i], self._step)
                    for i, w in enumerate(self._group.workers)]
            metrics = ray_tpu.get(refs, timeout=600)
            if sp is not None:
                sp.add("compute", time.perf_counter() - t_run)
        learner_metrics().group_update_seconds.observe(
            time.perf_counter() - t0)
        if sp is not None:
            sp.finish(publish=False)
        return metrics[0]

    def foreach_learner(self, method: str, *args, **kwargs) -> List[Any]:
        """Invoke a learner method on every learner (e.g. DQN
        sync_target)."""
        return self._group.execute(_learner_call, method, *args, **kwargs)

    # ---------------------------------------------------------------- weights
    def get_weights(self) -> Any:
        return self._group.execute_single(0, _learner_get_weights)

    def set_weights(self, weights: Any) -> None:
        self._group.execute(_learner_set_weights, weights)

    def get_state(self) -> Any:
        return self._group.execute_single(0, _learner_get_state)

    def set_state(self, state: Any) -> None:
        self._group.execute(_learner_set_state, state)

    def shutdown(self) -> None:
        self._executor.shutdown()


def _split_batch(batch: Dict[str, Any], n: int) -> List[Dict[str, Any]]:
    """Even split along axis 0 of every leaf (handles nested multi-agent
    batches {module_id: {k: array}} the same as flat ones).

    Row counts not divisible by `n` distribute the remainder
    deterministically — one extra row to each of the first
    ``len(v) % n`` shards — and every row is conserved (the old
    floor-division split silently dropped the remainder)."""
    if n == 1:
        return [batch]

    def _shard(v, i):
        v = np.asarray(v)
        per, rem = divmod(len(v), n)
        start = i * per + min(i, rem)
        return v[start:start + per + (1 if i < rem else 0)]

    import jax

    shards = [jax.tree.map(lambda v, i=i: _shard(v, i), batch)
              for i in range(n)]
    first = jax.tree.leaves(batch)[0]
    got = sum(len(jax.tree.leaves(s)[0]) for s in shards)
    assert got == len(np.asarray(first)), \
        f"_split_batch dropped rows: {got} != {len(np.asarray(first))}"
    return shards
