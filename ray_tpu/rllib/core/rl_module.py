"""RLModule — the neural-network abstraction of the new API stack.

Reference: `rllib/core/rl_module/rl_module.py` (forward_exploration /
forward_inference / forward_train over a spec-built module). TPU-first:
a module is a flax.linen network plus pure functions over a param pytree,
so the learner can pjit the whole update and env runners can run the same
apply on CPU — one definition, two execution tiers.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.env.spaces import Box, Discrete


class RLModule:
    """Pure-functional module: params live outside; methods are jittable."""

    def init(self, rng: jax.Array) -> Any:
        raise NotImplementedError

    def forward_train(self, params: Any, obs: jax.Array) -> Dict[str, jax.Array]:
        """Returns at least {"action_logits", "vf"} for actor-critic."""
        raise NotImplementedError

    def forward_inference(self, params, obs):
        out = self.forward_train(params, obs)
        return {"actions": jnp.argmax(out["action_logits"], axis=-1)}

    def forward_exploration(self, params, obs, rng):
        out = self.forward_train(params, obs)
        logits = out["action_logits"]
        actions = jax.random.categorical(rng, logits)
        logp = jax.nn.log_softmax(logits)[
            jnp.arange(logits.shape[0]), actions]
        return {"actions": actions, "logp": logp, "vf": out["vf"]}


@dataclasses.dataclass
class RLModuleSpec:
    """Builds a module from spaces (reference: `rl_module.py` SingleAgent
    RLModuleSpec)."""

    observation_space: Box
    action_space: Discrete
    hidden: Sequence[int] = (64, 64)
    module_class: Optional[type] = None
    module_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def build(self) -> "RLModule":
        cls = self.module_class or MLPModule
        return cls(self.observation_space, self.action_space, self.hidden,
                   **self.module_kwargs)


class MLPModule(RLModule):
    """Actor-critic MLP over flax.linen, for vector observations."""

    def __init__(self, observation_space: Box, action_space: Discrete,
                 hidden: Sequence[int] = (64, 64)):
        import flax.linen as nn

        obs_dim = int(np.prod(observation_space.shape))
        n_actions = action_space.n

        class _Net(nn.Module):
            @nn.compact
            def __call__(self, x):
                h = x
                for width in hidden:
                    h = nn.tanh(nn.Dense(width)(h))
                logits = nn.Dense(n_actions,
                                  kernel_init=nn.initializers.normal(0.01))(h)
                hv = x
                for width in hidden:
                    hv = nn.tanh(nn.Dense(width)(hv))
                vf = nn.Dense(1)(hv)
                return logits, vf[..., 0]

        self._net = _Net()
        self._obs_dim = obs_dim

    def init(self, rng: jax.Array) -> Any:
        dummy = jnp.zeros((1, self._obs_dim), jnp.float32)
        return self._net.init(rng, dummy)

    def forward_train(self, params, obs):
        logits, vf = self._net.apply(params, obs)
        return {"action_logits": logits, "vf": vf}
