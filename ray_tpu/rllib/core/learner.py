"""Learner — gradient updates as a single pjit'd SPMD step.

Reference: `rllib/core/learner/learner.py` + `torch/torch_learner.py:374`
(which wraps modules in DDP). TPU-first difference: there is no DDP wrapper —
each learner process is one participant in a global jax mesh; the batch is
sharded over the "data" axis, params are replicated, and XLA inserts the
gradient psum over ICI automatically (GSPMD), so `update()` is one jitted
call whether there is 1 learner or 64.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.core.rl_module import RLModuleSpec


class Learner:
    """Subclasses implement `compute_loss(params, batch, rng)`."""

    def __init__(self, module_spec: RLModuleSpec,
                 config: Optional[Dict[str, Any]] = None):
        self.module_spec = module_spec
        self.config = dict(config or {})
        self.module = None
        self._state = None
        self._mesh = None
        self._update_fn = None

    # ------------------------------------------------------------------ build
    def build(self) -> None:
        import optax
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.module = self.module_spec.build()
        self._mesh = jax.make_mesh((jax.device_count(),), ("data",))
        self._repl = NamedSharding(self._mesh, P())
        self._data_sh = NamedSharding(self._mesh, P("data"))

        params = self.module.init(
            jax.random.key(int(self.config.get("seed", 0))))
        params = jax.device_put(params, self._repl)
        self._optimizer = self._make_optimizer()
        opt_state = jax.device_put(self._optimizer.init(params), self._repl)
        self._state = {"params": params, "opt_state": opt_state,
                       **self.init_extra_state(params)}

        def _update(state, batch, rng):
            def loss_fn(p):
                return self.compute_loss_from_state(
                    {**state, "params": p}, batch, rng)

            (loss, metrics), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state["params"])
            updates, new_opt = self._optimizer.update(
                grads, state["opt_state"], state["params"])
            new_params = optax.apply_updates(state["params"], updates)
            metrics = dict(metrics)
            metrics["total_loss"] = loss
            metrics["grad_norm"] = optax.global_norm(grads)
            new_state = self.post_update_state(
                {**state, "params": new_params, "opt_state": new_opt})
            return new_state, metrics

        from ray_tpu.observability.jit import tracked_jit

        self._update_fn = tracked_jit(
            _update, name=f"{type(self).__name__}_update",
            donate_argnums=(0,))

    def _make_optimizer(self):
        """Hook: subclasses may change clipping/optimizer structure (the
        multi-agent learner clips per module so policies stay decoupled)."""
        import optax

        return optax.chain(
            optax.clip_by_global_norm(self.config.get("grad_clip", 0.5)),
            optax.adam(self.config.get("lr", 3e-4)),
        )

    # ------------------------------------------------------------------- loss
    def compute_loss(self, params, batch: Dict[str, jax.Array],
                     rng: jax.Array) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        raise NotImplementedError

    def compute_loss_from_state(self, state, batch, rng):
        """Override when the loss needs learner state beyond params (e.g.
        DQN's target network); default delegates to compute_loss."""
        return self.compute_loss(state["params"], batch, rng)

    def init_extra_state(self, params) -> Dict[str, Any]:
        """Extra entries merged into the learner state pytree (carried
        through jitted updates untouched)."""
        return {}

    def post_update_state(self, state):
        """Traced inside the jitted update, after the optimizer step —
        the hook for per-update state transforms (e.g. SAC's polyak
        target averaging). Must be pure."""
        return state

    # ----------------------------------------------------------------- update
    def update(self, batch: Dict[str, np.ndarray],
               rng_seed: int = 0) -> Dict[str, float]:
        """One gradient step on this process's shard of the global batch.

        Multi-learner: every learner calls update() with its local shard of
        the same global step; `make_array_from_process_local_data` assembles
        the global sharded array and the psum rides the mesh.
        """
        import time

        from ray_tpu.observability import batch_num_samples, learner_metrics
        from ray_tpu.observability.goodput import (StepPhases,
                                                   goodput_enabled)
        from ray_tpu.util.tracing import span

        lm = learner_metrics()
        sp = None
        if goodput_enabled():
            sp = StepPhases(step=int(rng_seed),
                            worker=f"learner{jax.process_index()}")
        t0 = time.perf_counter()
        # tree.map so nested multi-agent batches ({module_id: {k: v}})
        # shard leaf-wise exactly like flat single-agent ones.
        with span("learner.update"):
            t_h2d = time.perf_counter()
            global_batch = jax.tree.map(
                lambda v: jax.make_array_from_process_local_data(
                    self._data_sh, np.asarray(v)), batch)
            if sp is not None:
                sp.add("h2d", time.perf_counter() - t_h2d)
            t_step = time.perf_counter()
            self._state, metrics = self._update_fn(
                self._state, global_batch, jax.random.key(rng_seed))
            if sp is not None:
                jax.block_until_ready(metrics)
                sp.add("compute", time.perf_counter() - t_step)
        lm.update_seconds.observe(time.perf_counter() - t0)
        lm.updates.inc()
        lm.samples.inc(batch_num_samples(batch))
        if sp is not None:
            sp.finish()
        out: Dict[str, Any] = {}
        for k, v in metrics.items():
            if np.ndim(v) == 0:
                out[k] = float(v)
            else:
                # Per-sample array metric (e.g. Rainbow's PER priorities).
                # Dropped when not fully addressable (multi-process mesh) —
                # the driver then skips the priority feedback for that step.
                try:
                    out[k] = np.asarray(v)
                except Exception:
                    pass
        if isinstance(out.get("total_loss"), float):
            lm.loss.set(out["total_loss"])
        return out

    # ---------------------------------------------------------------- weights
    def get_weights(self) -> Any:
        return jax.tree.map(lambda x: np.asarray(x), self._state["params"])

    def set_weights(self, weights: Any) -> None:
        self._state["params"] = jax.device_put(weights, self._repl)

    def get_state(self) -> Any:
        return jax.tree.map(lambda x: np.asarray(x), self._state)

    def set_state(self, state: Any) -> None:
        self._state = jax.device_put(state, self._repl)
