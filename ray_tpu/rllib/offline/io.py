"""Offline experience I/O — JSONL episode logs.

Reference: `rllib/offline/json_writer.py` / `json_reader.py` (newline-
delimited JSON sample batches, sharded files, env-rollout recording via
`config.offline_data(output=...)`).  Rows here are per-transition with an
`eps_id`, so readers can reassemble episodes and compute return-to-go for
advantage-weighted algorithms (MARWIL) without the writer knowing gamma.
"""

from __future__ import annotations

import glob as _glob
import json
import os
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np


class JsonWriter:
    """Append transitions as JSONL; rolls to a new shard every
    `max_rows_per_file` rows (reference: JsonWriter sharding)."""

    def __init__(self, path: str, max_rows_per_file: int = 100_000):
        import uuid

        self._dir = path
        os.makedirs(path, exist_ok=True)
        self._max = max_rows_per_file
        self._rows_in_file = 0
        self._shard = 0
        # Unique per-writer token (reference JsonWriter does the same):
        # two recordings into one directory must neither append to each
        # other's shards nor collide eps_ids at read time.
        self._token = uuid.uuid4().hex[:8]
        self._fh = None

    def _roll(self) -> None:
        if self._fh is not None:
            self._fh.close()
        fname = os.path.join(
            self._dir, f"rollouts-{self._token}-{self._shard:05d}.jsonl")
        self._fh = open(fname, "w")
        self._shard += 1
        self._rows_in_file = 0

    def write(self, row: Dict[str, Any]) -> None:
        if self._fh is None or self._rows_in_file >= self._max:
            self._roll()
        self._fh.write(json.dumps(
            {k: (v.tolist() if isinstance(v, np.ndarray) else
                 v.item() if isinstance(v, np.generic) else v)
             for k, v in row.items()}) + "\n")
        self._rows_in_file += 1

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class JsonReader:
    """Reads a JSONL rollout directory (or single file) back into rows;
    `with_returns(gamma)` appends discounted return-to-go per transition
    (grouped by eps_id, episode order = row order within a shard)."""

    def __init__(self, path: str):
        if os.path.isdir(path):
            self._files = sorted(_glob.glob(os.path.join(path, "*.jsonl")))
        else:
            self._files = [path]
        if not self._files:
            raise FileNotFoundError(f"no .jsonl files under {path}")

    def rows(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for f in self._files:
            with open(f) as fh:
                for ln in fh:
                    ln = ln.strip()
                    if ln:
                        out.append(json.loads(ln))
        return out

    def with_returns(self, gamma: float = 0.99) -> List[Dict[str, Any]]:
        return compute_returns(self.rows(), gamma)

    def to_dataset(self):
        """Rows as a ray_tpu.data Dataset (requires a live cluster)."""
        from ray_tpu import data as rdata

        return rdata.from_items(self.rows())


def compute_returns(rows: List[Dict[str, Any]],
                    gamma: float = 0.99) -> List[Dict[str, Any]]:
    """Append discounted return-to-go per transition, grouping by eps_id
    in row order (shared by JsonReader.with_returns and MARWIL's
    in-memory ingestion).  Rows must carry 'rewards' (or a precomputed
    'returns', which is left untouched)."""
    by_ep: Dict[Any, List[int]] = {}
    for i, r in enumerate(rows):
        if "returns" not in r and "rewards" not in r:
            raise ValueError(
                f"offline row {i} has neither 'rewards' nor a precomputed "
                f"'returns' column (keys: {sorted(r)})")
        by_ep.setdefault(r.get("eps_id", 0), []).append(i)
    for idxs in by_ep.values():
        ret = 0.0
        for i in reversed(idxs):
            if "returns" in rows[i]:
                ret = float(rows[i]["returns"])
                continue
            ret = float(rows[i]["rewards"]) + gamma * ret
            rows[i]["returns"] = ret
    return rows


def record_rollouts(env_spec, path: str, num_episodes: int,
                    policy: Optional[Callable[[np.ndarray], int]] = None,
                    seed: int = 0) -> Dict[str, Any]:
    """Roll `num_episodes` episodes of `env_spec` and persist them as
    JSONL (reference: `rllib/offline/` output API + `rllib train ...
    --out`).  `policy(obs) -> action`; None = uniform random."""
    import uuid

    from ray_tpu.rllib.env.cartpole import make_env

    env = make_env(env_spec, seed=seed)
    rng = np.random.RandomState(seed)
    returns: List[float] = []
    # Globally-unique episode ids: a second recording into the same
    # directory must not merge its episodes with this run's at read time.
    run = uuid.uuid4().hex[:8]
    with JsonWriter(path) as w:
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=seed * 100003 + ep)
            done, total, t = False, 0.0, 0
            while not done:
                if policy is None:
                    act = env.action_space.sample(rng)
                else:
                    act = policy(obs)
                nxt, r, term, trunc, _ = env.step(act)
                w.write({"eps_id": f"{run}-{ep}", "t": t, "obs": obs,
                         "actions": act, "rewards": r,
                         "terminateds": term, "truncateds": trunc})
                obs, total, t = nxt, total + r, t + 1
                done = term or trunc
            returns.append(total)
    return {"num_episodes": num_episodes,
            "episode_return_mean": float(np.mean(returns))}
