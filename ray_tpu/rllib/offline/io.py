"""Offline experience I/O — JSONL episode logs.

Reference: `rllib/offline/json_writer.py` / `json_reader.py` (newline-
delimited JSON sample batches, sharded files, env-rollout recording via
`config.offline_data(output=...)`).  Rows here are per-transition with an
`eps_id`, so readers can reassemble episodes and compute return-to-go for
advantage-weighted algorithms (MARWIL) without the writer knowing gamma.
"""

from __future__ import annotations

import glob as _glob
import json
import os
from typing import Any, Callable, Dict, Iterator, List, Optional

import numpy as np


class JsonWriter:
    """Append transitions as JSONL; rolls to a new shard every
    `max_rows_per_file` rows (reference: JsonWriter sharding)."""

    def __init__(self, path: str, max_rows_per_file: int = 100_000):
        import uuid

        self._dir = path
        os.makedirs(path, exist_ok=True)
        self._max = max_rows_per_file
        self._rows_in_file = 0
        self._shard = 0
        # Unique per-writer token (reference JsonWriter does the same):
        # two recordings into one directory must neither append to each
        # other's shards nor collide eps_ids at read time.
        self._token = uuid.uuid4().hex[:8]
        self._fh = None

    def _roll(self) -> None:
        if self._fh is not None:
            self._fh.close()
        fname = os.path.join(
            self._dir, f"rollouts-{self._token}-{self._shard:05d}.jsonl")
        self._fh = open(fname, "w")
        self._shard += 1
        self._rows_in_file = 0

    def write(self, row: Dict[str, Any]) -> None:
        if self._fh is None or self._rows_in_file >= self._max:
            self._roll()
        self._fh.write(json.dumps(
            {k: (v.tolist() if isinstance(v, np.ndarray) else
                 v.item() if isinstance(v, np.generic) else v)
             for k, v in row.items()}) + "\n")
        self._rows_in_file += 1

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class JsonReader:
    """Reads a JSONL rollout directory (or single file) back into rows;
    `with_returns(gamma)` appends discounted return-to-go per transition
    (grouped by eps_id, episode order = row order within a shard)."""

    def __init__(self, path: str):
        if os.path.isdir(path):
            self._files = sorted(_glob.glob(os.path.join(path, "*.jsonl")))
        else:
            self._files = [path]
        if not self._files:
            raise FileNotFoundError(f"no .jsonl files under {path}")

    def rows(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for f in self._files:
            with open(f) as fh:
                for ln in fh:
                    ln = ln.strip()
                    if ln:
                        out.append(json.loads(ln))
        return out

    def with_returns(self, gamma: float = 0.99) -> List[Dict[str, Any]]:
        return compute_returns(self.rows(), gamma)

    def to_dataset(self):
        """Rows as a ray_tpu.data Dataset (requires a live cluster)."""
        from ray_tpu import data as rdata

        return rdata.from_items(self.rows())


class ParquetWriter:
    """Append transitions, flushed as parquet shards — the interchange
    format with `ray_tpu.data` (reference: `rllib/offline/` reads sample
    batches through Ray Data; JSONL is the legacy path)."""

    def __init__(self, path: str, max_rows_per_file: int = 100_000):
        import uuid

        self._dir = path
        os.makedirs(path, exist_ok=True)
        self._max = max_rows_per_file
        self._rows: List[Dict[str, Any]] = []
        self._shard = 0
        self._token = uuid.uuid4().hex[:8]

    def write(self, row: Dict[str, Any]) -> None:
        self._rows.append(
            {k: (v.tolist() if isinstance(v, np.ndarray) else
                 v.item() if isinstance(v, np.generic) else v)
             for k, v in row.items()})
        if len(self._rows) >= self._max:
            self._flush()

    def _flush(self) -> None:
        if not self._rows:
            return
        import pyarrow as pa
        import pyarrow.parquet as pq

        cols: Dict[str, list] = {}
        for r in self._rows:
            for k in r:
                cols.setdefault(k, [])
        for r in self._rows:
            for k in cols:
                cols[k].append(r.get(k))
        pq.write_table(pa.table(cols), os.path.join(
            self._dir, f"rollouts-{self._token}-{self._shard:05d}.parquet"))
        self._shard += 1
        self._rows = []

    def close(self) -> None:
        self._flush()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class DatasetReader:
    """Stream transition batches out of a `ray_tpu.data.Dataset` —
    offline training ingests Data pipelines (parquet shards, any Data
    source) directly instead of JSONL-only (reference: `rllib/offline/`
    new-stack readers are Ray Data datasets; VERDICT r4 weak-7).

    `path_or_dataset`: a Dataset, or a path read via
    `data.read_parquet`. `batches(batch_size)` yields numpy dicts with
    float32 obs/rewards, ready for a Learner; `rows()` materializes (for
    small datasets / return computation).
    """

    def __init__(self, path_or_dataset):
        from ray_tpu import data as rdata

        if isinstance(path_or_dataset, str):
            self._ds = rdata.read_parquet(path_or_dataset)
        else:
            self._ds = path_or_dataset

    @property
    def dataset(self):
        return self._ds

    def rows(self) -> List[Dict[str, Any]]:
        return self._ds.take_all()

    def with_returns(self, gamma: float = 0.99) -> List[Dict[str, Any]]:
        return compute_returns(self.rows(), gamma)

    def batches(self, batch_size: int,
                epochs: Optional[int] = None) -> Iterator[Dict[str, Any]]:
        """Epoch-looped numpy batches (None = loop forever)."""
        epoch = 0
        while epochs is None or epoch < epochs:
            for b in self._ds.iter_batches(batch_size=batch_size,
                                           batch_format="numpy",
                                           drop_last=True):
                yield {k: (np.stack([np.asarray(x, np.float32)
                                     for x in v])
                           if v.dtype == object else v)
                       for k, v in b.items()}
            epoch += 1


def compute_returns(rows: List[Dict[str, Any]],
                    gamma: float = 0.99) -> List[Dict[str, Any]]:
    """Append discounted return-to-go per transition, grouping by eps_id
    in row order (shared by JsonReader.with_returns and MARWIL's
    in-memory ingestion).  Rows must carry 'rewards' (or a precomputed
    'returns', which is left untouched)."""
    by_ep: Dict[Any, List[int]] = {}
    for i, r in enumerate(rows):
        if "returns" not in r and "rewards" not in r:
            raise ValueError(
                f"offline row {i} has neither 'rewards' nor a precomputed "
                f"'returns' column (keys: {sorted(r)})")
        by_ep.setdefault(r.get("eps_id", 0), []).append(i)
    for idxs in by_ep.values():
        ret = 0.0
        for i in reversed(idxs):
            if "returns" in rows[i]:
                ret = float(rows[i]["returns"])
                continue
            ret = float(rows[i]["rewards"]) + gamma * ret
            rows[i]["returns"] = ret
    return rows


def record_rollouts(env_spec, path: str, num_episodes: int,
                    policy: Optional[Callable[[np.ndarray], Any]] = None,
                    seed: int = 0,
                    output_format: str = "json") -> Dict[str, Any]:
    """Roll `num_episodes` episodes of `env_spec` and persist them
    (reference: `rllib/offline/` output API + `rllib train ... --out`).
    `policy(obs) -> action`; None = uniform random.
    `output_format`: "json" (JSONL shards) or "parquet" (Data-ready)."""
    import uuid

    from ray_tpu.rllib.env.cartpole import make_env

    env = make_env(env_spec, seed=seed)
    rng = np.random.RandomState(seed)
    returns: List[float] = []
    # Globally-unique episode ids: a second recording into the same
    # directory must not merge its episodes with this run's at read time.
    run = uuid.uuid4().hex[:8]
    writer_cls = ParquetWriter if output_format == "parquet" else JsonWriter
    with writer_cls(path) as w:
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=seed * 100003 + ep)
            done, total, t = False, 0.0, 0
            while not done:
                if policy is None:
                    act = env.action_space.sample(rng)
                else:
                    act = policy(obs)
                nxt, r, term, trunc, _ = env.step(act)
                w.write({"eps_id": f"{run}-{ep}", "t": t, "obs": obs,
                         "actions": act, "rewards": r, "next_obs": nxt,
                         "terminateds": term, "truncateds": trunc})
                obs, total, t = nxt, total + r, t + 1
                done = term or trunc
            returns.append(total)
    return {"num_episodes": num_episodes,
            "episode_return_mean": float(np.mean(returns))}
