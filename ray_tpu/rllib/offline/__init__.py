"""ray_tpu.rllib.offline — offline-RL experience I/O.

Reference: `rllib/offline/` (JsonReader/JsonWriter, offline input/output
configs, dataset-backed training used by BC/MARWIL/CQL).
"""

from ray_tpu.rllib.offline.io import (JsonReader, JsonWriter,
                                      record_rollouts)

__all__ = ["JsonReader", "JsonWriter", "record_rollouts"]
