"""@ray_tpu.remote on functions (reference: `python/ray/remote_function.py`)."""

from __future__ import annotations

from typing import Any, Dict, Optional

import cloudpickle

# Option surface mirrors the reference's central validation table
# (`python/ray/_private/ray_option_utils.py`).
_VALID_TASK_OPTIONS = {
    "num_cpus", "num_tpus", "num_gpus", "resources", "memory",
    "accelerator_type", "max_retries", "retry_exceptions", "num_returns",
    "scheduling_strategy", "runtime_env", "name", "_labels",
}


def validate_task_options(options: Dict[str, Any]) -> None:
    for key in options:
        if key not in _VALID_TASK_OPTIONS:
            raise ValueError(
                f"invalid option {key!r} for a remote function; valid: "
                f"{sorted(_VALID_TASK_OPTIONS)}")
    nr = options.get("num_returns", 1)
    if isinstance(nr, str):
        if nr not in ("dynamic", "streaming"):
            raise ValueError(
                'num_returns must be an int, "dynamic", or "streaming"')
    elif not (isinstance(nr, int) and nr >= 0):
        raise ValueError("num_returns must be a non-negative int")
    if options.get("num_gpus"):
        raise ValueError(
            "ray_tpu is a TPU-native framework: use num_tpus instead of "
            "num_gpus")


class RemoteFunction:
    def __init__(self, fn, options: Optional[Dict[str, Any]] = None):
        self._function = fn
        self._options = dict(options or {})
        validate_task_options(self._options)
        self._pickled: Optional[bytes] = None
        self._fn_hash: Optional[str] = None
        self.__name__ = getattr(fn, "__name__", "remote_function")
        self.__doc__ = getattr(fn, "__doc__", None)

    def _ensure_exported(self, worker) -> str:
        if self._pickled is None:
            self._pickled = cloudpickle.dumps(self._function)
        # Re-export per driver session: a module-level @remote function
        # outlives init()/shutdown() cycles, and a cached hash from a
        # previous cluster's GCS function table is unknown to the next
        # one ("function ... not found in the GCS function table").
        # Keyed on the worker's random id — job ids restart at 1 per
        # cluster so they collide across sessions, and id(worker) could
        # be recycled after GC.
        token = worker.worker_id
        if self._fn_hash is None or getattr(self, "_export_token",
                                            None) != token:
            self._fn_hash = worker.export_function(self._pickled)
            self._export_token = token
        return self._fn_hash

    def remote(self, *args, **kwargs):
        from ray_tpu._private.worker import global_worker

        w = global_worker()
        fn_hash = self._ensure_exported(w)
        refs = w.submit_task(fn_hash, self.__name__, args, kwargs,
                             self._options)
        nr = self._options.get("num_returns", 1)
        if nr == 0:
            return None
        if nr == 1 or isinstance(nr, str):
            # "dynamic" -> ref resolving to the item-ref list;
            # "streaming" -> an ObjectRefGenerator.
            return refs[0]
        return refs

    def options(self, **options) -> "RemoteFunction":
        merged = {**self._options, **options}
        clone = RemoteFunction(self._function, merged)
        clone._pickled = self._pickled
        clone._fn_hash = self._fn_hash
        clone._export_token = getattr(self, "_export_token", None)
        return clone

    def bind(self, *args, **kwargs):
        """Lazy DAG node (reference `remote_function.py` bind)."""
        from ray_tpu.dag import FunctionNode

        return FunctionNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self.__name__} cannot be called directly; use "
            f"{self.__name__}.remote()")
