"""ray_tpu.workflow — durable task DAGs with storage-backed resume.

Reference: `python/ray/workflow/` (`workflow_executor.py:32`,
`workflow_state.py`, `workflow_state_from_storage.py`): steps compose into
a DAG; every step's output is checkpointed to storage as it completes, so
a crashed/interrupted workflow resumes from its last finished step —
completed steps replay from storage, never re-execute.

API (classic step style)::

    from ray_tpu import workflow

    workflow.init("/path/to/storage")

    @workflow.step
    def fetch(x): ...

    @workflow.step
    def combine(a, b): ...

    out = combine.step(fetch.step(1), fetch.step(2)).run("my_wf")
    # after a crash: workflow.resume("my_wf")
"""

from __future__ import annotations

import json
import os
import pickle
import time
from typing import Any, Dict, List, Optional

import cloudpickle

import ray_tpu

_storage_dir: Optional[str] = None


def init(storage_dir: str) -> None:
    global _storage_dir
    _storage_dir = os.path.abspath(storage_dir)
    os.makedirs(_storage_dir, exist_ok=True)


def _storage() -> str:
    if _storage_dir is None:
        raise RuntimeError("call workflow.init(storage_dir) first")
    return _storage_dir


class Step:
    """One DAG node: a function + args (args may be other Steps).

    Per-step options (reference: `workflow.options(max_retries=...,
    catch_exceptions=...)`): `max_retries` re-executes a crashed/raising
    step before failing the workflow; `catch_exceptions=True` makes the
    step's checkpointed output `(result, None)` or `(None, exception)`
    so downstream steps decide how to proceed."""

    def __init__(self, fn, args: tuple, kwargs: dict, name: str,
                 max_retries: int = 0, catch_exceptions: bool = False):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.name = name
        self.max_retries = max_retries
        self.catch_exceptions = catch_exceptions
        self.step_id: Optional[str] = None  # assigned at run (deterministic)

    def options(self, *, max_retries: Optional[int] = None,
                catch_exceptions: Optional[bool] = None) -> "Step":
        # Copy semantics, matching _StepBuilder.options: a Step node
        # reused in two DAG positions must not inherit options applied
        # to one of them.
        return Step(
            self.fn, self.args, self.kwargs, self.name,
            max_retries=(self.max_retries if max_retries is None
                         else max_retries),
            catch_exceptions=(self.catch_exceptions
                              if catch_exceptions is None
                              else catch_exceptions))

    def run(self, workflow_id: str) -> Any:
        return run(self, workflow_id)

    def run_async(self, workflow_id: str):
        raise NotImplementedError("use run(); async execution TBD")


class _StepBuilder:
    def __init__(self, fn, max_retries: int = 0,
                 catch_exceptions: bool = False):
        self._fn = fn
        self._max_retries = max_retries
        self._catch_exceptions = catch_exceptions
        self.__name__ = getattr(fn, "__name__", "step")

    def step(self, *args, **kwargs) -> Step:
        return Step(self._fn, args, kwargs, self.__name__,
                    max_retries=self._max_retries,
                    catch_exceptions=self._catch_exceptions)

    def options(self, *, max_retries: Optional[int] = None,
                catch_exceptions: Optional[bool] = None) -> "_StepBuilder":
        return _StepBuilder(
            self._fn,
            self._max_retries if max_retries is None else max_retries,
            self._catch_exceptions if catch_exceptions is None
            else catch_exceptions)

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)


def step(fn=None, *, max_retries: int = 0, catch_exceptions: bool = False):
    if fn is None:
        return lambda f: _StepBuilder(f, max_retries, catch_exceptions)
    return _StepBuilder(fn, max_retries, catch_exceptions)


class EventStep(Step):
    """A DAG node that completes when an external event arrives
    (reference: `workflow.wait_for_event` over event_listener.py).
    Executes DRIVER-side (it only polls); its delivered payload
    checkpoints like any step output, so resume replays instead of
    re-waiting, and the listener's `event_checkpointed` ack fires only
    after the checkpoint is durable."""

    def __init__(self, listener, timeout=None, name: str = "wait_for_event"):
        super().__init__(fn=None, args=(), kwargs={}, name=name)
        self.listener = listener
        self.timeout = timeout

    def options(self, *, max_retries=None,
                catch_exceptions=None) -> "EventStep":
        # Step.options copy semantics would produce a plain Step (fn=None,
        # listener dropped) that crashes at execution.
        out = EventStep(self.listener, self.timeout, self.name)
        out.max_retries = (self.max_retries if max_retries is None
                           else max_retries)
        out.catch_exceptions = (self.catch_exceptions
                                if catch_exceptions is None
                                else catch_exceptions)
        return out


# ---------------------------------------------------------------- executor

def _assign_ids(root: Step) -> List[Step]:
    """Deterministic ids from DAG structure (stable across resumes)."""
    order: List[Step] = []
    counter: Dict[str, int] = {}

    def visit(node: Step):
        for a in list(node.args) + list(node.kwargs.values()):
            if isinstance(a, Step):
                visit(a)
        if node.step_id is None:
            idx = counter.get(node.name, 0)
            counter[node.name] = idx + 1
            node.step_id = f"{node.name}_{idx}"
            order.append(node)

    visit(root)
    return order  # topological: dependencies before dependents


def _wf_dir(workflow_id: str) -> str:
    return os.path.join(_storage(), workflow_id)


def _step_output_path(workflow_id: str, step_id: str) -> str:
    return os.path.join(_wf_dir(workflow_id), f"step_{step_id}.pkl")


def _set_status(workflow_id: str, status: str) -> None:
    meta = os.path.join(_wf_dir(workflow_id), "status.json")
    with open(meta + ".tmp", "w") as f:
        json.dump({"status": status, "ts": time.time()}, f)
    os.replace(meta + ".tmp", meta)


def run(dag: Step, workflow_id: str) -> Any:
    """Execute the DAG durably. The DAG definition itself persists first so
    `resume(workflow_id)` works without re-supplying code."""
    wf_dir = _wf_dir(workflow_id)
    os.makedirs(wf_dir, exist_ok=True)
    dag_path = os.path.join(wf_dir, "dag.pkl")
    if not os.path.exists(dag_path):
        with open(dag_path, "wb") as f:
            cloudpickle.dump(dag, f)
    return _execute(dag, workflow_id)


def _execute(dag: Step, workflow_id: str) -> Any:
    _set_status(workflow_id, "RUNNING")
    steps = _assign_ids(dag)
    results: Dict[str, Any] = {}

    try:
        for node in steps:  # topological order
            out_path = _step_output_path(workflow_id, node.step_id)
            if os.path.exists(out_path):
                with open(out_path, "rb") as f:
                    results[node.step_id] = pickle.load(f)
                continue  # checkpointed by a previous run: replay, not rerun

            def resolve(v):
                return results[v.step_id] if isinstance(v, Step) else v

            if isinstance(node, EventStep):
                node.listener.bind(workflow_id, _storage())
                value = node.listener.poll_for_event(timeout=node.timeout)
                with open(out_path + ".tmp", "wb") as f:
                    cloudpickle.dump(value, f)
                os.replace(out_path + ".tmp", out_path)
                try:
                    node.listener.event_checkpointed(value)
                except Exception:
                    pass  # ack is best-effort; the checkpoint is durable
                results[node.step_id] = value
                continue

            args = tuple(resolve(a) for a in node.args)
            kwargs = {k: resolve(v) for k, v in node.kwargs.items()}
            remote_fn = ray_tpu.remote(node.fn)
            if node.max_retries:
                # Explicit per-step retries also retry on application
                # exceptions; steps WITHOUT explicit retries keep the
                # global crash-retry default (never override it with 0).
                remote_fn = remote_fn.options(
                    max_retries=node.max_retries, retry_exceptions=True)
            if node.catch_exceptions:
                try:
                    value = ray_tpu.get(remote_fn.remote(*args, **kwargs),
                                        timeout=3600)
                    value = (value, None)
                except Exception as e:  # noqa: BLE001
                    # Hand the user the application exception, not the
                    # RayTaskError transport wrapper.
                    value = (None, getattr(e, "cause", e))
            else:
                value = ray_tpu.get(remote_fn.remote(*args, **kwargs),
                                    timeout=3600)
            with open(out_path + ".tmp", "wb") as f:
                # cloudpickle: catch_exceptions outputs can hold
                # dynamically-created RayTaskError subclasses that plain
                # pickle cannot serialize by reference.
                cloudpickle.dump(value, f)
            os.replace(out_path + ".tmp", out_path)  # atomic checkpoint
            results[node.step_id] = value
    except BaseException:
        _set_status(workflow_id, "FAILED")
        raise
    _set_status(workflow_id, "SUCCEEDED")
    return results[dag.step_id]


def resume(workflow_id: str) -> Any:
    """Continue an interrupted workflow from its persisted DAG + completed
    step checkpoints."""
    dag_path = os.path.join(_wf_dir(workflow_id), "dag.pkl")
    if not os.path.exists(dag_path):
        raise KeyError(f"no persisted workflow '{workflow_id}'")
    with open(dag_path, "rb") as f:
        dag = cloudpickle.load(f)
    return _execute(dag, workflow_id)


def get_status(workflow_id: str) -> Optional[str]:
    meta = os.path.join(_wf_dir(workflow_id), "status.json")
    if not os.path.exists(meta):
        return None
    with open(meta) as f:
        return json.load(f)["status"]


def get_output(workflow_id: str) -> Any:
    """Output of a finished workflow (from storage; no re-execution)."""
    with open(os.path.join(_wf_dir(workflow_id), "dag.pkl"), "rb") as f:
        dag = cloudpickle.load(f)
    steps = _assign_ids(dag)
    out_path = _step_output_path(workflow_id, steps[-1].step_id)
    if not os.path.exists(out_path):
        raise RuntimeError(f"workflow '{workflow_id}' has no final output "
                           "(resume it first)")
    with open(out_path, "rb") as f:
        return pickle.load(f)


def list_all() -> List[Dict[str, Any]]:
    out = []
    root = _storage()
    for wf_id in sorted(os.listdir(root)):
        status = get_status(wf_id)
        if status is not None:
            out.append({"workflow_id": wf_id, "status": status})
    return out

from ray_tpu.workflow.events import (  # noqa: E402
    EventListener, FileEventListener, HTTPEventProvider, wait_for_event,
)

from ray_tpu._private.usage_stats import record_library_usage as _rlu  # noqa: E402

_rlu("workflow")
del _rlu
