"""Workflow event system — durable external triggers.

Reference: `python/ray/workflow/event_listener.py:1` (EventListener ABC:
``poll_for_event`` + the post-checkpoint ``event_checkpointed`` ack) and
`python/ray/workflow/http_event_provider.py:1` (an HTTP endpoint
external systems POST events to; workflows park on them).

Redesign over this package's storage model: a delivered event is a FILE
in the workflow's storage directory (written atomically), so event
durability needs no extra service state —

* `wait_for_event(listener)` makes a DAG node that completes when the
  listener's poll returns. The payload checkpoints like any step
  output: a workflow that crashes AFTER delivery replays it from
  storage on resume (never re-waits); a crash BEFORE delivery resumes
  into the same poll. `event_checkpointed` fires only after the
  checkpoint is on disk — the at-least-once ack point for the external
  system.
* `HTTPEventProvider` exposes POST /event/{workflow_id}/{key} (body =
  JSON payload); it writes the event file the default
  `FileEventListener` polls. GET on the same path reads it back
  (delivery check for the poster).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Optional

__all__ = ["EventListener", "FileEventListener", "HTTPEventProvider",
           "wait_for_event"]


class EventListener:
    """One external-event source (reference: event_listener.py ABC)."""

    def bind(self, workflow_id: str, storage_dir: str) -> None:
        """Called by the executor before polling: runtime identity."""

    def poll_for_event(self, timeout: Optional[float] = None) -> Any:
        raise NotImplementedError

    def event_checkpointed(self, event: Any) -> None:
        """Post-checkpoint ack: the event is durable; the source may
        delete/commit it."""


def _event_path(storage_dir: str, workflow_id: str, key: str) -> str:
    return os.path.join(storage_dir, workflow_id, "events", f"{key}.json")


class FileEventListener(EventListener):
    """Polls the storage-backed event file the HTTP provider (or any
    writer) delivers. The default listener."""

    def __init__(self, event_key: str, poll_interval_s: float = 0.2):
        self.event_key = event_key
        self._poll = poll_interval_s
        self._wf_id: Optional[str] = None
        self._storage: Optional[str] = None

    def bind(self, workflow_id: str, storage_dir: str) -> None:
        self._wf_id = workflow_id
        self._storage = storage_dir

    def _path(self) -> str:
        if self._wf_id is None:
            raise RuntimeError("listener not bound to a workflow")
        return _event_path(self._storage, self._wf_id, self.event_key)

    def poll_for_event(self, timeout: Optional[float] = None) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        path = self._path()
        while True:
            if os.path.exists(path):
                with open(path) as f:
                    return json.load(f)
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(
                    f"no '{self.event_key}' event for workflow "
                    f"'{self._wf_id}' within {timeout}s")
            time.sleep(self._poll)


def deliver_event(storage_dir: str, workflow_id: str, key: str,
                  payload: Any) -> str:
    """Write an event file atomically (what the HTTP provider does; also
    usable directly by co-located systems/tests)."""
    path = _event_path(storage_dir, workflow_id, key)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path + ".tmp", "w") as f:
        json.dump(payload, f)
    os.replace(path + ".tmp", path)
    return path


class HTTPEventProvider:
    """POST /event/{workflow_id}/{key} -> durable event file.

    Reference: `workflow/http_event_provider.py` (a Serve deployment
    there; a plain aiohttp app here — it only needs to turn an HTTP
    request into one atomic file write)."""

    def __init__(self, storage_dir: str, host: str = "127.0.0.1",
                 port: int = 0):
        self._storage = storage_dir
        self._host = host
        self._requested_port = port
        self.port: Optional[int] = None
        self._runner = None
        self._thread = None

    def start(self) -> "HTTPEventProvider":
        import asyncio
        import threading

        from aiohttp import web

        async def post_event(req):
            wf, key = req.match_info["wf"], req.match_info["key"]
            try:
                payload = await req.json()
            except Exception:
                payload = (await req.read()).decode("utf-8", "replace")
            path = await asyncio.get_running_loop().run_in_executor(
                None, deliver_event, self._storage, wf, key, payload)
            return web.json_response({"delivered": True, "path": path})

        async def get_event(req):
            path = _event_path(self._storage, req.match_info["wf"],
                               req.match_info["key"])
            if not os.path.exists(path):
                return web.json_response({"delivered": False}, status=404)

            def _read():
                with open(path) as f:
                    return json.load(f)

            payload = await asyncio.get_running_loop().run_in_executor(
                None, _read)
            return web.json_response({"delivered": True,
                                      "payload": payload})

        app = web.Application()
        app.router.add_post("/event/{wf}/{key}", post_event)
        app.router.add_get("/event/{wf}/{key}", get_event)

        loop = asyncio.new_event_loop()
        started = threading.Event()

        def _run():
            asyncio.set_event_loop(loop)

            async def _up():
                self._runner = web.AppRunner(app)
                await self._runner.setup()
                site = web.TCPSite(self._runner, self._host,
                                   self._requested_port)
                await site.start()
                self.port = site._server.sockets[0].getsockname()[1]
                started.set()

            loop.run_until_complete(_up())
            loop.run_forever()

        self._loop = loop
        self._thread = threading.Thread(target=_run, daemon=True,
                                        name="wf-event-provider")
        self._thread.start()
        if not started.wait(timeout=10):
            raise RuntimeError("event provider failed to start")
        return self

    def stop(self) -> None:
        import asyncio

        if self._thread is None:
            return

        async def _down():
            if self._runner is not None:
                await self._runner.cleanup()
            self._loop.stop()

        try:
            asyncio.run_coroutine_threadsafe(_down(), self._loop)
            self._thread.join(timeout=5)
        except Exception:
            pass


def wait_for_event(listener, *, timeout: Optional[float] = None,
                   name: str = "wait_for_event"):
    """DAG node that parks until the listener's event arrives
    (reference: `workflow.wait_for_event`). `listener`: an EventListener
    instance, a zero-arg factory, or an event-key string (shorthand for
    the default FileEventListener)."""
    from ray_tpu.workflow import EventStep

    if isinstance(listener, str):
        listener = FileEventListener(listener)
    elif callable(listener) and not isinstance(listener, EventListener):
        listener = listener()
    return EventStep(listener, timeout=timeout, name=name)
