"""Grafana dashboard generator (reference:
`dashboard/modules/metrics/grafana_dashboard_factory.py` +
`default_dashboard_panels.py` — auto-generated dashboards over the
Prometheus metrics the cluster exports).

`generate_default_dashboard()` returns a Grafana dashboard JSON whose
panels query the `rtpu_*` series served by the GCS `/metrics` endpoint;
`write_dashboard(path)` drops it where Grafana provisioning can pick it
up.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

_PANELS: List[Dict[str, str]] = [
    {"title": "Alive nodes", "expr": 'rtpu_nodes{state="ALIVE"}',
     "unit": "short"},
    {"title": "Actors by state", "expr": "rtpu_actors",
     "legend": "{{state}}", "unit": "short"},
    {"title": "Task events by state",
     "expr": "rate(rtpu_tasks_events_total[5m])",
     "legend": "{{state}}", "unit": "short"},
    {"title": "Cluster events rate",
     "expr": "rate(rtpu_cluster_events_total[5m])",
     "legend": "{{type}}/{{severity}}", "unit": "short"},
    {"title": "CPU available vs total",
     "expr": 'rtpu_resource_available{resource="CPU"}',
     "expr_b": 'rtpu_resource_capacity{resource="CPU"}',
     "unit": "short"},
    {"title": "TPU available vs total",
     "expr": 'rtpu_resource_available{resource="TPU"}',
     "expr_b": 'rtpu_resource_capacity{resource="TPU"}',
     "unit": "short"},
    {"title": "Object store used",
     "expr": 'rtpu_resource_capacity{resource="object_store_memory"} - '
             'rtpu_resource_available{resource="object_store_memory"}',
     "unit": "bytes"},
    {"title": "Placement groups",
     "expr": "rtpu_placement_groups", "legend": "{{state}}",
     "unit": "short"},
    # --- serving / JIT / device telemetry (observability plane) ---
    {"title": "Serve TTFT p50/p99",
     "expr": 'histogram_quantile(0.5, '
             'rate(rtpu_serve_ttft_seconds_bucket[5m]))',
     "expr_b": 'histogram_quantile(0.99, '
               'rate(rtpu_serve_ttft_seconds_bucket[5m]))',
     "unit": "s"},
    {"title": "Serve e2e latency p50/p99",
     "expr": 'histogram_quantile(0.5, '
             'rate(rtpu_serve_e2e_seconds_bucket[5m]))',
     "expr_b": 'histogram_quantile(0.99, '
               'rate(rtpu_serve_e2e_seconds_bucket[5m]))',
     "unit": "s"},
    {"title": "Serve tokens/sec",
     "expr": "rate(rtpu_serve_tokens_total[1m])", "unit": "short"},
    {"title": "Serve queue depth / active slots",
     "expr": "rtpu_serve_queue_depth",
     "expr_b": "rtpu_serve_active_slots", "unit": "short"},
    {"title": "JIT retraces (recompiles)",
     "expr": "rate(rtpu_jit_traces_total[5m])",
     "legend": "{{fn}}", "unit": "short"},
    {"title": "JIT compile time",
     "expr": "rate(rtpu_jit_compile_seconds_sum[5m])",
     "legend": "{{fn}}", "unit": "s"},
    {"title": "Device HBM used vs total",
     "expr": "rtpu_device_hbm_used_bytes",
     "expr_b": "rtpu_device_hbm_total_bytes", "unit": "bytes"},
    # --- live profiling plane: scheduling-latency breakdown ---
    {"title": "Scheduling phase latency p50/p99",
     "expr": 'histogram_quantile(0.5, '
             'rate(rtpu_sched_phase_seconds_bucket[5m]))',
     "expr_b": 'histogram_quantile(0.99, '
               'rate(rtpu_sched_phase_seconds_bucket[5m]))',
     "legend": "{{phase}}", "unit": "s"},
    # --- memory & data-pipeline observability plane ---
    {"title": "Object store utilization (per node)",
     "expr": "rtpu_object_store_used_bytes",
     "expr_b": "rtpu_object_store_capacity_bytes",
     "legend": "{{node}}", "unit": "bytes"},
    {"title": "Spill / restore rate",
     "expr": "rate(rtpu_object_store_spills_total[5m])",
     "expr_b": "rate(rtpu_object_store_restores_total[5m])",
     "legend": "{{node}}", "unit": "short"},
    {"title": "Data pipeline rows/sec per stage",
     "expr": "rate(rtpu_data_rows_out_total[1m])",
     "legend": "{{stage}}", "unit": "short"},
    {"title": "Data backpressure: in-flight / queued per stage",
     "expr": "rtpu_data_inflight_tasks",
     "expr_b": "rtpu_data_queued_blocks",
     "legend": "{{stage}}", "unit": "short"},
    # --- paged KV cache & LLM router (serve/llm/kv_cache.py, router.py) ---
    {"title": "KV pool utilization",
     "expr": "rtpu_serve_kv_blocks_used / "
             "(rtpu_serve_kv_blocks_used + rtpu_serve_kv_blocks_free)",
     "unit": "percentunit"},
    {"title": "KV blocks used vs free",
     "expr": "rtpu_serve_kv_blocks_used",
     "expr_b": "rtpu_serve_kv_blocks_free", "unit": "short"},
    {"title": "Prefix-cache hit rate",
     "expr": "rate(rtpu_serve_prefix_cache_hits_total[5m]) / "
             "(rate(rtpu_serve_prefix_cache_hits_total[5m]) + "
             "rate(rtpu_serve_prefix_cache_misses_total[5m]))",
     "unit": "percentunit"},
    {"title": "Prefill tokens skipped via prefix cache",
     "expr": "rate(rtpu_serve_prefix_cache_hit_tokens_total[1m])",
     "unit": "short"},
    {"title": "Router queue depth per replica",
     "expr": "rtpu_serve_router_queue_depth",
     "legend": "{{replica}}", "unit": "short"},
    {"title": "Router requests per replica",
     "expr": "rate(rtpu_serve_router_requests_total[5m])",
     "legend": "{{replica}}", "unit": "short"},
    # --- disaggregated serving (serve/llm/disagg) ---
    {"title": "Lane queue depth",
     "expr": "rtpu_serve_lane_queue_depth",
     "legend": "{{lane}}", "unit": "short"},
    {"title": "Batch-decode preemptions",
     "expr": "rate(rtpu_serve_preemptions_total[5m])",
     "legend": "{{lane}}", "unit": "short"},
    {"title": "KV migration rate (blocks, bytes/sec)",
     "expr": "rate(rtpu_serve_kv_migrated_blocks_total[5m])",
     "expr_b": "rate(rtpu_serve_kv_migrated_bytes_total[5m])",
     "unit": "short"},
    {"title": "Speculative-decode accept ratio",
     "expr": "rtpu_serve_spec_accept_ratio",
     "unit": "percentunit"},
    {"title": "Router lane routing",
     "expr": "rate(rtpu_serve_router_lane_requests_total[5m])",
     "legend": "{{lane}}/{{pool}}", "unit": "short"},
    # --- KV memory hierarchy (kv_cache.KVTierManager + cache-aware router) ---
    {"title": "KV tier residency (hbm/host/store)",
     "expr": "rtpu_serve_kv_tier_bytes",
     "legend": "{{tier}}", "unit": "bytes"},
    {"title": "KV tier traffic: spills vs promotes",
     "expr": "rate(rtpu_serve_prefix_tier_spills_total[5m])",
     "expr_b": "rate(rtpu_serve_prefix_tier_promotes_total[5m])",
     "legend": "{{tier}}", "unit": "short"},
    # --- collectives (Pallas ICI backend + util.collective API) ---
    {"title": "Collective ops rate",
     "expr": "rate(rtpu_collective_ops_total[5m])",
     "legend": "{{op}}/{{backend}}", "unit": "short"},
    {"title": "Collective bytes/sec",
     "expr": "rate(rtpu_collective_bytes_total[5m])",
     "legend": "{{op}}/{{backend}}/{{dtype}}", "unit": "Bps"},
    {"title": "Collective op latency p50/p99",
     "expr": 'histogram_quantile(0.5, '
             'rate(rtpu_collective_op_seconds_bucket[5m]))',
     "expr_b": 'histogram_quantile(0.99, '
               'rate(rtpu_collective_op_seconds_bucket[5m]))',
     "legend": "{{op}}/{{backend}}", "unit": "s"},
    {"title": "Exposed comm fraction (split-phase overlap)",
     "expr": "rate(rtpu_collective_exposed_seconds_sum[5m]) / "
             "(rate(rtpu_collective_exposed_seconds_sum[5m]) + "
             "rate(rtpu_collective_hidden_seconds_sum[5m]))",
     "legend": "{{op}}/{{backend}}", "unit": "percentunit"},
    # --- request-scoped tracing (util/tracing.py + TraceStore) ---
    {"title": "Traces kept vs sampled out",
     "expr": "rate(rtpu_trace_kept_total[5m])",
     "expr_b": "rate(rtpu_trace_sampled_out_total[5m])",
     "unit": "short"},
    {"title": "Trace store pressure (pending, drops/sec)",
     "expr": "rtpu_trace_pending",
     "expr_b": "rate(rtpu_trace_spans_dropped_total[5m])",
     "unit": "short"},
    # --- metrics-driven control plane ---
    {"title": "Serve replicas (autoscaler)",
     "expr": "rtpu_serve_replicas",
     "legend": "{{deployment}}", "unit": "short"},
    {"title": "Control decisions rate",
     "expr": "rate(rtpu_ctrl_decisions_total[5m])",
     "legend": "{{controller}}/{{action}}", "unit": "short"},
    # --- decoupled RL (podracer plane) ---
    {"title": "RL acting vs learning throughput",
     "expr": "rate(rtpu_rl_env_steps_total[1m])",
     "expr_b": "rate(rtpu_rl_samples_total[1m])", "unit": "short"},
    {"title": "RL weight version / staleness",
     "expr": "rtpu_rl_weight_version",
     "expr_b": "rtpu_rl_weight_staleness", "unit": "short"},
    {"title": "RL sample queue depth / backpressure",
     "expr": "rtpu_rl_sample_queue_depth",
     "expr_b": "rate(rtpu_rl_backpressure_waits_total[5m])",
     "unit": "short"},
    {"title": "RL inference batching factor",
     "expr": "rate(rtpu_rl_infer_requests_total[5m]) / "
             "rate(rtpu_rl_infer_batches_total[5m])",
     "unit": "short"},
    # --- training goodput & stragglers (observability/goodput.py) ---
    {"title": "Train goodput ratio",
     "expr": "rtpu_train_goodput_ratio",
     "unit": "percentunit"},
    {"title": "Train step phase breakdown p50",
     "expr": 'histogram_quantile(0.5, '
             'rate(rtpu_train_step_phase_seconds_bucket[5m]))',
     "legend": "{{phase}}", "unit": "s"},
    {"title": "Train lost seconds by cause",
     "expr": "rate(rtpu_train_lost_seconds_total[5m])",
     "legend": "{{cause}}", "unit": "s"},
    {"title": "Train stragglers / stalls",
     "expr": 'rate(rtpu_cluster_events_total'
             '{type="TRAIN_STRAGGLER"}[5m])',
     "expr_b": 'rate(rtpu_cluster_events_total'
               '{type="TRAIN_STALL"}[5m])',
     "unit": "short"},
    # --- per-request cost accounting & SLO plane (observability/accounting) ---
    {"title": "Tenant chip-seconds/sec",
     "expr": "rate(rtpu_serve_tenant_chip_seconds_total[5m])",
     "legend": "{{tenant}}", "unit": "s"},
    {"title": "Tenant tokens/sec",
     "expr": "rate(rtpu_serve_tenant_tokens_total[1m])",
     "legend": "{{tenant}}", "unit": "short"},
    {"title": "Tenant KV block-seconds/sec",
     "expr": "rate(rtpu_serve_tenant_block_seconds_total[5m])",
     "legend": "{{tenant}}", "unit": "s"},
    {"title": "Request cost p50/p99 (chip-seconds)",
     "expr": 'histogram_quantile(0.5, '
             'rate(rtpu_serve_request_cost_chip_seconds_bucket[5m]))',
     "expr_b": 'histogram_quantile(0.99, '
               'rate(rtpu_serve_request_cost_chip_seconds_bucket[5m]))',
     "unit": "s"},
    {"title": "SLO attainment per lane",
     "expr": "rtpu_serve_slo_attainment_ratio",
     "legend": "{{lane}}", "unit": "percentunit"},
    {"title": "SLO burn rate (fast vs slow)",
     "expr": 'rtpu_serve_slo_burn_rate{window="fast"}',
     "expr_b": 'rtpu_serve_slo_burn_rate{window="slow"}',
     "legend": "{{lane}}/{{window}}", "unit": "short"},
    {"title": "SLO burn events",
     "expr": 'rate(rtpu_cluster_events_total{type="SLO_BURN"}[5m])',
     "unit": "short"},
    # --- XLA program cost & roofline attribution (observability/xla) ---
    {"title": "Program MFU / MBU",
     "expr": "rtpu_xla_program_mfu",
     "expr_b": "rtpu_xla_program_mbu",
     "legend": "{{fn}}", "unit": "percentunit"},
    {"title": "Program FLOPs / peak HBM bytes",
     "expr": "rtpu_xla_program_flops",
     "expr_b": "rtpu_xla_program_bytes_hbm",
     "legend": "{{fn}}", "unit": "short"},
    {"title": "Sampled program wall p50/p99",
     "expr": 'histogram_quantile(0.5, '
             'rate(rtpu_xla_program_wall_seconds_bucket[5m]))',
     "expr_b": 'histogram_quantile(0.99, '
               'rate(rtpu_xla_program_wall_seconds_bucket[5m]))',
     "legend": "{{fn}}", "unit": "s"},
    {"title": "Perf regression events",
     "expr": 'rate(rtpu_cluster_events_total'
             '{type="PERF_REGRESSION"}[5m])',
     "unit": "short"},
]


def _panel(spec: Dict[str, str], panel_id: int, x: int, y: int
           ) -> Dict[str, Any]:
    targets = [{"expr": spec["expr"], "refId": "A",
                "legendFormat": spec.get("legend", "")}]
    if "expr_b" in spec:
        targets.append({"expr": spec["expr_b"], "refId": "B",
                        "legendFormat": "total"})
    return {
        "id": panel_id, "title": spec["title"], "type": "timeseries",
        "datasource": {"type": "prometheus", "uid": "${datasource}"},
        "gridPos": {"h": 8, "w": 12, "x": x, "y": y},
        "fieldConfig": {"defaults": {"unit": spec.get("unit", "short")},
                        "overrides": []},
        "targets": targets,
    }


def generate_default_dashboard(
        extra_metric_names: Optional[List[str]] = None) -> Dict[str, Any]:
    """The default cluster dashboard; `extra_metric_names` appends one
    panel per user-defined metric (ray_tpu.util.metrics name, without
    the rtpu_ prefix)."""
    specs = list(_PANELS)
    for name in extra_metric_names or []:
        specs.append({"title": name, "expr": f"rtpu_{name}"})
    panels = [_panel(s, i + 1, (i % 2) * 12, (i // 2) * 8)
              for i, s in enumerate(specs)]
    return {
        "title": "ray_tpu cluster",
        "uid": "ray-tpu-default",
        "tags": ["ray_tpu", "generated"],
        "timezone": "browser",
        "schemaVersion": 39,
        "refresh": "10s",
        "time": {"from": "now-30m", "to": "now"},
        "templating": {"list": [{
            "name": "datasource", "type": "datasource",
            "query": "prometheus",
        }]},
        "panels": panels,
    }


def write_dashboard(path: str,
                    extra_metric_names: Optional[List[str]] = None) -> str:
    with open(path, "w") as f:
        json.dump(generate_default_dashboard(extra_metric_names), f,
                  indent=2)
    return path
