"""ray_tpu.dashboard — the cluster web UI / REST head.

Reference: `dashboard/head.py` + `dashboard/state_aggregator.py` — an
aiohttp server on the head node aggregating GCS + raylet state into REST
endpoints and a browser page.
"""
