"""Dashboard head — aiohttp REST + HTML over GCS/raylet state.

Reference: `dashboard/head.py` (aiohttp head server), `dashboard/
state_aggregator.py` (GCS+raylet aggregation), `modules/metrics` (the
Prometheus endpoint). Runs as its own process per head node
(`python -m ray_tpu.dashboard.head --gcs-host ... --gcs-port ...`);
the URL is registered in the GCS KV under "dashboard_url" so clients
and the CLI can find it.

Endpoints:
  GET /               SPA client (hash-routed views, no build step;
                      `dashboard/client.py` — reference
                      `dashboard/client/src/App.tsx`)
  GET /api/cluster    resource totals/availability
  GET /api/nodes      nodes + per-raylet stats (workers, store, OOM)
  GET /api/actors     actor table
  GET /api/jobs       job table
  GET /api/tasks      recent task lifecycle events
  GET /api/timeline   Chrome-trace JSON download (chrome://tracing)
  GET /api/serve      live serving/JIT telemetry summary
  GET /api/rl         decoupled-RL rollup: acting vs learning
                      throughput, weight version/staleness, sample
                      queue depth, inference batching factor
  GET /api/train      training goodput & straggler rollup: per-worker
                      step matrix rows (?worker=, ?limit=), goodput
                      ratio + lost seconds by cause, per-phase means,
                      stall/straggler flags
  GET /api/accounting serve cost accounting & SLO attainment: top-N
                      tenants by chip-seconds (?top_n=), per-lane
                      attainment/burn, per-request cost rows
                      (?tenant=, ?lane=, ?trace_id=, ?limit=), and the
                      serve_tenant_*/serve_request_cost_* metric series
  GET /api/programs   XLA program cost & roofline attribution: the
                      fleet's compiled-program set ranked by FLOPs,
                      peak HBM bytes, and lost-to-roofline headroom
                      (?top_n=), per-program rows with MFU/MBU and
                      verdicts (?fn=, ?verdict=, ?limit=), and the
                      xla_program_* metric series
  GET /api/memory     per-node object-store introspection + spill metrics
  GET /api/data       data-pipeline (DatasetStats) metric summary
  GET /api/events     ClusterEventLog (failure forensics) with ?type=,
                      ?severity= (INFO/WARNING/ERROR), ?node=, ?limit=
                      filters. Registered event types: WORKER_EXIT,
                      ACTOR_DEATH, ACTOR_RESTART, NODE_ADDED,
                      NODE_REMOVED, LEASE_RECLAIMED, TASK_RETRY,
                      SPILL_PRESSURE, JOB_STARTED, JOB_FINISHED,
                      AUTOSCALE_UP, AUTOSCALE_DOWN, PREEMPT_RESCHEDULE,
                      BACKPRESSURE_ADJUST, TRAIN_STRAGGLER, TRAIN_STALL,
                      SLO_BURN, PERF_REGRESSION.
  GET /api/controller control-plane decision log (serve autoscaler,
                      data backpressure, memory preemption) with
                      ?controller=, ?action=, ?limit= filters; each row
                      carries the metric reading that triggered it
  GET /api/logs       per-task/actor/worker log retrieval: exactly one
                      of ?task_id=, ?actor_id=, ?worker_id= (hex), plus
                      ?tail=N (default 100)
  GET /api/stacks     cluster-wide all-thread Python stack dump (the
                      `ray stack` equivalent): ?node=<hex prefix>,
                      ?worker=<hex> narrow the fan-out
  GET /api/profile    on-demand wall-clock sampling profile of a node's
                      workers: ?node=, ?worker=, ?duration=, ?hz=;
                      ?format=speedscope merges every worker into one
                      speedscope JSON (threads namespaced by worker)
  GET /api/profile/stacks  single-node stack dump (legacy spelling of
                      /api/stacks with a ?node= scope)
  GET /metrics        Prometheus text (scrape target)
"""

from __future__ import annotations

import argparse
import asyncio
import json
from typing import Any, Dict, List

from aiohttp import web

from ray_tpu._private.rpc import RpcClient

from ray_tpu.dashboard.client import HTML as _HTML


class DashboardHead:
    def __init__(self, gcs_host: str, gcs_port: int):
        self._gcs = RpcClient(gcs_host, gcs_port)
        self._gcs_addr = (gcs_host, gcs_port)
        self._job_client = None
        self._job_client_lock = __import__("threading").Lock()

    def _jobs_client(self):
        """Lazy embedded driver connection: job submission needs actor
        creation, so the dashboard becomes a (CPU-less) driver on first
        use (reference: job_head.py forwards to the JobManager's own
        core worker)."""
        with self._job_client_lock:
            if self._job_client is None:
                import ray_tpu
                from ray_tpu.job_submission import JobSubmissionClient

                ray_tpu.init(address="%s:%d" % self._gcs_addr,
                             ignore_reinit_error=True)
                self._job_client = JobSubmissionClient()
            return self._job_client

    # ------------------------------------------------------------ handlers
    async def index(self, _req) -> web.Response:
        return web.Response(text=_HTML, content_type="text/html")

    async def cluster(self, _req) -> web.Response:
        total = await self._gcs.acall("cluster_resources", timeout=10)
        avail = await self._gcs.acall("available_resources", timeout=10)
        return web.json_response({"total": total, "available": avail})

    async def nodes(self, _req) -> web.Response:
        nodes = await self._gcs.acall("get_all_nodes", timeout=10)
        out: List[Dict[str, Any]] = []
        for n in nodes:
            row = {
                "node_id": n["node_id"].hex()[:12],
                "state": n["state"],
                "addr": f"{n['addr'][0]}:{n['addr'][1]}",
                "total": n.get("total", {}),
                "available": n.get("available", {}),
            }
            if n["state"] == "ALIVE":
                client = RpcClient(*tuple(n["addr"]))
                try:
                    st = await client.acall("node_stats", timeout=5)
                    row.update(workers=st.get("num_workers"),
                               oom_kills=st.get("oom_kills"),
                               store=st.get("store", {}))
                except Exception as e:
                    row["stats_error"] = str(e)
                finally:
                    client.close()
            out.append(row)
        return web.json_response(out)

    async def actors(self, _req) -> web.Response:
        actors = await self._gcs.acall("list_actors", timeout=10)
        out = []
        for a in actors or []:
            if a is None:
                continue
            aid = a.get("actor_id")
            out.append({
                "actor_id": aid.hex()[:12] if isinstance(aid, bytes)
                else str(aid),
                "class": a.get("class_name", ""),
                "state": a.get("state", ""),
                "name": a.get("name") or "",
                "restarts": a.get("restarts_used", 0),
            })
        return web.json_response(out)

    async def jobs(self, _req) -> web.Response:
        jobs = await self._gcs.acall("list_jobs", timeout=10)
        out = []
        for j in jobs or []:
            jid = j.get("job_id")
            out.append({
                "job_id": jid.hex() if isinstance(jid, bytes) else str(jid),
                "state": j.get("state", ""),
                "namespace": (j.get("metadata") or {}).get("namespace", ""),
            })
        return web.json_response(out)

    async def tasks(self, req) -> web.Response:
        limit = int(req.query.get("limit", 200))
        events = await self._gcs.acall("get_task_events", limit=limit,
                                       timeout=10)
        safe = []
        for e in events or []:
            safe.append({k: (v.hex() if isinstance(v, bytes) else v)
                         for k, v in e.items()})
        return web.json_response(safe)

    async def metrics(self, _req) -> web.Response:
        text = await self._gcs.acall("metrics_text", timeout=10)
        return web.Response(text=text, content_type="text/plain")

    async def traces(self, req) -> web.Response:
        """Kept-trace summaries plus TraceStore health counters."""
        limit = int(req.query.get("limit", 100))
        summaries = await self._gcs.acall("list_traces", limit=limit,
                                          timeout=10)
        stats = await self._gcs.acall("trace_stats", timeout=10)
        return web.json_response(
            {"traces": summaries or [], "stats": stats or {}},
            dumps=lambda o: json.dumps(o, default=str))

    async def trace(self, req) -> web.Response:
        """One request's assembled causal tree: /api/trace?trace_id=."""
        trace_id = req.query.get("trace_id")
        if not trace_id:
            return web.json_response(
                {"error": "trace_id query parameter required"},
                status=400)
        rec = await self._gcs.acall("get_trace", trace_id=trace_id,
                                    timeout=10)
        if rec is None:
            return web.json_response(
                {"error": f"no trace {trace_id}"}, status=404)
        from ray_tpu.util.tracing import build_trace_tree, critical_path

        tree = build_trace_tree(rec.get("spans") or [])
        tree.update({"trace_id": trace_id,
                     "complete": bool(rec.get("complete")),
                     "dur": rec.get("dur"),
                     "error": rec.get("error", False),
                     "keep_reason": rec.get("keep_reason"),
                     "critical_path": critical_path(tree)})
        return web.json_response(
            tree, dumps=lambda o: json.dumps(o, default=str))

    async def timeline(self, req) -> web.Response:
        """Chrome-trace JSON of the task-event ring buffer — load in
        chrome://tracing or https://ui.perfetto.dev."""
        from ray_tpu._private.config import GlobalConfig
        from ray_tpu.observability.timeline import build_chrome_trace

        limit = int(req.query.get(
            "limit", GlobalConfig.task_events_buffer_size))
        events = await self._gcs.acall("get_task_events", limit=limit,
                                       timeout=30)
        trace = build_chrome_trace(events or [])
        resp = web.json_response(
            trace, dumps=lambda o: json.dumps(o, default=str))
        resp.headers["Content-Disposition"] = (
            'attachment; filename="timeline.json"')
        return resp

    async def serve_stats(self, _req) -> web.Response:
        """Live serving/JIT telemetry aggregated on the GCS (engine
        latency histograms, queue gauges, compile counters), plus an
        explicit paged-KV / prefix-cache / router rollup so the "is HBM
        or prefill the bottleneck?" question is one fetch away."""
        summary = await self._gcs.acall(
            "user_metrics_summary",
            prefixes=["serve_", "jit_", "device_"], timeout=10)
        summary = summary or {}

        def _total(name):
            entry = summary.get(name)
            if not entry or not entry.get("data"):
                return None
            return sum(float(v) for v in entry["data"].values())

        used, free = (_total("serve_kv_blocks_used"),
                      _total("serve_kv_blocks_free"))
        hits, misses = (_total("serve_prefix_cache_hits_total"),
                        _total("serve_prefix_cache_misses_total"))
        kv: Dict[str, Any] = {"blocks_used": used, "blocks_free": free}
        if used is not None and free is not None and (used + free):
            kv["utilization"] = used / (used + free)
        prefix: Dict[str, Any] = {
            "hits": hits, "misses": misses,
            "hit_tokens": _total("serve_prefix_cache_hit_tokens_total"),
            "evictions": _total("serve_prefix_cache_evictions_total"),
        }
        if hits is not None and misses is not None and (hits + misses):
            prefix["hit_rate"] = hits / (hits + misses)
        router_depth = summary.get("serve_router_queue_depth", {})
        summary["kv_cache"] = kv
        summary["prefix_cache"] = prefix
        summary["router"] = {
            "queue_depth": dict(router_depth.get("data", {})),
            "requests": dict(summary.get(
                "serve_router_requests_total", {}).get("data", {})),
            # Disagg pool split: series tagged (lane, pool) — how much
            # traffic each SLO lane sent down the two-hop path.
            "lane_requests": dict(summary.get(
                "serve_router_lane_requests_total", {}).get("data", {})),
        }
        # Disaggregated-serving rollup: KV migration volume between the
        # prefill and decode pools, per-lane queue pressure +
        # preemptions, and the speculative-decode acceptance ratio —
        # the "is prefill stealing decode slots?" playbook numbers
        # (PERF.md) in one fetch.
        spec_prop = _total("serve_spec_proposed_tokens_total")
        spec_acc = _total("serve_spec_accepted_tokens_total")
        disagg: Dict[str, Any] = {
            "kv_migrated_blocks": _total("serve_kv_migrated_blocks_total"),
            "kv_migrated_bytes": _total("serve_kv_migrated_bytes_total"),
            "lane_queue_depth": dict(summary.get(
                "serve_lane_queue_depth", {}).get("data", {})),
            "preemptions": dict(summary.get(
                "serve_preemptions_total", {}).get("data", {})),
            "spec_proposed": spec_prop,
            "spec_accepted": spec_acc,
        }
        if spec_prop:
            disagg["spec_accept_ratio"] = (spec_acc or 0.0) / spec_prop
        summary["disagg"] = disagg

        # KV memory hierarchy rollup: per-tier traffic + residency and
        # the cache-aware router's decision mix — the "is the cluster
        # re-prefilling what a peer already computed?" numbers (PERF.md)
        # in one fetch. Series are tagged; fold them per tag value.
        def _by_tag(name, key):
            entry = summary.get(name)
            if not entry or not entry.get("data"):
                return {}
            folded: Dict[str, float] = {}
            pat = key + '="'
            for labels, v in entry["data"].items():
                for part in labels.split(","):
                    if part.startswith(pat):
                        tag = part[len(pat):-1]
                        folded[tag] = folded.get(tag, 0.0) + float(v)
                        break
            return folded

        tier_hits = _by_tag("serve_prefix_tier_hits_total", "tier")
        tier_misses = _by_tag("serve_prefix_tier_misses_total", "tier")
        tiers: Dict[str, Any] = {
            "hits": tier_hits,
            "misses": tier_misses,
            "spills": _by_tag("serve_prefix_tier_spills_total", "tier"),
            "promotes": _by_tag(
                "serve_prefix_tier_promotes_total", "tier"),
            "bytes": _by_tag("serve_kv_tier_bytes", "tier"),
            "router_decisions": _by_tag(
                "serve_router_cache_decisions_total", "outcome"),
        }
        hit_rate = {}
        for t in tier_hits:
            n = tier_hits[t] + tier_misses.get(t, 0.0)
            if n:
                hit_rate[t] = tier_hits[t] / n
        if hit_rate:
            tiers["hit_rate"] = hit_rate
        # One router per app: report the WORST (oldest) live view, not
        # a meaningless sum across per-pid gauge series.
        ages = [float(v) for v in summary.get(
            "serve_router_index_age_seconds", {}).get(
                "data", {}).values()]
        if ages:
            tiers["index_age_s"] = max(ages)
        summary["kv_tiers"] = tiers
        return web.json_response(summary)

    async def rl_stats(self, _req) -> web.Response:
        """Decoupled-RL rollup: the "is acting or learning the
        bottleneck?" numbers in one fetch — env-step vs learner-sample
        throughput counters, the versioned weight channel's
        version/staleness gauges, sample-queue depth and backpressure,
        and the inference servers' achieved batching factor."""
        summary = await self._gcs.acall(
            "user_metrics_summary", prefixes=["rl_"], timeout=10)
        summary = summary or {}

        def _total(name):
            entry = summary.get(name)
            if not entry or not entry.get("data"):
                return None
            return sum(float(v) for v in entry["data"].values())

        def _max(name):
            entry = summary.get(name)
            if not entry or not entry.get("data"):
                return None
            return max(float(v) for v in entry["data"].values())

        requests, batches = (_total("rl_infer_requests_total"),
                             _total("rl_infer_batches_total"))
        rollup: Dict[str, Any] = {
            "env_steps": _total("rl_env_steps_total"),
            "samples": _total("rl_samples_total"),
            "weight_version": _max("rl_weight_version"),
            "weight_staleness": _max("rl_weight_staleness"),
            "sample_queue_depth": _total("rl_sample_queue_depth"),
            "backpressure_waits": _total("rl_backpressure_waits_total"),
            "dropped_stale": _total("rl_dropped_stale_total"),
        }
        if requests is not None and batches:
            rollup["infer_batching_factor"] = requests / batches
        summary["rollup"] = rollup
        return web.json_response(summary)

    async def train_stats(self, req) -> web.Response:
        """Training goodput & straggler page: the GCS cross-worker
        rollup (per-worker steps / stall / straggler flags, cluster
        goodput ratio, lost seconds by cause, per-phase means), the
        recent step-matrix rows (?worker= and ?limit= filter them), and
        the cluster-folded ``train_*`` metric series."""
        try:
            limit = int(req.query.get("limit", 50))
        except ValueError:
            return web.json_response({"error": "bad limit"}, status=400)
        summary = await self._gcs.acall("train_summary", timeout=10)
        rows = await self._gcs.acall(
            "list_train_steps", worker=req.query.get("worker"),
            limit=limit, timeout=10)
        metrics = await self._gcs.acall(
            "user_metrics_summary", prefixes=["train_"], timeout=10)
        return web.json_response({
            "summary": summary or {},
            "steps": rows or [],
            "metrics": metrics or {},
        })

    async def accounting(self, req) -> web.Response:
        """Serve cost accounting & SLO attainment: the GCS summary
        (top-N tenants by chip-seconds, per-lane SLO attainment/burn),
        recent per-request cost rows (?tenant=, ?lane=, ?trace_id= and
        ?limit= filter them), and the cluster-folded accounting metric
        series. ?trace_id= additionally surfaces that request's own
        cost row inside the summary (acceptance path for the
        x-trace-id a routed request returned)."""
        try:
            limit = int(req.query.get("limit", 50))
            top_n = int(req.query.get("top_n", 0)) or None
        except ValueError:
            return web.json_response({"error": "bad limit"}, status=400)
        trace_id = req.query.get("trace_id")
        summary = await self._gcs.acall(
            "serve_accounting_summary", top_n=top_n, trace_id=trace_id,
            timeout=10)
        rows = await self._gcs.acall(
            "list_serve_accounting",
            tenant=req.query.get("tenant"),
            lane=req.query.get("lane"),
            trace_id=trace_id, limit=limit, timeout=10)
        metrics = await self._gcs.acall(
            "user_metrics_summary",
            prefixes=["serve_tenant_", "serve_request_cost_"],
            timeout=10)
        return web.json_response({
            "summary": summary or {},
            "requests": rows or [],
            "metrics": metrics or {},
        })

    async def programs(self, req) -> web.Response:
        """XLA program cost & roofline attribution: the GCS summary
        (current program set ranked by cumulative FLOPs, peak HBM
        bytes, and lost-to-roofline headroom, with verdict/measurement
        counts), recent program rows (?fn=, ?verdict= and ?limit=
        filter them), and the cluster-folded ``xla_program_*`` metric
        series. Rows tagged ``measurement: "cpu"`` carry nominal-spec
        ratios — plumbing proof, not performance."""
        try:
            limit = int(req.query.get("limit", 50))
            top_n = int(req.query.get("top_n", 8))
        except ValueError:
            return web.json_response({"error": "bad limit"}, status=400)
        summary = await self._gcs.acall(
            "xla_summary", top_n=top_n, timeout=10)
        rows = await self._gcs.acall(
            "list_xla_programs", fn=req.query.get("fn"),
            verdict=req.query.get("verdict"), limit=limit, timeout=10)
        metrics = await self._gcs.acall(
            "user_metrics_summary", prefixes=["xla_program_"],
            timeout=10)
        return web.json_response({
            "summary": summary or {},
            "programs": rows or [],
            "metrics": metrics or {},
        })

    async def memory(self, req) -> web.Response:
        """Object-store memory introspection: live per-node snapshots
        straight from each raylet's store (same numbers
        ``ray_tpu.util.state.memory_summary()`` renders) plus the
        cluster-folded ``object_store_*`` metric series (which survive
        node exit via GCS tombstone folding)."""
        top_n = int(req.query.get("top_n", 10))
        nodes = await self._gcs.acall("get_all_nodes", timeout=10)
        out: List[Dict[str, Any]] = []
        for n in nodes or []:
            if n["state"] != "ALIVE":
                continue
            row: Dict[str, Any] = {"node_id": n["node_id"].hex()[:12]}
            client = RpcClient(*tuple(n["addr"]))
            try:
                snap = await client.acall("memory_stats", top_n=top_n,
                                          timeout=10)
                row["store"] = snap.get("store", {})
                row["top_objects"] = snap.get("objects", [])[:top_n]
            except Exception as e:
                row["stats_error"] = str(e)
            finally:
                client.close()
            out.append(row)
        summary = await self._gcs.acall(
            "user_metrics_summary", prefixes=["object_store_"], timeout=10)
        return web.json_response({"nodes": out, "metrics": summary or {}})

    async def events(self, req) -> web.Response:
        """ClusterEventLog query surface (failure forensics): typed,
        severity-tagged events with type/severity/node filters."""
        try:
            limit = int(req.query.get("limit", 100))
        except ValueError:
            return web.json_response({"error": "bad limit"}, status=400)
        rows = await self._gcs.acall(
            "list_cluster_events",
            event_type=req.query.get("type"),
            severity=req.query.get("severity"),
            node_id=req.query.get("node"),
            limit=limit, timeout=10)
        return web.json_response(rows or [])

    async def controller(self, req) -> web.Response:
        """Why did the control plane act? The GCS decision ring, newest
        last — every autoscale/backpressure/preempt action with the
        triggering metric reading attached."""
        try:
            limit = int(req.query.get("limit", 100))
        except ValueError:
            return web.json_response({"error": "bad limit"}, status=400)
        rows = await self._gcs.acall(
            "list_ctrl_decisions",
            controller=req.query.get("controller"),
            action=req.query.get("action"),
            limit=limit, timeout=10)
        return web.json_response(rows or [])

    async def logs(self, req) -> web.Response:
        """Per-task / per-actor / per-worker log retrieval, resolved
        through the GCS and served by the owning raylet from the on-disk
        log files (so dead workers' logs remain retrievable)."""
        task_id = req.query.get("task_id")
        actor_id = req.query.get("actor_id")
        worker_id = req.query.get("worker_id")
        if sum(bool(s) for s in (task_id, actor_id, worker_id)) != 1:
            return web.json_response(
                {"error": "exactly one of task_id=, actor_id=, "
                          "worker_id= is required"}, status=400)
        try:
            tail = int(req.query.get("tail", 100))
        except ValueError:
            return web.json_response({"error": "bad tail"}, status=400)
        try:
            if actor_id:
                info = await self._gcs.acall(
                    "get_actor_info", actor_id=bytes.fromhex(actor_id),
                    timeout=10)
                if not info or not info.get("worker_id"):
                    return web.json_response(
                        {"error": f"actor {actor_id} not found or has "
                                  "no worker"}, status=404)
                worker_id = info["worker_id"].hex()
            if worker_id:
                node_hex = None
                for row in await self._gcs.acall("list_workers",
                                                 timeout=10):
                    if row["worker_id"].hex() == worker_id:
                        node_hex = row["node_id"].hex()
                        break
                if node_hex is None:
                    return web.json_response(
                        {"error": f"worker {worker_id} not found"},
                        status=404)
                client = await self._node_raylet(node_hex)
                if client is None:
                    return web.json_response(
                        {"error": f"node {node_hex[:12]} unreachable"},
                        status=404)
                try:
                    reply = await client.acall(
                        "get_log", worker_id=bytes.fromhex(worker_id),
                        tail=tail, timeout=15)
                finally:
                    client.close()
                return web.json_response(
                    {"lines": reply.get("lines", [])})
            # task_id: fan out to every alive node; the attribution
            # markers make non-owners return nothing.
            lines: List[str] = []
            nodes = await self._gcs.acall("get_all_nodes", timeout=10)
            for n in nodes or []:
                if n["state"] != "ALIVE":
                    continue
                client = RpcClient(*tuple(n["addr"]))
                try:
                    reply = await client.acall(
                        "get_log", task_id=task_id, tail=tail,
                        timeout=15)
                    lines.extend(reply.get("lines", []))
                except Exception:
                    pass
                finally:
                    client.close()
            if tail:
                lines = lines[-tail:]
            return web.json_response({"lines": lines})
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)

    async def data_stats(self, _req) -> web.Response:
        """Data-pipeline telemetry: per-stage ``data_*`` series (rows/
        bytes/blocks out, wall vs blocked time, in-flight tasks and queue
        depth) aggregated on the GCS."""
        summary = await self._gcs.acall(
            "user_metrics_summary", prefixes=["data_"], timeout=10)
        return web.json_response(summary or {})

    # ---- profiling (reference: dashboard/modules/reporter/
    # profile_manager.py — on-demand stack dump + sampling CPU profile
    # per worker, flamegraph-able folded-stack payloads) ----------------

    async def _node_raylet(self, node_prefix):
        nodes = await self._gcs.acall("get_all_nodes", timeout=10)
        for n in nodes or []:
            if n["state"] != "ALIVE":
                continue
            if (node_prefix is None
                    or n["node_id"].hex().startswith(node_prefix)):
                return RpcClient(*n["addr"])
        return None

    async def profile(self, req) -> web.Response:
        client = await self._node_raylet(req.query.get("node"))
        if client is None:
            return web.json_response({"error": "no such node"}, status=404)
        kind = ("stacks" if req.path.endswith("/stacks") else "profile")
        wid = req.query.get("worker")
        hz = req.query.get("hz")
        try:
            out = await client.acall(
                "profile_worker",
                worker_id=bytes.fromhex(wid) if wid else None,
                duration_s=float(req.query.get("duration", 5.0)),
                kind=kind, hz=float(hz) if hz else None, timeout=120)
        finally:
            client.close()
        if kind == "profile" and req.query.get("format") == "speedscope":
            # One merged speedscope document: every worker's threads,
            # namespaced `<worker8>:<thread>`, over a shared frame table.
            from ray_tpu.observability import profiling as _profiling

            counts = {}
            for whex, rep in (out or {}).items():
                if isinstance(rep, dict):
                    _profiling.merge_counts(
                        counts, rep.get("counts") or {},
                        thread_prefix=f"{whex[:8]}:")
            return web.json_response(_profiling.render_speedscope(
                counts, name="ray_tpu node profile"))
        return web.json_response(out)

    async def stacks(self, req) -> web.Response:
        """Cluster-wide stack dump: fan the raylet `dump_stacks` RPC out
        to every alive node (optionally scoped by ?node= / ?worker=) and
        merge the per-worker replies."""
        node_prefix = req.query.get("node")
        wid = req.query.get("worker")
        nodes = await self._gcs.acall("get_all_nodes", timeout=10)
        out: Dict[str, Any] = {}
        for n in nodes or []:
            if n["state"] != "ALIVE":
                continue
            if node_prefix and \
                    not n["node_id"].hex().startswith(node_prefix):
                continue
            client = RpcClient(*tuple(n["addr"]))
            try:
                reply = await client.acall(
                    "dump_stacks",
                    worker_id=bytes.fromhex(wid) if wid else None,
                    timeout=20)
                out.update(reply or {})
            except Exception as e:  # noqa: BLE001
                out[f"node-{n['node_id'].hex()[:12]}"] = {"error": str(e)}
            finally:
                client.close()
        return web.json_response(out)

    # ---- job submission REST (reference: dashboard/modules/job/job_head
    # .py — POST/GET/logs endpoints so off-cluster clients submit over
    # HTTP; SDK/CLI counterpart in job_submission.JobSubmissionClient
    # with an http:// address) ------------------------------------------

    async def submit_job(self, req) -> web.Response:
        body = await req.json()
        entrypoint = body.get("entrypoint")
        if not entrypoint:
            return web.json_response(
                {"error": "entrypoint is required"}, status=400)
        loop = asyncio.get_running_loop()

        def _go():
            return self._jobs_client().submit_job(
                entrypoint=entrypoint,
                submission_id=body.get("submission_id"),
                env=body.get("env"),
                working_dir=body.get("working_dir"))

        try:
            sid = await loop.run_in_executor(None, _go)
        except Exception as e:  # noqa: BLE001
            return web.json_response({"error": str(e)}, status=500)
        return web.json_response({"submission_id": sid})

    async def list_job_submissions(self, _req) -> web.Response:
        loop = asyncio.get_running_loop()
        jobs = await loop.run_in_executor(
            None, lambda: self._jobs_client().list_jobs())
        return web.json_response(jobs)

    async def job_submission(self, req) -> web.Response:
        sid = req.match_info["sid"]
        loop = asyncio.get_running_loop()
        try:
            info = await loop.run_in_executor(
                None, lambda: self._jobs_client().get_job_info(sid))
        except KeyError:
            return web.json_response({"error": "no such job"}, status=404)
        return web.json_response(info)

    async def job_submission_logs(self, req) -> web.Response:
        sid = req.match_info["sid"]
        loop = asyncio.get_running_loop()
        try:
            logs = await loop.run_in_executor(
                None, lambda: self._jobs_client().get_job_logs(sid))
        except KeyError:
            return web.json_response({"error": "no such job"}, status=404)
        return web.json_response({"logs": logs})

    async def stop_job_submission(self, req) -> web.Response:
        sid = req.match_info["sid"]
        loop = asyncio.get_running_loop()
        try:
            stopped = await loop.run_in_executor(
                None, lambda: self._jobs_client().stop_job(sid))
        except KeyError:
            return web.json_response({"error": "no such job"}, status=404)
        return web.json_response({"stopped": bool(stopped)})

    # --------------------------------------------------------------- serve
    def build_app(self) -> web.Application:
        app = web.Application()
        app.router.add_get("/", self.index)
        app.router.add_get("/api/cluster", self.cluster)
        app.router.add_get("/api/nodes", self.nodes)
        app.router.add_get("/api/actors", self.actors)
        app.router.add_get("/api/jobs", self.jobs)
        app.router.add_get("/api/tasks", self.tasks)
        app.router.add_get("/metrics", self.metrics)
        app.router.add_get("/api/timeline", self.timeline)
        app.router.add_get("/api/traces", self.traces)
        app.router.add_get("/api/trace", self.trace)
        app.router.add_get("/api/serve", self.serve_stats)
        app.router.add_get("/api/rl", self.rl_stats)
        app.router.add_get("/api/train", self.train_stats)
        app.router.add_get("/api/accounting", self.accounting)
        app.router.add_get("/api/programs", self.programs)
        app.router.add_get("/api/memory", self.memory)
        app.router.add_get("/api/data", self.data_stats)
        app.router.add_get("/api/events", self.events)
        app.router.add_get("/api/controller", self.controller)
        app.router.add_get("/api/logs", self.logs)
        app.router.add_get("/api/profile", self.profile)
        app.router.add_get("/api/profile/stacks", self.profile)
        app.router.add_get("/api/stacks", self.stacks)
        app.router.add_post("/api/job_submissions", self.submit_job)
        app.router.add_get("/api/job_submissions", self.list_job_submissions)
        app.router.add_get("/api/job_submissions/{sid}", self.job_submission)
        app.router.add_get("/api/job_submissions/{sid}/logs",
                           self.job_submission_logs)
        app.router.add_post("/api/job_submissions/{sid}/stop",
                            self.stop_job_submission)
        return app


async def _serve(head: DashboardHead, host: str, port: int) -> int:
    runner = web.AppRunner(head.build_app())
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    port = runner.addresses[0][1]
    # Register for discovery (CLI / clients read this KV key). Same event
    # loop as every other GCS call — RpcClient connections are loop-bound.
    try:
        await head._gcs.acall(
            "kv_put", namespace="dashboard", key="dashboard_url",
            value=f"http://{host}:{port}".encode(), timeout=10)
    except Exception:
        pass
    return port


def main() -> None:
    import os
    import sys

    parser = argparse.ArgumentParser()
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--gcs-host", required=True)
    parser.add_argument("--gcs-port", type=int, required=True)
    parser.add_argument("--fate-share-pid", type=int, default=0)
    # Identification only: puts the session dir on the command line so
    # `pkill -f <session_dir>` cleanup and humans can find the daemon
    # that belongs to a session.
    parser.add_argument("--session-dir", default="")
    args = parser.parse_args()

    if args.fate_share_pid:
        from ray_tpu._private.fate_share import watch_parent

        watch_parent(args.fate_share_pid)

    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    head = DashboardHead(args.gcs_host, args.gcs_port)
    port = loop.run_until_complete(_serve(head, args.host, args.port))
    print(f"DASHBOARD_PORT={port}", flush=True)
    sys.stdout.flush()

    async def _gcs_watchdog():
        # The dashboard must never outlive its cluster: without this, a
        # no-fate-share start (`ray_tpu start --head`) leaks the process
        # forever once the GCS goes away (observed as a cross-test daemon
        # leak). Tolerate brief GCS bounces; exit after sustained loss.
        misses = 0
        while True:
            await asyncio.sleep(5.0)
            try:
                await head._gcs.acall("get_all_nodes", timeout=5)
                misses = 0
            except Exception:
                misses += 1
                if misses >= 6:
                    sys.stderr.write(
                        "[dashboard] GCS unreachable for ~30s; exiting\n")
                    os._exit(0)

    from ray_tpu._private.rpc import spawn_task

    spawn_task(_gcs_watchdog(), loop=loop)
    loop.run_forever()


if __name__ == "__main__":
    main()
