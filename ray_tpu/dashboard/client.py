"""Dashboard SPA client — a single-file, no-build equivalent of the
reference's React app (`dashboard/client/src/App.tsx:1`, routes in
`App.tsx`: Overview/Cluster/Actors/Jobs/Tasks + job detail/logs).

Hash-routed views over the head's REST API, auto-refreshing, with a job
submission form, per-submission detail + logs, and stop buttons. All
dynamic data lands via createElement/textContent — actor class names,
job entrypoints etc. are user-controlled strings, so innerHTML on them
would be stored XSS (same discipline the old single page had).
"""

HTML = r"""<!doctype html>
<html><head><meta charset="utf-8"><title>ray_tpu dashboard</title>
<style>
:root { --bg:#fff; --fg:#1a1a2e; --mut:#667; --line:#e3e6ec;
        --acc:#4455dd; --ok:#1a7f37; --bad:#c0392b; }
* { box-sizing: border-box; }
body { font: 14px/1.45 system-ui, sans-serif; margin: 0;
       color: var(--fg); background: var(--bg); }
nav { display: flex; gap: .25rem; padding: .6rem 1rem; border-bottom:
      1px solid var(--line); align-items: center; flex-wrap: wrap; }
nav b { margin-right: 1rem; }
nav a { padding: .35rem .7rem; border-radius: 6px; color: var(--fg);
        text-decoration: none; }
nav a.on { background: var(--acc); color: #fff; }
main { padding: 1rem; max-width: 1200px; }
.tiles { display: flex; gap: .75rem; flex-wrap: wrap; margin: .5rem 0 1rem; }
.tile { border: 1px solid var(--line); border-radius: 8px;
        padding: .6rem .9rem; min-width: 9rem; }
.tile .v { font-size: 1.4rem; font-weight: 600; }
.tile .k { color: var(--mut); font-size: .8rem; }
table { border-collapse: collapse; width: 100%; margin: .5rem 0 1.5rem; }
th { text-align: left; color: var(--mut); font-weight: 500; }
th, td { padding: .35rem .6rem; border-bottom: 1px solid var(--line);
         font-size: .85rem; vertical-align: top; max-width: 26rem;
         overflow-wrap: anywhere; }
tr:hover td { background: #f6f7fb; }
.ok { color: var(--ok); } .bad { color: var(--bad); }
button { border: 1px solid var(--line); background: #fff; padding:
         .3rem .7rem; border-radius: 6px; cursor: pointer; }
button.danger { color: var(--bad); border-color: var(--bad); }
pre { background: #14161f; color: #dde2ee; padding: .8rem; border-radius:
      8px; overflow: auto; max-height: 28rem; }
input, select { padding: .35rem .5rem; border: 1px solid var(--line);
        border-radius: 6px; min-width: 22rem; }
.muted { color: var(--mut); }
</style></head>
<body>
<nav><b>ray_tpu</b>
<a href="#/overview">Overview</a><a href="#/nodes">Nodes</a>
<a href="#/actors">Actors</a><a href="#/jobs">Jobs</a>
<a href="#/submissions">Submissions</a><a href="#/tasks">Tasks</a>
<span id="beat" class="muted" style="margin-left:auto"></span></nav>
<main id="view"></main>
<script>
"use strict";
const $ = (t, attrs = {}, kids = []) => {
  const e = document.createElement(t);
  for (const [k, v] of Object.entries(attrs)) {
    if (k === "text") e.textContent = v;
    else if (k === "click") e.addEventListener("click", v);
    else e.setAttribute(k, v);
  }
  for (const k of kids) e.appendChild(k);
  return e;
};
const api = async (path, opts) => {
  const r = await fetch(path, opts);
  if (!r.ok) throw new Error(path + " -> " + r.status);
  return r.json();
};
const fmt = (v) => typeof v === "object" ? JSON.stringify(v) : String(v);

function dataTable(rows, opts = {}) {
  if (!rows || !rows.length)
    return $("p", {class: "muted", text: "none"});
  const cols = opts.cols || Object.keys(rows[0]);
  const head = $("tr", {}, cols.map(c => $("th", {text: c})));
  const body = rows.map(r => $("tr", {}, cols.map(c => {
    const td = $("td");
    if (opts.render && opts.render[c]) td.appendChild(opts.render[c](r));
    else {
      td.textContent = fmt(r[c] === undefined ? "" : r[c]);
      if (/^(ALIVE|RUNNING|SUCCEEDED|FINISHED)$/.test(r[c]))
        td.className = "ok";
      if (/^(DEAD|FAILED|STOPPED)$/.test(r[c])) td.className = "bad";
    }
    return td;
  })));
  return $("table", {}, [head, ...body]);
}

const views = {
  async overview(el) {
    const [cl, nodes, actors, jobs] = await Promise.all([
      api("/api/cluster"), api("/api/nodes"), api("/api/actors"),
      api("/api/jobs")]);
    const tile = (k, v) => $("div", {class: "tile"}, [
      $("div", {class: "v", text: fmt(v)}),
      $("div", {class: "k", text: k})]);
    const cpuT = cl.total.CPU || 0, cpuA = cl.available.CPU || 0;
    el.appendChild($("div", {class: "tiles"}, [
      tile("nodes", nodes.length),
      tile("CPU used / total", (cpuT - cpuA).toFixed(1) + " / " + cpuT),
      tile("TPU total", cl.total.TPU || 0),
      tile("actors alive",
           actors.filter(a => a.state === "ALIVE").length),
      tile("jobs", jobs.length)]));
    el.appendChild($("h3", {text: "Resources"}));
    el.appendChild(dataTable([
      {kind: "total", ...cl.total}, {kind: "available", ...cl.available}]));
    el.appendChild($("h3", {text: "Nodes"}));
    el.appendChild(dataTable(nodes));
  },
  async nodes(el) { el.appendChild(dataTable(await api("/api/nodes"))); },
  async actors(el) { el.appendChild(dataTable(await api("/api/actors"))); },
  async jobs(el) { el.appendChild(dataTable(await api("/api/jobs"))); },
  async submissions(el) {
    const entry = $("input", {placeholder:
      "entrypoint, e.g. python -c \"print('hi')\""});
    const go = $("button", {text: "submit", click: async () => {
      if (!entry.value) return;
      await api("/api/job_submissions", {method: "POST",
        headers: {"content-type": "application/json"},
        body: JSON.stringify({entrypoint: entry.value})});
      route();
    }});
    el.appendChild($("div", {}, [entry, document.createTextNode(" "), go]));
    const subs = await api("/api/job_submissions");
    el.appendChild(dataTable(subs, {render: {
      submission_id: (r) => $("a",
        {href: "#/submission/" + r.submission_id,
         text: r.submission_id}),
      actions: (r) => $("button", {class: "danger", text: "stop",
        click: async () => {
          await api("/api/job_submissions/" + r.submission_id + "/stop",
                    {method: "POST"});
          route();
        }}),
    }, cols: [...(subs.length ? Object.keys(subs[0]) : []), "actions"]}));
  },
  async submission(el, sid) {
    const info = await api("/api/job_submissions/" + sid);
    el.appendChild($("h3", {text: "submission " + sid}));
    el.appendChild(dataTable([info]));
    const logs = await fetch(
      "/api/job_submissions/" + sid + "/logs");
    const body = await logs.text();
    let text = body;
    try { text = JSON.parse(body).logs ?? body; } catch (e) {}
    el.appendChild($("h3", {text: "logs"}));
    el.appendChild($("pre", {text: text || "(empty)"}));
  },
  async tasks(el) {
    const tasks = await api("/api/tasks?limit=200");
    el.appendChild(dataTable(tasks));
  },
};

let timer = null;
let gen = 0;                 // stale-response guard across navigations
async function route() {
  const hash = location.hash || "#/overview";
  const [name, arg] = hash.slice(2).split("/");
  document.querySelectorAll("nav a").forEach(a =>
    a.classList.toggle("on", a.getAttribute("href") === "#/" + name));
  const myGen = ++gen;
  // Render into a detached element: if the user navigates away while
  // this view's fetches are in flight, the late continuation must not
  // append stale content into the new view.
  const el = document.createElement("div");
  try {
    await (views[name] || views.overview)(el, arg);
    if (myGen !== gen) return;
    document.getElementById("beat").textContent =
      "updated " + new Date().toLocaleTimeString();
  } catch (e) {
    if (myGen !== gen) return;
    el.replaceChildren($("p", {class: "bad", text: String(e)}));
  }
  document.getElementById("view").replaceChildren(...el.childNodes);
  clearTimeout(timer);
  if (!arg) timer = setTimeout(route, 4000);  // no auto-poll on detail
}
addEventListener("hashchange", route);
route();
</script></body></html>
"""
