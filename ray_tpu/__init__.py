"""ray_tpu — a TPU-native distributed AI framework.

The capabilities of Ray (tasks, actors, objects, placement groups,
collectives, Train/Tune/Data/Serve/RLlib equivalents) re-designed TPU-first:
the control/object plane is accelerator-agnostic RPC + shared memory; the
tensor plane is XLA collectives over ICI/DCN inside jitted SPMD programs.

Public API parity map (reference file:line):
  init/shutdown        ~ python/ray/_private/worker.py:1219
  remote/get/put/wait  ~ worker.py:3153/:2583/:2695/:2760
  kill/cancel          ~ worker.py:2941/:2972
  get_actor            ~ worker.py:2906
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Union

from ray_tpu import exceptions
from ray_tpu._private.config import GlobalConfig
from ray_tpu._private.ids import JobID
from ray_tpu._private.object_ref import ObjectRef, ObjectRefGenerator
from ray_tpu._private.worker import (
    MODE_DRIVER, Worker, global_worker, global_worker_or_none,
    set_global_worker,
)
from ray_tpu.actor import ActorClass, ActorHandle, method
from ray_tpu.remote_function import RemoteFunction
from ray_tpu.runtime_context import get_runtime_context

__version__ = "0.1.0"

_init_lock = threading.Lock()
_local_node = None


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[float] = None,
    num_tpus: Optional[float] = None,
    resources: Optional[Dict[str, float]] = None,
    labels: Optional[Dict[str, str]] = None,
    object_store_memory: Optional[int] = None,
    namespace: Optional[str] = None,
    ignore_reinit_error: bool = False,
    log_to_driver: bool = True,
    include_dashboard: bool = False,
    _system_config: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Start (or connect to) a cluster and attach this process as the driver."""
    global _local_node
    with _init_lock:
        if global_worker_or_none() is not None:
            if ignore_reinit_error:
                return {"already_initialized": True}
            raise RuntimeError("ray_tpu.init() called twice; pass "
                               "ignore_reinit_error=True to ignore")
        from ray_tpu._private.node import Node
        from ray_tpu._private.rpc import RpcClient

        if address is not None and address.startswith("ray_tpu://"):
            # Thin-client mode (reference "ray://"): every API call
            # proxies to a server-side driver; no local daemons.
            from ray_tpu.client.worker import ClientWorker

            host, port = address[len("ray_tpu://"):].rsplit(":", 1)
            client_worker = ClientWorker(host, int(port))
            set_global_worker(client_worker)
            return {"client": True, "address": address}

        if address is None:
            node = Node(head=True, num_cpus=num_cpus, num_tpus=num_tpus,
                        resources=resources, labels=labels,
                        object_store_memory=object_store_memory,
                        system_config=_system_config,
                        include_dashboard=include_dashboard)
            _local_node = node
            gcs_addr = node.gcs_addr
            raylet_addr = node.raylet_addr
            node_id = node.node_id.binary()
            session_dir = node.session_dir
        else:
            host, port = address.rsplit(":", 1)
            gcs_addr = (host, int(port))
            probe = RpcClient(*gcs_addr)
            GlobalConfig.load_system_config(probe.call("get_system_config",
                                                       timeout=10))
            nodes = [n for n in probe.call("get_all_nodes", timeout=10)
                     if n["state"] == "ALIVE"]
            probe.close()
            if not nodes:
                raise ConnectionError(f"no alive nodes at {address}")
            raylet_addr = tuple(nodes[0]["addr"])
            node_id = nodes[0]["node_id"]
            session_dir = ""

        gcs = RpcClient(*gcs_addr)
        job_int = gcs.call("next_job_id", timeout=10)
        job_id = JobID.from_int(job_int)
        worker = Worker(mode=MODE_DRIVER, gcs_addr=gcs_addr,
                        raylet_addr=raylet_addr, node_id=node_id,
                        job_id=job_id, session_dir=session_dir)
        worker.namespace = namespace or f"job-{job_id.hex()}"
        set_global_worker(worker)
        import sys as _sys

        gcs.call("register_job", job_id=job_id.binary(),
                 driver_addr=worker.addr,
                 metadata={"namespace": worker.namespace,
                           # Workers mirror the driver's import environment
                           # (same-filesystem equivalent of the reference's
                           # working_dir runtime env).
                           "sys_path": list(_sys.path),
                           "cwd": os.getcwd()})
        gcs.close()
        if log_to_driver:
            _start_log_listener(gcs_addr, job_id.hex())
        return {"gcs_address": f"{gcs_addr[0]}:{gcs_addr[1]}",
                "node_id": node_id.hex(), "job_id": job_id.hex(),
                "session_dir": session_dir,
                "dashboard_url": getattr(_local_node, "dashboard_url", None)
                if _local_node is not None else None}


_log_listener_stop = None


def _start_log_listener(gcs_addr, job_id_hex: Optional[str] = None) -> None:
    """Subscribe to the "logs" pubsub channel and echo worker output
    (reference: the driver-side subscriber fed by `log_monitor.py`)."""
    global _log_listener_stop
    import sys
    import threading

    from ray_tpu._private.log_monitor import echo_to_driver
    from ray_tpu._private.rpc import RpcClient

    stop = threading.Event()
    _log_listener_stop = stop

    my_job = job_id_hex

    def run():
        client = None
        cursor = None
        while not stop.is_set():
            try:
                if client is None:
                    client = RpcClient(*gcs_addr)
                if cursor is None:
                    cursor = client.call("pubsub_seq", timeout=10)
                msgs = client.call("poll", channel="logs", cursor=cursor,
                                   wait_timeout=2.0, timeout=30)
                for seq, msg in msgs:
                    cursor = max(cursor, seq)
                    # Only this driver's job (other drivers echo their own).
                    if msg.get("job_id") not in (None, my_job):
                        continue
                    echo_to_driver(msg, msg.get("ip", "?"),
                                   sys.stderr.write)
            except Exception:
                # Transient GCS hiccup: drop the connection, retry. The
                # cursor survives so no lines are replayed.
                if client is not None:
                    try:
                        client.close()
                    except Exception:
                        pass
                    client = None
                stop.wait(1.0)
        if client is not None:
            try:
                client.close()
            except Exception:
                pass

    threading.Thread(target=run, daemon=True,
                     name="ray_tpu_log_listener").start()


def shutdown() -> None:
    global _local_node
    with _init_lock:
        if _log_listener_stop is not None:
            _log_listener_stop.set()
        w = global_worker_or_none()
        from ray_tpu.client.worker import ClientWorker

        if isinstance(w, ClientWorker):
            # Thin client: disconnect only — the cluster lives on.
            w.shutdown()
            set_global_worker(None)
            return
        if w is not None:
            try:
                w.gcs.call("mark_job_finished", job_id=w.job_id.binary(),
                           timeout=5)
            except Exception:
                pass
            if _local_node is not None:
                try:  # local usage report (reference: usage_stats ping)
                    from ray_tpu._private import usage_stats

                    # Opt-out guards the RPCs too, not just the write.
                    if usage_stats.usage_stats_enabled():
                        caps = w.gcs.call("cluster_resources", timeout=5)
                        n_nodes = len(
                            w.gcs.call("get_all_nodes", timeout=5) or [])
                        usage_stats.write_report(
                            _local_node.session_dir, {
                                "session_id": os.path.basename(
                                    _local_node.session_dir),
                                "num_nodes": n_nodes,
                                "num_cpus": caps.get("CPU"),
                                "num_tpus": caps.get("TPU"),
                            })
                except Exception:
                    pass
            try:
                w.shutdown()
            finally:
                # Even a failed teardown must drop the global worker, or
                # the next init(ignore_reinit_error=True) silently reuses
                # a half-dead cluster (observed as cross-module test
                # leakage: later suites inherited a stale session).
                # NOTE: uses the module-level import — a local import here
                # would shadow `set_global_worker` for the WHOLE function
                # and break the thin-client branch above with
                # UnboundLocalError.
                set_global_worker(None)
        if _local_node is not None:
            try:
                _local_node.shutdown()
            finally:
                _local_node = None


def is_initialized() -> bool:
    return global_worker_or_none() is not None


def remote(*args, **options) -> Union[RemoteFunction, ActorClass]:
    """``@ray_tpu.remote`` / ``@ray_tpu.remote(num_tpus=1, ...)``."""
    if len(args) == 1 and not options and callable(args[0]):
        target = args[0]
        if isinstance(target, type):
            return ActorClass(target)
        return RemoteFunction(target)
    if args:
        raise TypeError("@remote takes only keyword options")

    def decorator(target):
        if isinstance(target, type):
            return ActorClass(target, options)
        return RemoteFunction(target, options)

    return decorator


def put(value: Any) -> ObjectRef:
    if isinstance(value, ObjectRef):
        raise TypeError("put() of an ObjectRef is not allowed")
    return global_worker().put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]],
        *, timeout: Optional[float] = None) -> Any:
    single = isinstance(refs, ObjectRef)
    batch = [refs] if single else list(refs)
    for r in batch:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() expects ObjectRefs, got {type(r)}")
    values = global_worker().get_objects(batch, timeout)
    return values[0] if single else values


def wait(refs: Sequence[ObjectRef], *, num_returns: int = 1,
         timeout: Optional[float] = None, fetch_local: bool = True):
    refs = list(refs)
    if len(set(refs)) != len(refs):
        raise ValueError("wait() got duplicate ObjectRefs")
    if num_returns > len(refs):
        raise ValueError("num_returns exceeds the number of refs")
    return global_worker().wait(refs, num_returns, timeout, fetch_local)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    global_worker().kill_actor(actor._actor_id, no_restart)


def cancel(ref: Union[ObjectRef, ObjectRefGenerator], *,
           force: bool = False) -> None:
    if isinstance(ref, ObjectRefGenerator):
        ref = ref._ref0
    global_worker().cancel_task(ref, force)


def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    return global_worker().get_actor(name, namespace)


# -- cluster state ----------------------------------------------------------

def nodes() -> List[Dict[str, Any]]:
    out = []
    for n in global_worker().gcs.call("get_all_nodes", timeout=10):
        out.append({
            "NodeID": n["node_id"].hex(), "Alive": n["state"] == "ALIVE",
            "Resources": n["total"], "Available": n["available"],
            "Labels": n["labels"], "RayletAddr": n["addr"],
        })
    return out


def cluster_resources() -> Dict[str, float]:
    return global_worker().gcs.call("cluster_resources", timeout=10)


def available_resources() -> Dict[str, float]:
    return global_worker().gcs.call("available_resources", timeout=10)


def task_events(limit: Optional[int] = None) -> List[Dict]:
    """Raw task lifecycle events from the head's ring buffer (all of it by
    default — the server-side default limit of 1000 would silently drop
    older tasks from timelines)."""
    from ray_tpu._private.config import GlobalConfig
    return global_worker().gcs.call(
        "get_task_events", timeout=30,
        limit=limit or GlobalConfig.task_events_buffer_size)


def timeline(filename: Optional[str] = None) -> List[Dict]:
    """Chrome-trace-format task timeline (reference: `ray timeline`,
    `scripts/scripts.py:1875` dumping chrome://tracing JSON from GCS task
    events). Returns the trace events; also writes JSON to `filename` if
    given. The same rendering backs the dashboard's `GET /api/timeline`
    (`observability/timeline.py`)."""
    from ray_tpu.observability.timeline import build_chrome_trace

    trace = build_chrome_trace(task_events())
    if filename:
        import json as _json
        with open(filename, "w") as f:
            _json.dump(trace, f)
    return trace


__all__ = [
    "init", "shutdown", "is_initialized", "remote", "put", "get", "wait",
    "kill", "cancel", "get_actor", "method", "nodes", "cluster_resources",
    "available_resources", "get_runtime_context", "ObjectRef", "ActorHandle",
    "exceptions", "timeline", "task_events", "__version__",
]
