"""Mutable shared-memory channels — the compiled-DAG substrate.

Reference: `src/ray/core_worker/experimental_mutable_object_manager.h` +
`python/ray/experimental/channel/` — reusable zero-copy slots that a
static DAG writes/reads repeatedly, bypassing the per-call task path
(lease, RPC, object store) entirely.

Design: one single-writer/single-reader slot in POSIX shared memory
(`/dev/shm`). Header = three aligned u64 counters + a closed flag:

    write_seq  — bumped by the writer AFTER the payload is in place
    ack_seq    — bumped by the reader AFTER it consumed the payload
    length     — payload byte length

Backpressure is the protocol: the writer blocks until `ack_seq ==
write_seq` (previous value consumed), the reader blocks until
`write_seq > ack_seq`. Each counter has exactly one writing side, so
torn updates can't happen (aligned 8-byte stores), and the payload is
never rewritten while the reader may touch it. Polling backs off
50µs → 1ms: one write+read round-trip is ~100µs vs ~1ms+ for a task
RPC. Same-host only (like the reference's mutable objects, which ride
node-local shm / NVLink).
"""

from __future__ import annotations

import pickle
import struct
import time
import uuid
from multiprocessing import resource_tracker, shared_memory
from typing import Any, Optional

_U64 = struct.Struct("<Q")
_OFF_WRITE = 0
_OFF_ACK = 8
_OFF_LEN = 16
_OFF_CLOSED = 24
_HEADER_SIZE = 32

DEFAULT_BUFFER_SIZE = 8 * 1024 * 1024


class ChannelClosedError(Exception):
    """The peer tore the channel down."""


class ChannelFullError(Exception):
    """Serialized value exceeds the channel's fixed buffer."""


def _untrack(shm: shared_memory.SharedMemory) -> None:
    # Attachers must not let the resource tracker unlink the segment when
    # *their* process exits — the creator owns the lifetime.
    try:
        resource_tracker.unregister(shm._name, "shared_memory")  # noqa
    except Exception:
        pass


class Channel:
    """One SPSC mutable slot. `create=True` allocates (owner side);
    readers/writers in other processes attach by name."""

    def __init__(self, name: Optional[str] = None, *,
                 buffer_size: int = DEFAULT_BUFFER_SIZE,
                 create: bool = False):
        if create:
            name = name or f"rtch-{uuid.uuid4().hex[:16]}"
            self._shm = shared_memory.SharedMemory(
                name=name, create=True, size=_HEADER_SIZE + buffer_size)
            self._shm.buf[:_HEADER_SIZE] = b"\0" * _HEADER_SIZE
        else:
            if name is None:
                raise ValueError("attaching requires a channel name")
            self._shm = shared_memory.SharedMemory(name=name)
            _untrack(self._shm)
        self.name = name
        self._owner = create
        self._capacity = self._shm.size - _HEADER_SIZE

    # ------------------------------------------------------------ counters
    def _get(self, off: int) -> int:
        return _U64.unpack_from(self._shm.buf, off)[0]

    def _set(self, off: int, val: int) -> None:
        _U64.pack_into(self._shm.buf, off, val)

    @property
    def closed(self) -> bool:
        return self._shm.buf[_OFF_CLOSED] != 0

    # ------------------------------------------------------------------ io
    @staticmethod
    def serialize(value: Any) -> bytes:
        """Pre-serialize once when the same value fans out to several
        channels (pair with write_serialized)."""
        return pickle.dumps(value, protocol=5)

    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        self.write_serialized(self.serialize(value), timeout)

    def write_serialized(self, payload: bytes,
                         timeout: Optional[float] = None) -> None:
        if len(payload) > self._capacity:
            raise ChannelFullError(
                f"serialized value is {len(payload)} bytes; channel buffer "
                f"is {self._capacity} (pass a larger buffer_size at "
                f"compile time)")
        self._wait(lambda: self._get(_OFF_ACK) == self._get(_OFF_WRITE),
                   timeout, "write")
        self._shm.buf[_HEADER_SIZE:_HEADER_SIZE + len(payload)] = payload
        self._set(_OFF_LEN, len(payload))
        self._set(_OFF_WRITE, self._get(_OFF_WRITE) + 1)

    def read(self, timeout: Optional[float] = None) -> Any:
        self._wait(lambda: self._get(_OFF_WRITE) > self._get(_OFF_ACK),
                   timeout, "read")
        n = self._get(_OFF_LEN)
        value = pickle.loads(bytes(self._shm.buf[_HEADER_SIZE:
                                                 _HEADER_SIZE + n]))
        self._set(_OFF_ACK, self._get(_OFF_ACK) + 1)
        return value

    def _wait(self, ready, timeout: Optional[float], op: str) -> None:
        start = time.monotonic()
        deadline = None if timeout is None else start + timeout
        while not ready():
            if self.closed:
                raise ChannelClosedError(
                    f"channel {self.name} closed during {op}")
            now = time.monotonic()
            if deadline is not None and now > deadline:
                raise TimeoutError(f"channel {self.name} {op} timed out")
            # Hot path: spin ~200µs (a pipelined peer answers within that),
            # then 50µs naps to 20ms, then 1ms naps — so a hop costs ~µs
            # when the DAG is being driven and ~1ms wake-up when idle.
            waited = now - start
            if waited < 200e-6:
                continue
            time.sleep(50e-6 if waited < 20e-3 else 1e-3)

    # ------------------------------------------------------------ lifecycle
    def close(self) -> None:
        """Mark closed (wakes both sides), keep the mapping."""
        try:
            self._shm.buf[_OFF_CLOSED] = 1
        except (ValueError, TypeError):
            pass

    def release(self) -> None:
        """Detach; the owner also unlinks the segment."""
        self.close()
        try:
            self._shm.close()
        except Exception:
            pass
        if self._owner:
            try:
                self._shm.unlink()
            except Exception:
                pass

    def __reduce__(self):
        # Handing a channel to another process pickles the *name*; the
        # receiver attaches to the same shm segment.
        return (_attach, (self.name,))


def _attach(name: str) -> "Channel":
    return Channel(name)
