from ray_tpu.experimental.channel import Channel, ChannelClosedError

__all__ = ["Channel", "ChannelClosedError"]
