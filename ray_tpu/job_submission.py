"""Job submission — run driver scripts on a cluster under supervision.

Reference: `dashboard/modules/job/job_manager.py` (JobManager spawns a
JobSupervisor actor per job; the supervisor runs the entrypoint as a
subprocess, captures logs, and records terminal status) + `job/sdk.py`
(JobSubmissionClient).
"""

from __future__ import annotations

import json
import os
import subprocess
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu

_KV_NS = "job_submissions"


@ray_tpu.remote(num_cpus=0.5, max_concurrency=2)
class JobSupervisor:
    """Runs one job's entrypoint as a child process and reports status.

    ``max_concurrency=2`` so ``stop()`` can be delivered while ``run()``
    is blocked in ``proc.wait()`` — the stop must execute on the node
    that owns the child process (a client-side ``os.kill`` only works
    when client and supervisor share a machine; ADVICE r4 medium).
    """

    def __init__(self):
        import threading

        self._proc: Optional[subprocess.Popen] = None
        # Closes the stop-before-spawn race: stop() sets _stopped under
        # the lock; run() checks it under the same lock around Popen, so
        # an early stop() can never let the child spawn afterwards.
        self._stopped = False
        self._lock = threading.Lock()

    def stop(self, grace_s: float = 3.0) -> bool:
        """Terminate this job's entrypoint process group: SIGTERM, a
        grace window, then SIGKILL. Runs where the child lives, so it is
        correct on multi-node clusters and for off-cluster HTTP clients.
        Returns True iff the job can no longer run (process killed, or
        spawn permanently suppressed)."""
        import signal

        with self._lock:
            self._stopped = True
            proc = self._proc
        if proc is None:
            return True  # run() will see _stopped and never spawn
        if proc.poll() is not None:
            return True  # already exited
        try:
            os.killpg(proc.pid, signal.SIGTERM)
        except (OSError, ProcessLookupError):
            try:
                proc.terminate()
            except Exception:
                pass
        deadline = time.monotonic() + grace_s
        while time.monotonic() < deadline and proc.poll() is None:
            time.sleep(0.1)
        if proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (OSError, ProcessLookupError):
                try:
                    proc.kill()
                except Exception:
                    pass
        return True

    def run(self, submission_id: str, entrypoint: str, gcs_addr: str,
            env: Dict[str, str], working_dir: Optional[str]) -> int:
        from ray_tpu._private.worker import global_worker

        w = global_worker()

        def put_status(**fields):
            record = json.loads(
                w.gcs.call("kv_get", namespace=_KV_NS,
                           key=submission_id) or b"{}")
            record.update(fields)
            w.gcs.call("kv_put", namespace=_KV_NS, key=submission_id,
                       value=json.dumps(record).encode())

        log_path = os.path.join(
            w.session_dir or "/tmp", "logs",
            f"job-{submission_id}.log")
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        child_env = dict(os.environ)
        child_env.update(env or {})
        child_env["RAY_TPU_ADDRESS"] = gcs_addr
        # The driver must import this framework no matter its cwd/script
        # location (equivalent of a pip-installed package).
        import ray_tpu as _pkg

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(_pkg.__file__)))
        child_env["PYTHONPATH"] = os.pathsep.join(
            [pkg_root] + [p for p in
                          child_env.get("PYTHONPATH", "").split(os.pathsep)
                          if p])
        with open(log_path, "wb") as log:
            with self._lock:
                if self._stopped:
                    # stop_job() beat us here: never spawn.
                    return -1
            # Spawn OUTSIDE the lock: fork+exec of a shell can take tens
            # of ms and stop() queues on the same lock — holding it here
            # stalls every concurrent stop/status call for the spawn's
            # duration. The stop-before-spawn race stays closed below: a
            # stop() landing mid-spawn either sees _proc once published,
            # or we see _stopped and apply its verdict to the fresh
            # child ourselves.
            # Own session/process group: stop() kills the whole tree.
            proc = subprocess.Popen(
                entrypoint, shell=True, stdout=log,
                stderr=subprocess.STDOUT, env=child_env,
                cwd=working_dir or None, start_new_session=True)
            with self._lock:
                self._proc = proc
                stopped_now = self._stopped
            if stopped_now:
                # stop() raced the spawn before _proc was visible: kill
                # the process group it could not see.
                import signal

                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except (OSError, ProcessLookupError):
                    try:
                        proc.kill()
                    except Exception:
                        pass
                proc.wait()  # graftlint: disable=deadlock-unbounded-wait
                return -1
            put_status(status="RUNNING", log_path=log_path,
                       start_time=time.time(), pid=os.getpid(),
                       child_pid=proc.pid)  # same-node stop fallback
            # Unbounded by design: a job's entrypoint runs for as long
            # as the user's workload does; stop_job() is the bound.
            rc = proc.wait()  # graftlint: disable=deadlock-unbounded-wait
        record = json.loads(
            w.gcs.call("kv_get", namespace=_KV_NS,
                       key=submission_id) or b"{}")
        if record.get("status") == "STOPPED" or self._stopped:
            return rc  # stop_job already wrote the terminal state
        put_status(status="SUCCEEDED" if rc == 0 else "FAILED",
                   returncode=rc, end_time=time.time())
        return rc


class JobSubmissionClient:
    """Submit/inspect jobs.

    Two transports (reference: `job/sdk.py`): with no address, talks to
    the initialized in-process cluster connection; with an ``http://``
    address, talks to the dashboard head's job REST API — the off-cluster
    path (`dashboard/modules/job/job_head.py`).
    """

    def __init__(self, address: Optional[str] = None):
        self._http = None
        if address and address.startswith("http"):
            self._http = address.rstrip("/")
            return
        from ray_tpu._private.worker import global_worker

        self._worker = global_worker()

    # ---- HTTP transport ---------------------------------------------------
    def _http_json(self, method: str, path: str, body=None):
        import urllib.request

        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self._http + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read())

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   env: Optional[Dict[str, str]] = None,
                   working_dir: Optional[str] = None) -> str:
        if self._http:
            return self._http_json("POST", "/api/job_submissions", {
                "entrypoint": entrypoint, "submission_id": submission_id,
                "env": env, "working_dir": working_dir,
            })["submission_id"]
        submission_id = submission_id or f"job_{uuid.uuid4().hex[:10]}"
        gcs_addr = "%s:%d" % self._worker.gcs_addr
        self._worker.gcs.call(
            "kv_put", namespace=_KV_NS, key=submission_id,
            value=json.dumps({
                "submission_id": submission_id,
                "entrypoint": entrypoint,
                "status": "PENDING",
                "submit_time": time.time(),
            }).encode())
        supervisor = JobSupervisor.options(
            name=f"_job_supervisor:{submission_id}",
            lifetime="detached").remote()
        # Fire and track: the ref resolves when the job process exits.
        self._refs = getattr(self, "_refs", {})
        self._refs[submission_id] = supervisor.run.remote(
            submission_id, entrypoint, gcs_addr, env or {}, working_dir)
        return submission_id

    def get_job_status(self, submission_id: str) -> str:
        return self.get_job_info(submission_id).get("status", "UNKNOWN")

    def get_job_info(self, submission_id: str) -> Dict[str, Any]:
        if self._http:
            return self._http_json(
                "GET", f"/api/job_submissions/{submission_id}")
        return self._record(submission_id)

    def get_job_logs(self, submission_id: str) -> str:
        if self._http:
            return self._http_json(
                "GET", f"/api/job_submissions/{submission_id}/logs")["logs"]
        path = self._record(submission_id).get("log_path")
        if not path or not os.path.exists(path):
            return ""
        with open(path, "r", errors="replace") as f:
            return f.read()

    def stop_job(self, submission_id: str) -> bool:
        """Kill the job's entrypoint process and mark it STOPPED."""
        if self._http:
            return self._http_json(
                "POST", f"/api/job_submissions/{submission_id}/stop"
            ).get("stopped", False)
        record = self._record(submission_id)
        if record.get("status") in ("SUCCEEDED", "FAILED", "STOPPED"):
            return False
        # Terminal state FIRST: the supervisor checks for STOPPED before
        # writing its own terminal status, so writing before the kill
        # closes the race where its FAILED overwrites our STOPPED.
        record.update(status="STOPPED", end_time=time.time())
        self._worker.gcs.call(
            "kv_put", namespace=_KV_NS, key=submission_id,
            value=json.dumps(record).encode())
        # Route the kill through the supervisor: it owns the child and
        # runs on the child's node, so this is correct on multi-node
        # clusters (a client-side os.kill only ever worked same-node).
        stopped_via_supervisor = False
        sup = None
        try:
            sup = ray_tpu.get_actor(f"_job_supervisor:{submission_id}")
            stopped_via_supervisor = bool(
                ray_tpu.get(sup.stop.remote(), timeout=30))
        except Exception:
            pass
        if not stopped_via_supervisor:
            # Same-node fallback when the supervisor is unreachable.
            pid = record.get("child_pid")
            if pid:
                try:
                    os.kill(pid, 15)
                except OSError:
                    pass
        if sup is not None:
            try:
                ray_tpu.kill(sup)
            except Exception:
                pass
        return True

    def list_jobs(self) -> List[Dict[str, Any]]:
        if self._http:
            return self._http_json("GET", "/api/job_submissions")
        keys = self._worker.gcs.call("kv_keys", namespace=_KV_NS)
        return [self._record(k if isinstance(k, str) else k.decode())
                for k in keys]

    def wait_until_finished(self, submission_id: str,
                            timeout: float = 600.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(submission_id)
            if status in ("SUCCEEDED", "FAILED", "STOPPED"):
                return status
            time.sleep(0.5)
        raise TimeoutError(
            f"job {submission_id} still {status} after {timeout}s")

    def _record(self, submission_id: str) -> Dict[str, Any]:
        raw = self._worker.gcs.call("kv_get", namespace=_KV_NS,
                                    key=submission_id)
        if raw is None:
            raise KeyError(f"no such job: {submission_id}")
        return json.loads(raw)
