"""Job submission — run driver scripts on a cluster under supervision.

Reference: `dashboard/modules/job/job_manager.py` (JobManager spawns a
JobSupervisor actor per job; the supervisor runs the entrypoint as a
subprocess, captures logs, and records terminal status) + `job/sdk.py`
(JobSubmissionClient).
"""

from __future__ import annotations

import json
import os
import subprocess
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu

_KV_NS = "job_submissions"


@ray_tpu.remote(num_cpus=0.5)
class JobSupervisor:
    """Runs one job's entrypoint as a child process and reports status."""

    def run(self, submission_id: str, entrypoint: str, gcs_addr: str,
            env: Dict[str, str], working_dir: Optional[str]) -> int:
        from ray_tpu._private.worker import global_worker

        w = global_worker()

        def put_status(**fields):
            record = json.loads(
                w.gcs.call("kv_get", namespace=_KV_NS,
                           key=submission_id) or b"{}")
            record.update(fields)
            w.gcs.call("kv_put", namespace=_KV_NS, key=submission_id,
                       value=json.dumps(record).encode())

        log_path = os.path.join(
            w.session_dir or "/tmp", "logs",
            f"job-{submission_id}.log")
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        child_env = dict(os.environ)
        child_env.update(env or {})
        child_env["RAY_TPU_ADDRESS"] = gcs_addr
        # The driver must import this framework no matter its cwd/script
        # location (equivalent of a pip-installed package).
        import ray_tpu as _pkg

        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(_pkg.__file__)))
        child_env["PYTHONPATH"] = os.pathsep.join(
            [pkg_root] + [p for p in
                          child_env.get("PYTHONPATH", "").split(os.pathsep)
                          if p])
        put_status(status="RUNNING", log_path=log_path,
                   start_time=time.time(), pid=os.getpid())
        with open(log_path, "wb") as log:
            proc = subprocess.Popen(
                entrypoint, shell=True, stdout=log,
                stderr=subprocess.STDOUT, env=child_env,
                cwd=working_dir or None)
            put_status(child_pid=proc.pid)  # stop_job kills this
            rc = proc.wait()
        record = json.loads(
            w.gcs.call("kv_get", namespace=_KV_NS,
                       key=submission_id) or b"{}")
        if record.get("status") == "STOPPED":
            return rc  # stop_job already wrote the terminal state
        put_status(status="SUCCEEDED" if rc == 0 else "FAILED",
                   returncode=rc, end_time=time.time())
        return rc


class JobSubmissionClient:
    """Submit/inspect jobs.

    Two transports (reference: `job/sdk.py`): with no address, talks to
    the initialized in-process cluster connection; with an ``http://``
    address, talks to the dashboard head's job REST API — the off-cluster
    path (`dashboard/modules/job/job_head.py`).
    """

    def __init__(self, address: Optional[str] = None):
        self._http = None
        if address and address.startswith("http"):
            self._http = address.rstrip("/")
            return
        from ray_tpu._private.worker import global_worker

        self._worker = global_worker()

    # ---- HTTP transport ---------------------------------------------------
    def _http_json(self, method: str, path: str, body=None):
        import urllib.request

        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self._http + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read())

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   env: Optional[Dict[str, str]] = None,
                   working_dir: Optional[str] = None) -> str:
        if self._http:
            return self._http_json("POST", "/api/job_submissions", {
                "entrypoint": entrypoint, "submission_id": submission_id,
                "env": env, "working_dir": working_dir,
            })["submission_id"]
        submission_id = submission_id or f"job_{uuid.uuid4().hex[:10]}"
        gcs_addr = "%s:%d" % self._worker.gcs_addr
        self._worker.gcs.call(
            "kv_put", namespace=_KV_NS, key=submission_id,
            value=json.dumps({
                "submission_id": submission_id,
                "entrypoint": entrypoint,
                "status": "PENDING",
                "submit_time": time.time(),
            }).encode())
        supervisor = JobSupervisor.options(
            name=f"_job_supervisor:{submission_id}",
            lifetime="detached").remote()
        # Fire and track: the ref resolves when the job process exits.
        self._refs = getattr(self, "_refs", {})
        self._refs[submission_id] = supervisor.run.remote(
            submission_id, entrypoint, gcs_addr, env or {}, working_dir)
        return submission_id

    def get_job_status(self, submission_id: str) -> str:
        return self.get_job_info(submission_id).get("status", "UNKNOWN")

    def get_job_info(self, submission_id: str) -> Dict[str, Any]:
        if self._http:
            return self._http_json(
                "GET", f"/api/job_submissions/{submission_id}")
        return self._record(submission_id)

    def get_job_logs(self, submission_id: str) -> str:
        if self._http:
            return self._http_json(
                "GET", f"/api/job_submissions/{submission_id}/logs")["logs"]
        path = self._record(submission_id).get("log_path")
        if not path or not os.path.exists(path):
            return ""
        with open(path, "r", errors="replace") as f:
            return f.read()

    def stop_job(self, submission_id: str) -> bool:
        """Kill the job's entrypoint process and mark it STOPPED."""
        if self._http:
            return self._http_json(
                "POST", f"/api/job_submissions/{submission_id}/stop"
            ).get("stopped", False)
        record = self._record(submission_id)
        if record.get("status") in ("SUCCEEDED", "FAILED", "STOPPED"):
            return False
        # Terminal state FIRST: the supervisor checks for STOPPED before
        # writing its own terminal status, so writing before the kill
        # closes the race where its FAILED overwrites our STOPPED.
        record.update(status="STOPPED", end_time=time.time())
        self._worker.gcs.call(
            "kv_put", namespace=_KV_NS, key=submission_id,
            value=json.dumps(record).encode())
        pid = record.get("child_pid")
        if pid:
            try:
                os.kill(pid, 15)
            except OSError:
                pass
        try:
            sup = ray_tpu.get_actor(f"_job_supervisor:{submission_id}")
            ray_tpu.kill(sup)
        except Exception:
            pass
        return True

    def list_jobs(self) -> List[Dict[str, Any]]:
        if self._http:
            return self._http_json("GET", "/api/job_submissions")
        keys = self._worker.gcs.call("kv_keys", namespace=_KV_NS)
        return [self._record(k if isinstance(k, str) else k.decode())
                for k in keys]

    def wait_until_finished(self, submission_id: str,
                            timeout: float = 600.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(submission_id)
            if status in ("SUCCEEDED", "FAILED", "STOPPED"):
                return status
            time.sleep(0.5)
        raise TimeoutError(
            f"job {submission_id} still {status} after {timeout}s")

    def _record(self, submission_id: str) -> Dict[str, Any]:
        raw = self._worker.gcs.call("kv_get", namespace=_KV_NS,
                                    key=submission_id)
        if raw is None:
            raise KeyError(f"no such job: {submission_id}")
        return json.loads(raw)
