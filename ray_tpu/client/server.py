"""Client proxy server — a server-side driver mirror.

Reference: `python/ray/util/client/server/server.py` — every public API
call a thin client makes is replayed here against the real cluster; the
server pins the resulting ObjectRefs/ActorHandles per client session so
cluster-side GC follows the CLIENT's lifetime, not wire round-trips.
"""

from __future__ import annotations

import argparse
import pickle
from typing import Any, Dict, List

from ray_tpu.client.common import active_server, dumps as client_dumps
from ray_tpu._private.rpc import RpcServer


class ClientServer:
    """Serves thin clients using THIS process's driver connection."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        self._server = RpcServer(host, port)
        # Session pins: object refs / actor handles the client still uses.
        self._refs: Dict[bytes, Any] = {}
        self._actors: Dict[bytes, Any] = {}
        for name in ["export_function", "submit_task", "get", "put",
                     "wait", "release", "create_actor",
                     "submit_actor_task", "get_actor", "kill_actor",
                     "release_actor", "cancel", "gcs_call", "ping",
                     "disconnect",
                     # msgpack-typed surface for non-Python frontends
                     # (the C++ client in cpp/): see cross_language.py.
                     "xlang_call", "xlang_get", "xlang_put",
                     "xlang_wait", "xlang_create_actor",
                     "xlang_actor_call", "xlang_get_actor"]:
            self._server.register(f"client_{name}",
                                  getattr(self, f"_h_{name}"))
        self._xlang_fns: Dict[str, Any] = {}
        self._xlang_actor_cls: Dict[str, Any] = {}

    def start(self) -> int:
        return self._server.start()

    @property
    def port(self) -> int:
        return self._server.port

    def stop(self) -> None:
        self._server.stop()
        self._refs.clear()
        self._actors.clear()

    # -------------------------------------------------------------- helpers
    def _pin(self, ref) -> bytes:
        self._refs[ref.binary()] = ref
        return ref.binary()

    def _ref(self, object_id: bytes):
        ref = self._refs.get(object_id)
        if ref is None:
            raise KeyError(f"unknown/released object {object_id.hex()[:12]}")
        return ref

    def _resolve_args(self, payload: bytes):
        # Markers anywhere in the graph rebuild into the real pinned
        # refs/handles while this server is "active".
        with active_server(self):
            args, kwargs = pickle.loads(payload)
        return list(args), kwargs

    def _actor_handle(self, actor_id: bytes, class_name: str = "Actor"):
        handle = self._actors.get(actor_id)
        if handle is None:
            from ray_tpu.actor import ActorHandle

            handle = ActorHandle(actor_id, class_name)
            self._actors[actor_id] = handle
        return handle

    # ------------------------------------------------------------- handlers
    @staticmethod
    async def _blocking(fn, *args):
        """Worker calls do sync RPC internally (export -> kv_put, submit
        -> lease); they must run OFF the server's io loop."""
        import asyncio

        return await asyncio.get_running_loop().run_in_executor(
            None, fn, *args)

    async def _h_ping(self):
        return True

    async def _h_export_function(self, payload):
        from ray_tpu._private.worker import global_worker

        return await self._blocking(global_worker().export_function,
                                    payload)

    async def _h_submit_task(self, fn_hash, fn_name, args_payload, options):
        from ray_tpu._private.worker import global_worker

        args, kwargs = self._resolve_args(args_payload)
        if isinstance(options.get("num_returns"), str):
            raise NotImplementedError(
                "dynamic/streaming returns are not supported in client "
                "mode yet")
        refs = await self._blocking(
            lambda: global_worker().submit_task(fn_hash, fn_name, args,
                                                kwargs, options))
        return [self._pin(r) for r in refs]

    async def _h_get(self, object_ids, wait_timeout):
        import asyncio

        from ray_tpu._private.worker import global_worker

        refs = [self._ref(oid) for oid in object_ids]
        w = global_worker()
        values = await asyncio.get_running_loop().run_in_executor(
            None, lambda: w.get_objects(refs, wait_timeout))
        # Refs nested in results are pinned before shipping so the client
        # can get() them later.
        return client_dumps(values, pin=self._pin)

    async def _h_put(self, payload):
        from ray_tpu._private.worker import global_worker

        value = pickle.loads(payload)
        ref = await self._blocking(global_worker().put, value)
        return self._pin(ref)

    # --------------------------------------------------- xlang (msgpack)
    def _xlang_remote(self, func: str):
        """Cache one RemoteFunction per cross-language symbol so repeated
        calls reuse the exported function hash."""
        rf = self._xlang_fns.get(func)
        if rf is None:
            import ray_tpu
            from ray_tpu.cross_language import resolve

            rf = ray_tpu.remote(resolve(func))
            self._xlang_fns[func] = rf
        return rf

    async def _h_xlang_call(self, func, args, options=None):
        """Submit `func` (registered name or "module:attr") with
        msgpack-typed args; returns the result ref id (bytes)."""
        from ray_tpu.cross_language import decode

        rf = self._xlang_remote(func)
        if options:
            rf = rf.options(**options)
        call_args = [decode(a) for a in (args or [])]
        ref = await self._blocking(lambda: rf.remote(*call_args))
        return self._pin(ref)

    async def _h_xlang_get(self, object_id, wait_timeout=None):
        import asyncio

        from ray_tpu._private.worker import global_worker
        from ray_tpu.cross_language import encode

        ref = self._ref(object_id)
        w = global_worker()
        (value,) = await asyncio.get_running_loop().run_in_executor(
            None, lambda: w.get_objects([ref], wait_timeout))
        return encode(value)

    async def _h_xlang_put(self, value):
        from ray_tpu._private.worker import global_worker
        from ray_tpu.cross_language import decode

        ref = await self._blocking(global_worker().put, decode(value))
        return self._pin(ref)

    async def _h_xlang_wait(self, object_ids, num_returns, wait_timeout):
        import asyncio

        import ray_tpu

        refs = [self._ref(oid) for oid in object_ids]
        ready, pending = await asyncio.get_running_loop().run_in_executor(
            None, lambda: ray_tpu.wait(refs, num_returns=num_returns,
                                       timeout=wait_timeout))
        return [[r.binary() for r in ready],
                [r.binary() for r in pending]]

    async def _h_xlang_create_actor(self, cls, args, options=None):
        """Create an actor from a cross-language symbol (a name
        registered via cross_language.register — e.g. a cpp_actor_class
        — or an importable "module:Class"); msgpack-typed args. Returns
        the actor id (bytes); kill/release ride the existing
        client_kill_actor / client_release_actor methods, whose
        payloads are already msgpack-representable."""
        import ray_tpu
        from ray_tpu.cross_language import decode, resolve

        acls = self._xlang_actor_cls.get(cls)
        if acls is None:
            acls = ray_tpu.remote(resolve(cls))
            self._xlang_actor_cls[cls] = acls
        if options:
            acls = acls.options(**options)
        call_args = [decode(a) for a in (args or [])]
        handle = await self._blocking(lambda: acls.remote(*call_args))
        self._actors[handle._actor_id] = handle
        return handle._actor_id

    async def _h_xlang_actor_call(self, actor_id, method, args):
        """Invoke a method on a pinned actor with msgpack-typed args;
        returns the result ref id (fetch via client_xlang_get)."""
        from ray_tpu.cross_language import decode

        handle = self._actors.get(actor_id)
        if handle is None:
            raise KeyError(
                f"unknown or released actor {actor_id!r}; create it via "
                f"xlang_create_actor or look it up via xlang_get_actor")
        call_args = [decode(a) for a in (args or [])]
        refs = await self._blocking(
            lambda: getattr(handle, method).remote(*call_args))
        ref = refs if not isinstance(refs, (list, tuple)) else refs[0]
        return self._pin(ref)

    async def _h_xlang_get_actor(self, name, namespace=None):
        from ray_tpu._private.worker import global_worker

        # Named actors register under "default" when no namespace is
        # given; passing None through would miss every one of them.
        handle = await self._blocking(global_worker().get_actor, name,
                                      namespace or "default")
        self._actors[handle._actor_id] = handle
        return handle._actor_id

    async def _h_wait(self, object_ids, num_returns, wait_timeout,
                      fetch_local):
        import asyncio

        from ray_tpu._private.worker import global_worker

        refs = [self._ref(oid) for oid in object_ids]
        w = global_worker()
        ready, rest = await asyncio.get_running_loop().run_in_executor(
            None,
            lambda: w.wait(refs, num_returns, wait_timeout, fetch_local))
        return ([r.binary() for r in ready], [r.binary() for r in rest])

    async def _h_release(self, object_ids):
        for oid in object_ids:
            self._refs.pop(oid, None)
        return True

    async def _h_create_actor(self, cls_payload, cls_name, args_payload,
                              options):
        from ray_tpu._private.worker import global_worker

        args, kwargs = self._resolve_args(args_payload)
        handle = await self._blocking(
            lambda: global_worker().create_actor(cls_payload, cls_name,
                                                 args, kwargs, options))
        self._actors[handle._actor_id] = handle
        return {"actor_id": handle._actor_id,
                "class_name": handle._class_name}

    async def _h_submit_actor_task(self, actor_id, method_name,
                                   args_payload, options,
                                   max_task_retries):
        from ray_tpu._private.worker import global_worker

        args, kwargs = self._resolve_args(args_payload)
        refs = await self._blocking(
            lambda: global_worker().submit_actor_task(
                actor_id, method_name, args, kwargs, options,
                max_task_retries=max_task_retries))
        return [self._pin(r) for r in refs]

    async def _h_get_actor(self, name, namespace):
        from ray_tpu._private.worker import global_worker

        handle = await self._blocking(global_worker().get_actor, name,
                                      namespace)
        self._actors[handle._actor_id] = handle
        return {"actor_id": handle._actor_id,
                "class_name": handle._class_name}

    async def _h_kill_actor(self, actor_id, no_restart):
        from ray_tpu._private.worker import global_worker

        await self._blocking(global_worker().kill_actor, actor_id,
                             no_restart)
        return True

    async def _h_release_actor(self, actor_id):
        self._actors.pop(actor_id, None)
        return True

    async def _h_cancel(self, object_id, force):
        from ray_tpu._private.worker import global_worker

        await self._blocking(global_worker().cancel_task,
                             self._ref(object_id), force)
        return True

    async def _h_disconnect(self):
        """Client session end: drop every pin so cluster-side GC can run
        (a crashed client that never calls this leaks its pins — the
        single-session proxy has no liveness tracking yet)."""
        self._refs.clear()
        self._actors.clear()
        return True

    async def _h_gcs_call(self, gcs_method, kwargs):
        from ray_tpu._private.worker import global_worker

        return await global_worker().gcs.acall(gcs_method, timeout=30,
                                               **kwargs)


def serve(port: int = 0, host: str = "0.0.0.0") -> ClientServer:
    """Start a client proxy inside the current driver; returns it."""
    server = ClientServer(host, port)
    server.start()
    return server


def main() -> None:
    import signal
    import sys

    import ray_tpu

    parser = argparse.ArgumentParser()
    parser.add_argument("--address", required=True,
                        help="GCS address host:port of the cluster to join")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=10001)
    args = parser.parse_args()

    ray_tpu.init(address=args.address, log_to_driver=False)
    server = serve(args.port, args.host)
    print(f"CLIENT_SERVER_PORT={server.port}", flush=True)
    sys.stdout.flush()
    signal.pause()


if __name__ == "__main__":
    main()
