"""ClientWorker — the thin-client stand-in for the in-process Worker.

Reference: `python/ray/util/client/worker.py` — implements the worker
surface the public API calls (`submit_task`, `get_objects`, `put`,
actors, `wait`, `kill`/`cancel`) by forwarding each to the proxy server,
so `ray_tpu.init(address="ray_tpu://host:port")` makes the ordinary API
work unchanged from outside the cluster.
"""

from __future__ import annotations

import collections
import pickle
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ray_tpu.client.common import dumps as client_dumps
from ray_tpu._private.ids import WorkerID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.rpc import RpcClient


class _ClientRefCounter:
    """Local refcounts; zero -> release the server-side pin."""

    def __init__(self, owner: "ClientWorker"):
        self._owner = owner
        self._counts: Dict[bytes, int] = {}
        self._lock = threading.Lock()

    def add_local_ref(self, object_id: bytes) -> None:
        with self._lock:
            self._counts[object_id] = self._counts.get(object_id, 0) + 1

    def remove_local_ref(self, object_id: bytes) -> None:
        if self._decref(object_id):
            self._owner._release_objects([object_id])

    def _decref(self, object_id: bytes) -> bool:
        """Drop one local ref; True iff the count reached zero (the
        caller then releases the server-side pin)."""
        with self._lock:
            n = self._counts.get(object_id, 0) - 1
            if n > 0:
                self._counts[object_id] = n
                return False
            self._counts.pop(object_id, None)
            return True

    def mark_shared(self, object_id: bytes) -> None:
        # Shared into a task argument: keep the server pin for the
        # session (conservative, mirrors the in-process counter).
        with self._lock:
            self._counts[object_id] = self._counts.get(object_id, 0) + 1


class _ClientActorGC:
    def __init__(self, owner: "ClientWorker"):
        self._owner = owner
        self._counts: Dict[bytes, int] = {}
        self._lock = threading.Lock()

    def add_ref(self, actor_id: bytes) -> None:
        with self._lock:
            self._counts[actor_id] = self._counts.get(actor_id, 0) + 1

    def remove_ref(self, actor_id: bytes) -> None:
        # GC-context entry (ActorHandle.__del__): append-only, like the
        # in-process worker — never RPC under a finalizer.
        self._owner.defer_actor_release(actor_id)

    def mark_created(self, actor_id: bytes) -> None:
        pass

    def mark_shared(self, actor_id: bytes) -> None:
        self.add_ref(actor_id)

    def _decref(self, actor_id: bytes) -> bool:
        with self._lock:
            n = self._counts.get(actor_id, 0) - 1
            if n > 0:
                self._counts[actor_id] = n
                return False
            self._counts.pop(actor_id, None)
            return True


class ClientWorker:
    """Quacks like ray_tpu._private.worker.Worker for the public API."""

    def __init__(self, host: str, port: int):
        self._client = RpcClient(host, port)
        self._client.call("client_ping", timeout=15)
        self.worker_id = WorkerID.from_random()
        self.namespace = "client"
        self.reference_counter = _ClientRefCounter(self)
        self.actor_handles = _ClientActorGC(self)
        self.gcs = _GcsProxy(self._client)
        self._closed = False
        # Deferred finalizer releases (ObjectRef/ActorHandle.__del__): a
        # __del__ must never RPC — append here, drain from the background
        # thread and at shutdown. Without this, client-mode __del__ used to
        # hit the missing-method except and leak every server-side pin for
        # the whole session (ADVICE r4 high).
        self._pending_releases: collections.deque = collections.deque()
        self._pending_actor_releases: collections.deque = collections.deque()
        self._release_wake = threading.Event()
        self._release_thread = threading.Thread(
            target=self._release_loop, name="client-release-drainer",
            daemon=True)
        self._release_thread.start()

    # ------------------------------------------------------------ marshall
    @staticmethod
    def _pack_args(args: Sequence[Any], kwargs: Dict[str, Any]) -> bytes:
        # ClientPickler reduces refs/handles anywhere in the graph.
        return client_dumps((list(args), dict(kwargs)))

    def _make_ref(self, object_id: bytes) -> ObjectRef:
        return ObjectRef(object_id, None, b"client")

    # ------------------------------------------------------------ task API
    def export_function(self, payload: bytes) -> str:
        return self._client.call("client_export_function", payload=payload,
                                 timeout=60)

    def submit_task(self, fn_hash: str, fn_name: str, args, kwargs,
                    options: Dict[str, Any]) -> List[ObjectRef]:
        if isinstance(options.get("num_returns"), str):
            raise NotImplementedError(
                "dynamic/streaming returns are not supported in client "
                "mode yet")
        ids = self._client.call(
            "client_submit_task", fn_hash=fn_hash, fn_name=fn_name,
            args_payload=self._pack_args(args, kwargs), options=options,
            timeout=120)
        return [self._make_ref(i) for i in ids]

    def put(self, value: Any) -> ObjectRef:
        oid = self._client.call("client_put",
                                payload=client_dumps(value),
                                timeout=120)
        return self._make_ref(oid)

    def get_objects(self, refs: Sequence[ObjectRef],
                    timeout: Optional[float]) -> List[Any]:
        payload = self._client.call(
            "client_get", object_ids=[r.binary() for r in refs],
            timeout=(timeout + 30) if timeout else None,
            **{"wait_timeout": timeout})
        return pickle.loads(payload)

    def wait(self, refs: Sequence[ObjectRef], num_returns: int,
             timeout: Optional[float], fetch_local: bool
             ) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        by_id = {r.binary(): r for r in refs}
        ready, rest = self._client.call(
            "client_wait", object_ids=list(by_id),
            num_returns=num_returns, fetch_local=fetch_local,
            timeout=(timeout + 30) if timeout else None,
            **{"wait_timeout": timeout})
        return [by_id[i] for i in ready], [by_id[i] for i in rest]

    # ----------------------------------------------------------- actor API
    def create_actor(self, cls_payload: bytes, cls_name: str, args, kwargs,
                     options: Dict[str, Any]):
        from ray_tpu.actor import ActorHandle

        info = self._client.call(
            "client_create_actor", cls_payload=cls_payload,
            cls_name=cls_name,
            args_payload=self._pack_args(args, kwargs), options=options,
            timeout=180)
        return ActorHandle(info["actor_id"], info["class_name"],
                           max_task_retries=options.get(
                               "max_task_retries", 0))

    def submit_actor_task(self, actor_id: bytes, method_name: str, args,
                          kwargs, options: Dict[str, Any],
                          max_task_retries: int = 0) -> List[ObjectRef]:
        ids = self._client.call(
            "client_submit_actor_task", actor_id=actor_id,
            method_name=method_name,
            args_payload=self._pack_args(args, kwargs), options=options,
            max_task_retries=max_task_retries, timeout=120)
        return [self._make_ref(i) for i in ids]

    def get_actor(self, name: str, namespace: str = "default"):
        from ray_tpu.actor import ActorHandle

        info = self._client.call("client_get_actor", name=name,
                                 namespace=namespace, timeout=60)
        return ActorHandle(info["actor_id"], info["class_name"])

    def kill_actor(self, actor_id: bytes, no_restart: bool = True) -> None:
        self._client.call("client_kill_actor", actor_id=actor_id,
                          no_restart=no_restart, timeout=60)

    def cancel_task(self, ref: ObjectRef, force: bool = False) -> None:
        self._client.call("client_cancel", object_id=ref.binary(),
                          force=force, timeout=60)

    # ------------------------------------------------------------- lifecycle
    def defer_release(self, oid: bytes) -> None:
        """GC-safe local-ref release (ObjectRef.__del__ only): lock-free
        append; the decref + server release run at the next drain."""
        self._pending_releases.append(oid)
        self._release_wake.set()

    def defer_actor_release(self, actor_id: bytes) -> None:
        self._pending_actor_releases.append(actor_id)
        self._release_wake.set()

    def drain_releases(self) -> None:
        """Apply deferred __del__ releases; batch zero-count objects into
        one server round-trip."""
        q = self._pending_releases
        dead: List[bytes] = []
        while q:
            try:
                oid = q.popleft()
            except IndexError:
                break
            try:
                if self.reference_counter._decref(oid):
                    dead.append(oid)
            except Exception:
                pass
        if dead:
            self._release_objects(dead)
        aq = self._pending_actor_releases
        while aq:
            try:
                actor_id = aq.popleft()
            except IndexError:
                break
            try:
                if self.actor_handles._decref(actor_id):
                    self._release_actor(actor_id)
            except Exception:
                pass

    def _release_loop(self) -> None:
        while not self._closed:
            self._release_wake.wait(timeout=1.0)
            self._release_wake.clear()
            if self._closed:
                return
            try:
                self.drain_releases()
            except Exception:
                pass

    def _release_objects(self, object_ids: List[bytes]) -> None:
        if self._closed:
            return
        try:
            self._client.call("client_release", object_ids=object_ids,
                              timeout=10)
        except Exception:
            pass

    def _release_actor(self, actor_id: bytes) -> None:
        if self._closed:
            return
        try:
            self._client.call("client_release_actor", actor_id=actor_id,
                              timeout=10)
        except Exception:
            pass

    def async_get(self, refs):
        import asyncio

        return asyncio.to_thread(self.get_objects, refs, None)

    def shutdown(self) -> None:
        try:
            self.drain_releases()
        except Exception:
            pass
        try:
            self._client.call("client_disconnect", timeout=10)
        except Exception:
            pass
        self._closed = True
        self._release_wake.set()
        try:
            self._client.close()
        except Exception:
            pass


class _GcsProxy:
    """`worker.gcs.call(...)` passthrough for the state/inspection APIs
    (nodes(), cluster_resources, ...)."""

    def __init__(self, client: RpcClient):
        self._client = client

    def call(self, method: str, timeout: Optional[float] = None, **kwargs):
        return self._client.call("client_gcs_call", gcs_method=method,
                                 kwargs=kwargs, timeout=timeout or 30)
