"""Shared client<->server marshalling.

Refs/handles are swapped at PICKLE time via `reducer_override`, so they
are caught anywhere in the object graph — including inside user classes —
not just in plain arg containers. Unpickling server-side (inside an
`active_server()` scope) rebuilds the real pinned objects; client-side it
rebuilds thin refs registered with the ClientWorker."""

from __future__ import annotations

import contextlib
import io
from typing import Any

import cloudpickle

_ACTIVE_SERVER = None


@contextlib.contextmanager
def active_server(server):
    """Unpickles within this scope resolve markers against `server`."""
    global _ACTIVE_SERVER
    prev, _ACTIVE_SERVER = _ACTIVE_SERVER, server
    try:
        yield
    finally:
        _ACTIVE_SERVER = prev


def _rebuild_ref(object_id: bytes):
    if _ACTIVE_SERVER is not None:
        return _ACTIVE_SERVER._ref(object_id)
    # Client side: a thin ref that registers with the ClientWorker.
    from ray_tpu._private.object_ref import ObjectRef

    return ObjectRef(object_id, None, b"client")


def _rebuild_actor(actor_id: bytes, class_name: str):
    if _ACTIVE_SERVER is not None:
        return _ACTIVE_SERVER._actor_handle(actor_id, class_name)
    from ray_tpu.actor import ActorHandle

    return ActorHandle(actor_id, class_name)


class ClientPickler(cloudpickle.CloudPickler):
    """Reduces ObjectRef/ActorHandle anywhere in the graph to wire
    rebuilders (client -> server direction). `pin` (optional) is called
    on each ref id so the server can pin results it sends back."""

    def __init__(self, file, pin=None):
        super().__init__(file, protocol=cloudpickle.DEFAULT_PROTOCOL)
        self._pin = pin

    def reducer_override(self, obj):
        from ray_tpu.actor import ActorHandle
        from ray_tpu._private.object_ref import ObjectRef

        if isinstance(obj, ObjectRef):
            if self._pin is not None:
                self._pin(obj)
            return (_rebuild_ref, (obj.binary(),))
        if isinstance(obj, ActorHandle):
            return (_rebuild_actor, (obj._actor_id, obj._class_name))
        # Chain to CloudPickler: it uses reducer_override for by-value
        # pickling of __main__/unimportable classes and functions.
        return super().reducer_override(obj)


def dumps(obj: Any, pin=None) -> bytes:
    buf = io.BytesIO()
    ClientPickler(buf, pin=pin).dump(obj)
    return buf.getvalue()
