"""ray_tpu.client — remote driver proxy ("ray://" equivalent).

Reference: `python/ray/util/client/` — a proxy server runs a driver
inside the cluster; thin clients connect over RPC and the whole public
API (tasks, actors, get/put/wait) executes server-side. The TPU shape of
this matters: a laptop client drives a TPU-pod cluster without being in
the pod's network fabric.

Usage:
    server:  ray_tpu.client.serve(port)        # inside any driver
             python -m ray_tpu.client.server --address <gcs> --port P
    client:  ray_tpu.init(address="ray_tpu://host:port")
"""

from ray_tpu.client.server import ClientServer, serve
from ray_tpu.client.worker import ClientWorker

__all__ = ["ClientServer", "ClientWorker", "serve"]
