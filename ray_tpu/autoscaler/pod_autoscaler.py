"""Gang-aware autoscaler: scales TPU pod slices (node groups) atomically.

Reference: `autoscaler/_private/autoscaler.py` +
`resource_demand_scheduler.py`, with the unit of scaling changed from a
node to a *node group* (pod slice): demand that needs a slice launches
every host of one atomically; scale-down retires a slice only when every
host has been idle past the timeout. Built from a validated cluster YAML
(`ray_tpu.autoscaler.config`), it is what `ray_tpu up` runs on the head.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ray_tpu._private.resources import ResourceSet
from ray_tpu._private.rpc import RpcClient
from ray_tpu.autoscaler.tpu_pod_provider import PodGroupProvider


class PodAutoscaler:
    """One `update()` = one reconcile pass over groups."""

    def __init__(self, gcs_addr, provider: PodGroupProvider,
                 config: Dict[str, Any]):
        self._gcs = RpcClient(*tuple(gcs_addr))
        self.provider = provider
        self.config = config
        self.node_types = config["available_node_types"]
        self.max_hosts = config.get("max_workers", 8)
        self.idle_timeout_s = config.get("idle_timeout_minutes", 5) * 60.0
        self._group_idle_since: Dict[str, float] = {}

    # ------------------------------------------------------------------ update
    def update(self) -> Dict[str, int]:
        load = self._gcs.call("get_cluster_load", timeout=30)
        launched = self._scale_up(load)
        terminated = self._scale_down(load)
        self._enforce_min_groups()
        return {"launched": launched, "terminated": terminated}

    # ---------------------------------------------------------------- scale up
    def _host0_capacity(self, spec: Dict[str, Any]) -> ResourceSet:
        res = dict(spec.get("resources", {}))
        res.update(spec.get("head_resources", {}))
        return ResourceSet(res)

    def _type_fits(self, name: str, demand: ResourceSet) -> bool:
        return self._host0_capacity(self.node_types[name]).is_superset_of(
            demand)

    def _pick_type(self, demand: ResourceSet) -> Optional[str]:
        # Prefer the smallest gang that satisfies the demand.
        fitting = [n for n in self.node_types if self._type_fits(n, demand)]
        return min(fitting, default=None,
                   key=lambda n: (self.node_types[n]["gang_size"], n))

    def _groups_of_type(self, name: str) -> List[str]:
        return [g for g in self.provider.node_groups()
                if self.provider.group_type_of(g) == name]

    def _joined(self, load, group_id: str) -> bool:
        """Every host of the group has registered with the GCS."""
        live = {n["node_id"] for n in load}
        pids = self.provider.group_nodes(group_id)
        return bool(pids) and all(
            self.provider.internal_node_id(p) in live for p in pids)

    def _scale_up(self, load) -> int:
        demands = []
        for node in load:
            for demand in node.get("pending_demands", []):
                demands.append(ResourceSet(demand))
        if not demands:
            return 0
        # Capacity still joining covers demand without a new launch.
        pending_caps = [
            self._host0_capacity(self.node_types[t])
            for t in (self.provider.group_type_of(g)
                      for g in self.provider.node_groups()
                      if not self._joined(load, g))
            if t in self.node_types
        ]
        launched = 0
        for demand in demands:
            if any(ResourceSet(n["available"]).is_superset_of(demand)
                   for n in load):
                continue
            hit = next((i for i, cap in enumerate(pending_caps)
                        if cap.is_superset_of(demand)), None)
            if hit is not None:
                pending_caps.pop(hit)
                continue
            name = self._pick_type(demand)
            if name is None:
                continue
            spec = self.node_types[name]
            if (len(self._groups_of_type(name)) >= spec["max_workers"]
                    or len(self.provider.non_terminated_nodes())
                    + spec["gang_size"] > self.max_hosts):
                continue
            self.provider.create_node_group(name, spec, spec["gang_size"])
            pending_caps.append(self._host0_capacity(spec))
            launched += 1
        return launched

    # -------------------------------------------------------------- scale down
    def _scale_down(self, load) -> int:
        by_internal = {n["node_id"]: n for n in load}
        now = time.monotonic()
        terminated = 0
        for gid in self.provider.node_groups():
            name = self.provider.group_type_of(gid)
            spec = self.node_types.get(name)
            if spec is None:
                continue
            members = [by_internal.get(self.provider.internal_node_id(p))
                       for p in self.provider.group_nodes(gid)]
            if any(m is None for m in members):
                continue  # still joining
            all_idle = all(m["available"] == m["total"]
                           and not m.get("pending_demands")
                           for m in members)
            if not all_idle:
                self._group_idle_since.pop(gid, None)
                continue
            since = self._group_idle_since.setdefault(gid, now)
            if (now - since >= self.idle_timeout_s
                    and len(self._groups_of_type(name))
                    > spec.get("min_workers", 0)):
                self.provider.terminate_node_group(gid)
                self._group_idle_since.pop(gid, None)
                terminated += 1
        return terminated

    def _enforce_min_groups(self) -> None:
        for name, spec in self.node_types.items():
            short = spec.get("min_workers", 0) - len(
                self._groups_of_type(name))
            for _ in range(max(0, short)):
                if (len(self.provider.non_terminated_nodes())
                        + spec["gang_size"] > self.max_hosts):
                    return
                self.provider.create_node_group(name, spec,
                                                spec["gang_size"])


def run_monitor_loop(gcs_addr, config: Dict[str, Any],
                     session_dir: str, interval_s: float = 5.0,
                     stop_check=None) -> None:
    """The `ray_tpu up` monitor: reconcile until stopped."""
    from ray_tpu.autoscaler.config import make_provider

    provider = make_provider(config, gcs_addr, session_dir)
    scaler = PodAutoscaler(gcs_addr, provider, config)
    try:
        while stop_check is None or not stop_check():
            try:
                scaler.update()
            except Exception:
                pass
            time.sleep(interval_s)
    finally:
        provider.shutdown()
