"""NodeProvider — the pluggable infrastructure backend of the autoscaler.

Reference: `python/ray/autoscaler/node_provider.py` (the ABC all cloud
providers implement) and the fake in-process provider used for autoscaler
e2e tests without a cloud
(`autoscaler/_private/fake_multi_node/node_provider.py`).
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional


class NodeProvider:
    """Creates/terminates worker nodes of named types."""

    def create_node(self, node_type: str,
                    node_config: Dict[str, Any]) -> str:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def node_type_of(self, provider_node_id: str) -> Optional[str]:
        raise NotImplementedError

    def internal_node_id(self, provider_node_id: str) -> Optional[bytes]:
        """The cluster NodeID once the node has joined, else None."""
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class GcsNodeTableMixin:
    """TTL-cached GCS node snapshot for cloud providers that resolve
    provider node ids to cluster NodeIDs (shared by the GCE TPU and
    KubeRay providers; one fetch serves a whole reconcile pass)."""

    _gcs_addr: Optional[tuple] = None
    _NODE_TABLE_TTL_S = 2.0

    def _node_table(self):
        if self._gcs_addr is None:
            return None
        import time

        now = time.monotonic()
        cached = getattr(self, "_node_table_cache", None)
        if cached is not None and now - cached[0] < self._NODE_TABLE_TTL_S:
            return cached[1]
        try:
            from ray_tpu._private.rpc import RpcClient

            gcs = RpcClient(*self._gcs_addr)
            try:
                nodes = gcs.call("get_all_nodes", timeout=10)
            finally:
                gcs.close()
        except Exception:
            return None
        self._node_table_cache = (now, nodes)
        return nodes


class FakeMultiNodeProvider(NodeProvider):
    """Starts real raylet processes on this machine as 'cloud nodes' —
    scale-up/down runs the true join/leave path with no cloud."""

    def __init__(self, gcs_addr, session_dir: str):
        self._gcs_addr = tuple(gcs_addr)
        self._session_dir = session_dir
        self._nodes: Dict[str, Any] = {}      # provider id -> Node
        self._types: Dict[str, str] = {}
        self._lock = threading.Lock()

    def create_node(self, node_type: str,
                    node_config: Dict[str, Any]) -> str:
        from ray_tpu._private.node import Node

        resources = dict(node_config.get("resources", {}))
        num_cpus = resources.pop("CPU", 1)
        node = Node(head=False, gcs_addr=self._gcs_addr,
                    num_cpus=num_cpus, num_tpus=resources.pop("TPU", 0),
                    resources=resources, session_dir=self._session_dir,
                    labels={"autoscaler-node-type": node_type})
        pid = f"fake-{node_type}-{uuid.uuid4().hex[:6]}"
        with self._lock:
            self._nodes[pid] = node
            self._types[pid] = node_type
        return pid

    def terminate_node(self, provider_node_id: str) -> None:
        with self._lock:
            node = self._nodes.pop(provider_node_id, None)
            self._types.pop(provider_node_id, None)
        if node is not None:
            node.shutdown(cleanup_session=False)

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return list(self._nodes)

    def node_type_of(self, provider_node_id: str) -> Optional[str]:
        return self._types.get(provider_node_id)

    def internal_node_id(self, provider_node_id: str) -> Optional[bytes]:
        node = self._nodes.get(provider_node_id)
        return node.node_id.binary() if node is not None else None

    def shutdown(self) -> None:
        for pid in list(self._nodes):
            self.terminate_node(pid)
