"""NodeProvider — the pluggable infrastructure backend of the autoscaler.

Reference: `python/ray/autoscaler/node_provider.py` (the ABC all cloud
providers implement) and the fake in-process provider used for autoscaler
e2e tests without a cloud
(`autoscaler/_private/fake_multi_node/node_provider.py`).
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional


class NodeProvider:
    """Creates/terminates worker nodes of named types."""

    def create_node(self, node_type: str,
                    node_config: Dict[str, Any]) -> str:
        raise NotImplementedError

    def terminate_node(self, provider_node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError

    def node_type_of(self, provider_node_id: str) -> Optional[str]:
        raise NotImplementedError

    def internal_node_id(self, provider_node_id: str) -> Optional[bytes]:
        """The cluster NodeID once the node has joined, else None."""
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class FakeMultiNodeProvider(NodeProvider):
    """Starts real raylet processes on this machine as 'cloud nodes' —
    scale-up/down runs the true join/leave path with no cloud."""

    def __init__(self, gcs_addr, session_dir: str):
        self._gcs_addr = tuple(gcs_addr)
        self._session_dir = session_dir
        self._nodes: Dict[str, Any] = {}      # provider id -> Node
        self._types: Dict[str, str] = {}
        self._lock = threading.Lock()

    def create_node(self, node_type: str,
                    node_config: Dict[str, Any]) -> str:
        from ray_tpu._private.node import Node

        resources = dict(node_config.get("resources", {}))
        num_cpus = resources.pop("CPU", 1)
        node = Node(head=False, gcs_addr=self._gcs_addr,
                    num_cpus=num_cpus, num_tpus=resources.pop("TPU", 0),
                    resources=resources, session_dir=self._session_dir,
                    labels={"autoscaler-node-type": node_type})
        pid = f"fake-{node_type}-{uuid.uuid4().hex[:6]}"
        with self._lock:
            self._nodes[pid] = node
            self._types[pid] = node_type
        return pid

    def terminate_node(self, provider_node_id: str) -> None:
        with self._lock:
            node = self._nodes.pop(provider_node_id, None)
            self._types.pop(provider_node_id, None)
        if node is not None:
            node.shutdown(cleanup_session=False)

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return list(self._nodes)

    def node_type_of(self, provider_node_id: str) -> Optional[str]:
        return self._types.get(provider_node_id)

    def internal_node_id(self, provider_node_id: str) -> Optional[bytes]:
        node = self._nodes.get(provider_node_id)
        return node.node_id.binary() if node is not None else None

    def shutdown(self) -> None:
        for pid in list(self._nodes):
            self.terminate_node(pid)
