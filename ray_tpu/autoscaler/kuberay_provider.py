"""KubeRay-equivalent node provider — Ray worker pods on Kubernetes.

Reference: `python/ray/autoscaler/_private/kuberay/node_provider.py:1`
(the kuberay node provider: scale requests are PATCHes to the RayCluster
custom resource's ``workerGroupSpecs[*].replicas`` +
``scaleStrategy.workersToDelete``; the kuberay operator reconciles pods
to match, and pod state is read back through the core v1 API). This is a
from-scratch redesign of the same contract:

* Declarative scaling only — the provider NEVER creates pods itself. It
  patches the RayCluster CR (optimistic-concurrency read-modify-write on
  ``metadata.resourceVersion``, retried on 409) and waits for the
  operator to materialize/delete pods, observed via label-selected pod
  listings.
* TPU pod-slice gangs map to kuberay's multi-host worker groups
  (``numOfHosts`` > 1): one replica of such a group is a GANG of pods
  sharing a ``ray.io/replica-index`` label. `create_node_group` bumps
  replicas by one and returns the new replica-index as the group id —
  the whole slice scales atomically, exactly like the GCE pod-slice
  provider's one-TPU-node-per-gang (`gcp_tpu_provider.py`).
* Ray-node identity: a joined pod is matched to its cluster NodeID by
  pod IP against the GCS node table (pods run ``ray_tpu start`` from the
  CR's pod template; no SSH bootstrap exists or is needed on k8s).

Works against any API server reachable over REST; in production inside a
pod it uses the mounted serviceaccount token. Tests inject a fake
transport (`tests/test_kuberay_provider.py`).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import GcsNodeTableMixin, NodeProvider

SA_TOKEN = "/var/run/secrets/kubernetes.io/serviceaccount/token"  # noqa: S105


def k8s_transport(api_server: str,
                  token_path: str = SA_TOKEN) -> Callable:
    """REST transport against the Kubernetes API server (urllib only —
    the kubernetes client library is deliberately not a dependency)."""
    import ssl
    import urllib.request

    def read_token() -> str:
        # Re-read per request: bound serviceaccount tokens rotate on
        # disk (~1h validity); caching the boot-time string 401s a
        # long-running autoscaler.
        if os.path.exists(token_path):
            with open(token_path) as f:
                return f.read().strip()
        return ""

    ctx = ssl.create_default_context()
    ca_path = os.path.join(os.path.dirname(token_path), "ca.crt")
    if os.path.exists(ca_path):
        # In-cluster: verify the API server against the mounted
        # serviceaccount CA — the bearer token must never travel over an
        # unverified channel.
        ctx.load_verify_locations(ca_path)
    elif os.environ.get("RAY_TPU_K8S_INSECURE") == "1":
        # Explicit opt-out only (dev clusters without a CA mount).
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE

    def transport(method: str, path: str, body: Optional[dict] = None,
                  content_type: str = "application/json"):
        import urllib.error

        req = urllib.request.Request(
            api_server.rstrip("/") + path,
            data=json.dumps(body).encode() if body is not None else None,
            method=method,
            headers={"Authorization": f"Bearer {read_token()}",
                     "Content-Type": content_type,
                     "Accept": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30,
                                        context=ctx) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            if e.code == 409:
                raise Conflict(str(e)) from e
            raise

    return transport


class KubeRayError(RuntimeError):
    pass


class Conflict(KubeRayError):
    """resourceVersion conflict (concurrent CR writer); retried."""


class KubeRayProvider(GcsNodeTableMixin, NodeProvider):
    """Drives one RayCluster CR's worker groups."""

    CRD_PATH = "/apis/ray.io/v1/namespaces/{ns}/rayclusters/{name}"
    PODS_PATH = "/api/v1/namespaces/{ns}/pods"

    def __init__(self, provider_config: Dict[str, Any], gcs_addr,
                 transport: Optional[Callable] = None,
                 ready_timeout_s: float = 300.0,
                 poll_interval_s: float = 2.0):
        self._cfg = provider_config
        self._ns = provider_config.get("namespace", "default")
        self._name = provider_config.get("cluster_name", "raycluster")
        self._gcs_addr = tuple(gcs_addr) if gcs_addr else None
        self._t = transport or k8s_transport(
            provider_config.get("api_server",
                                "https://kubernetes.default.svc"))
        self._ready_timeout = ready_timeout_s
        self._poll = poll_interval_s
        self._internal_ids: Dict[str, bytes] = {}
        self._pods_cache: Dict[str, tuple] = {}

    # ------------------------------------------------------------- CR I/O
    def _cr_path(self) -> str:
        return self.CRD_PATH.format(ns=self._ns, name=self._name)

    def _get_cr(self) -> dict:
        return self._t("GET", self._cr_path())

    def _update_cr(self, mutate: Callable[[dict], None]) -> dict:
        """Optimistic-concurrency read-modify-write, retried on 409 —
        the operator and other autoscaler replicas write the same CR."""
        for _ in range(8):
            cr = self._get_cr()
            mutate(cr)
            try:
                out = self._t("PUT", self._cr_path(), cr)
                # Any CR write changes the pod set (operator reconcile):
                # serve the next read fresh, not from the TTL cache.
                self._pods_cache.clear()
                return out
            except Conflict:
                time.sleep(0.1)
            except Exception as e:
                if "409" in str(e):
                    time.sleep(0.1)
                    continue
                raise
        raise KubeRayError("persistent RayCluster resourceVersion "
                           "conflict; giving up")

    def _group_spec(self, cr: dict, node_type: str) -> dict:
        for spec in cr.get("spec", {}).get("workerGroupSpecs", []):
            if spec.get("groupName") == node_type:
                return spec
        raise KubeRayError(
            f"RayCluster {self._name!r} has no workerGroupSpec "
            f"{node_type!r}; declare it in the CR before autoscaling it")

    # --------------------------------------------------------------- pods
    _PODS_TTL_S = 2.0

    def _pods(self, extra_selector: str = "",
              fresh: bool = False) -> List[dict]:
        """Label-selected pod listing with a short TTL cache: one
        reconcile pass calls node_type_of/internal_node_id/group_nodes
        per node — uncached that is O(N) identical LIST requests per
        pass (API-server throttling). Wait loops pass fresh=True."""
        sel = f"ray.io/cluster={self._name},ray.io/node-type=worker"
        if extra_selector:
            sel += "," + extra_selector
        now = time.monotonic()
        cached = self._pods_cache.get(sel)
        if not fresh and cached is not None \
                and now - cached[0] < self._PODS_TTL_S:
            return cached[1]
        out = self._t("GET", self.PODS_PATH.format(ns=self._ns)
                      + f"?labelSelector={sel}")
        pods = [p for p in out.get("items", [])
                if not p.get("metadata", {}).get("deletionTimestamp")
                and p.get("status", {}).get("phase") in ("Pending",
                                                         "Running")]
        self._pods_cache[sel] = (now, pods)
        return pods

    @staticmethod
    def _pod_name(pod: dict) -> str:
        return pod["metadata"]["name"]

    @staticmethod
    def _pod_group(pod: dict) -> Optional[str]:
        return pod["metadata"].get("labels", {}).get("ray.io/group")

    @staticmethod
    def _replica_index(pod: dict) -> Optional[str]:
        return pod["metadata"].get("labels", {}).get(
            "ray.io/replica-index")

    # --------------------------------------------------- gang (pod-slice)
    def create_node_group(self, node_type: str,
                          node_config: Dict[str, Any],
                          gang_size: int) -> str:
        """Scale the multi-host worker group by ONE replica (= a gang of
        ``numOfHosts`` pods) and wait for its pods to appear."""
        before = {self._pod_name(p)
                  for p in self._pods(f"ray.io/group={node_type}")}

        def bump(cr):
            spec = self._group_spec(cr, node_type)
            hosts = int(spec.get("numOfHosts", 1))
            if gang_size > 1 and hosts != gang_size:
                raise KubeRayError(
                    f"group {node_type!r} has numOfHosts={hosts}, "
                    f"cannot launch a {gang_size}-host gang")
            spec["replicas"] = int(spec.get("replicas", 0)) + 1

        self._update_cr(bump)

        deadline = time.monotonic() + self._ready_timeout
        fresh: List[dict] = []
        while time.monotonic() < deadline:
            fresh = [p for p in self._pods(f"ray.io/group={node_type}",
                                           fresh=True)
                     if self._pod_name(p) not in before]
            if len(fresh) >= gang_size and all(
                    p["status"].get("phase") == "Running" for p in fresh):
                idx = self._replica_index(fresh[0])
                if gang_size > 1 and idx is None:
                    raise KubeRayError(
                        "operator did not label the multi-host replica "
                        "(ray.io/replica-index missing)")
                return idx if idx is not None else self._pod_name(fresh[0])
            time.sleep(self._poll)

        # Roll back the replica bump AND name the stuck gang's pods in
        # workersToDelete — a bare decrement would let the operator
        # reconcile away an arbitrary (possibly healthy, in-use) replica
        # while the unschedulable one survives.
        stuck = [self._pod_name(p) for p in fresh]

        def rollback(cr):
            spec = self._group_spec(cr, node_type)
            spec["replicas"] = max(0, int(spec.get("replicas", 1)) - 1)
            if stuck:
                dele = spec.setdefault("scaleStrategy", {}).setdefault(
                    "workersToDelete", [])
                for n in stuck:
                    if n not in dele:
                        dele.append(n)

        self._update_cr(rollback)
        raise KubeRayError(
            f"gang for group {node_type!r} not Running within "
            f"{self._ready_timeout}s")

    def terminate_node_group(self, group_id: str) -> None:
        pods = [p for p in self._pods()
                if self._replica_index(p) == group_id
                or self._pod_name(p) == group_id]
        if not pods:
            return
        node_type = self._pod_group(pods[0])
        names = [self._pod_name(p) for p in pods]

        def shrink(cr):
            spec = self._group_spec(cr, node_type)
            spec["replicas"] = max(0, int(spec.get("replicas", 1)) - 1)
            strat = spec.setdefault("scaleStrategy", {})
            dele = strat.setdefault("workersToDelete", [])
            for n in names:
                if n not in dele:
                    dele.append(n)

        self._update_cr(shrink)

    def node_groups(self) -> List[str]:
        seen = []
        for p in self._pods():
            gid = self._replica_index(p) or self._pod_name(p)
            if gid not in seen:
                seen.append(gid)
        return seen

    def group_nodes(self, group_id: str) -> List[str]:
        return sorted(
            self._pod_name(p) for p in self._pods()
            if (self._replica_index(p) or self._pod_name(p)) == group_id)

    def group_type_of(self, group_id: str) -> Optional[str]:
        for p in self._pods():
            if (self._replica_index(p) or self._pod_name(p)) == group_id:
                return self._pod_group(p)
        return None

    # ---------------------------------------------- NodeProvider surface
    def create_node(self, node_type: str,
                    node_config: Dict[str, Any]) -> str:
        return self.create_node_group(node_type, node_config, 1)

    def terminate_node(self, provider_node_id: str) -> None:
        pod = next((p for p in self._pods()
                    if self._pod_name(p) == provider_node_id), None)
        if pod is None:
            return
        if self._replica_index(pod) is not None:
            # A gang member cannot be deleted alone — the slice lives
            # and dies together (same contract as the GCE provider).
            self.terminate_node_group(self._replica_index(pod))
            return
        self.terminate_node_group(provider_node_id)

    def non_terminated_nodes(self) -> List[str]:
        return sorted(self._pod_name(p) for p in self._pods())

    def node_type_of(self, provider_node_id: str) -> Optional[str]:
        for p in self._pods():
            if self._pod_name(p) == provider_node_id:
                return self._pod_group(p)
        return None

    def internal_node_id(self, provider_node_id: str) -> Optional[bytes]:
        """Pod IP <-> GCS raylet address (k8s pods have stable IPs and
        ray_tpu start binds the pod IP; no label plumbing needed)."""
        cached = self._internal_ids.get(provider_node_id)
        if cached is not None:
            return cached
        pod = next((p for p in self._pods()
                    if self._pod_name(p) == provider_node_id), None)
        ip = pod and pod.get("status", {}).get("podIP")
        if not ip:
            return None
        nodes = self._node_table()
        for n in nodes or []:
            addr = n.get("addr") or ("", 0)
            if addr[0] == ip and n.get("state") == "ALIVE":
                self._internal_ids[provider_node_id] = n["node_id"]
                return n["node_id"]
        return None

