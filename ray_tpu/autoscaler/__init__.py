from ray_tpu.autoscaler.autoscaler import StandardAutoscaler
from ray_tpu.autoscaler.node_provider import (
    FakeMultiNodeProvider, NodeProvider,
)

__all__ = ["FakeMultiNodeProvider", "NodeProvider", "StandardAutoscaler"]
