"""Reconciler — one declarative pass: observed state -> instance table
-> provider actions.

Reference: `autoscaler/v2/instance_manager/reconciler.py` (Reconciler.
reconcile: sync cloud-provider state, sync ray-node state, compute
scaling decisions, issue transitions).  Unlike v1's StandardAutoscaler
(imperative in-memory loop), every decision here is a persisted
lifecycle transition, so a crash between any two steps resumes
consistently: REQUESTED instances whose cloud node never appeared are
re-queued, ALLOCATED ones are recognized when the node joins, leaked
cloud nodes are adopted or terminated.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ray_tpu._private.resources import ResourceSet
from ray_tpu._private.rpc import RpcClient
from ray_tpu.autoscaler.v2.instance_manager import (Instance,
                                                    InstanceManager,
                                                    InstanceStatus)


class Reconciler:
    def __init__(self, gcs_addr, provider,
                 available_node_types: Dict[str, Dict[str, Any]],
                 max_workers: int = 8, idle_timeout_s: float = 60.0,
                 adopt_untracked: bool = True):
        self._gcs = RpcClient(*tuple(gcs_addr))
        self.provider = provider
        self.node_types = available_node_types
        self.max_workers = max_workers
        self.idle_timeout_s = idle_timeout_s
        self.adopt_untracked = adopt_untracked
        self.im = InstanceManager(
            kv_get=lambda k: self._gcs.call(
                "kv_get", namespace="autoscaler", key=k, timeout=30),
            kv_put=lambda k, v: self._gcs.call(
                "kv_put", namespace="autoscaler", key=k, value=v,
                timeout=30))
        self._idle_since: Dict[str, float] = {}
        self._missing_since: Dict[str, float] = {}

    # ------------------------------------------------------------ one pass
    def reconcile(self) -> Dict[str, int]:
        stats = {"launched": 0, "terminated": 0, "adopted": 0,
                 "requeued": 0}
        self._sync_cloud(stats)
        self._sync_ray()
        self._scale_up(stats)
        self._scale_down(stats)
        self._launch_queued(stats)
        return stats

    # ----------------------------------------------------- observed state
    def _sync_cloud(self, stats) -> None:
        cloud_ids = set(self.provider.non_terminated_nodes())

        for inst in list(self.im.instances.values()):
            if inst.status == InstanceStatus.REQUESTED:
                # Crash between REQUESTED and recording the cloud id: the
                # node either exists untracked (adopted below) or was
                # never created — requeue so demand is re-evaluated.
                self.im.transition(inst.instance_id,
                                   InstanceStatus.TERMINATED)
                stats["requeued"] += 1
            elif (inst.status in (InstanceStatus.ALLOCATED,
                                  InstanceStatus.RAY_RUNNING,
                                  InstanceStatus.RAY_STOPPING)
                  and inst.cloud_instance_id not in cloud_ids):
                # Cloud node vanished under us (preemption, manual kill).
                self.im.transition(inst.instance_id,
                                   InstanceStatus.TERMINATED)

        # Retry sweep: TERMINATING rows whose terminate call failed on a
        # prior pass (and RAY_STOPPING rows a crash stranded) — re-issue
        # (idempotent) or finish the transition if the node is gone.
        for inst in self.im.with_status(InstanceStatus.RAY_STOPPING):
            self.im.transition(inst.instance_id,
                               InstanceStatus.TERMINATING)
        for inst in self.im.with_status(InstanceStatus.TERMINATING):
            if inst.cloud_instance_id not in cloud_ids:
                self.im.transition(inst.instance_id,
                                   InstanceStatus.TERMINATED)
            else:
                self._terminate(inst, stats)

        tracked = {i.cloud_instance_id for i in self.im.instances.values()
                   if i.cloud_instance_id}
        for cid in cloud_ids - tracked:
            node_type = self.provider.node_type_of(cid) or "unknown"
            if self.adopt_untracked:
                inst = self.im.add(node_type)
                self.im.transition(inst.instance_id,
                                   InstanceStatus.REQUESTED)
                self.im.transition(inst.instance_id,
                                   InstanceStatus.ALLOCATED,
                                   cloud_instance_id=cid)
                stats["adopted"] += 1
            else:
                self.provider.terminate_node(cid)
                stats["terminated"] += 1

    def _sync_ray(self) -> None:
        for inst in self.im.with_status(InstanceStatus.ALLOCATED):
            internal = self.provider.internal_node_id(
                inst.cloud_instance_id)
            if internal is not None:
                self.im.transition(inst.instance_id,
                                   InstanceStatus.RAY_RUNNING,
                                   node_id=internal.hex())

    # ---------------------------------------------------------- decisions
    def _load(self):
        return self._gcs.call("get_cluster_load", timeout=30)

    def _scale_up(self, stats) -> None:
        load = self._load()
        demands = [ResourceSet(d) for n in load
                   for d in n.get("pending_demands", [])]
        if not demands:
            # min_workers floor per type
            counts: Dict[str, int] = {}
            for inst in self.im.active():
                counts[inst.node_type] = counts.get(inst.node_type, 0) + 1
            for name, cfg in self.node_types.items():
                need = cfg.get("min_workers", 0) - counts.get(name, 0)
                for _ in range(max(0, need)):
                    if len(self.im.active()) >= self.max_workers:
                        return
                    self.im.add(name)
            return

        # Pending (not yet RAY_RUNNING) instances will absorb demand.
        pending_types = [i.node_type for i in self.im.active()
                         if i.status != InstanceStatus.RAY_RUNNING]
        for demand in demands:
            if any(ResourceSet(n["available"]).is_superset_of(demand)
                   for n in load):
                continue
            covered = next((t for t in pending_types
                            if self._type_fits(t, demand)), None)
            if covered is not None:
                pending_types.remove(covered)
                continue
            node_type = next((t for t in sorted(self.node_types)
                              if self._type_fits(t, demand)), None)
            if node_type is None:
                continue
            if len(self.im.active()) >= self.max_workers:
                break
            self.im.add(node_type)
            pending_types.append(node_type)

    def _type_fits(self, node_type: str, demand: ResourceSet) -> bool:
        caps = ResourceSet(self.node_types[node_type].get("resources", {}))
        return caps.is_superset_of(demand)

    def _scale_down(self, stats) -> None:
        load = self._load()
        by_internal = {n["node_id"].hex() if isinstance(n["node_id"], bytes)
                       else n["node_id"]: n for n in load}
        now = time.monotonic()
        for inst in self.im.with_status(InstanceStatus.RAY_RUNNING):
            node = by_internal.get(inst.node_id)
            if node is None:
                # Ray process gone but the VM is up (OOM-killed worker):
                # after a grace period, reclaim the node — otherwise it
                # consumes a max_workers slot forever doing nothing.
                since = self._missing_since.setdefault(
                    inst.instance_id, now)
                if now - since >= self.idle_timeout_s:
                    self.im.transition(inst.instance_id,
                                       InstanceStatus.TERMINATING)
                    self._terminate(inst, stats)
                    self._missing_since.pop(inst.instance_id, None)
                continue
            self._missing_since.pop(inst.instance_id, None)
            fully_idle = (node["available"] == node["total"]
                          and not node.get("pending_demands"))
            if not fully_idle:
                self._idle_since.pop(inst.instance_id, None)
                continue
            since = self._idle_since.setdefault(inst.instance_id, now)
            min_of_type = self.node_types.get(inst.node_type, {}).get(
                "min_workers", 0)
            same_type = [i for i in self.im.active()
                         if i.node_type == inst.node_type]
            if (now - since >= self.idle_timeout_s
                    and len(same_type) > min_of_type):
                self.im.transition(inst.instance_id,
                                   InstanceStatus.RAY_STOPPING)
                self.im.transition(inst.instance_id,
                                   InstanceStatus.TERMINATING)
                self._terminate(inst, stats)
                self._idle_since.pop(inst.instance_id, None)

    def _terminate(self, inst: Instance, stats) -> None:
        """TERMINATING -> TERMINATED; a failed cloud call leaves the row
        TERMINATING for the retry sweep in _sync_cloud (never wedged,
        never silently leaked)."""
        try:
            self.provider.terminate_node(inst.cloud_instance_id)
        except Exception:
            return
        self.im.transition(inst.instance_id, InstanceStatus.TERMINATED)
        stats["terminated"] += 1

    # ------------------------------------------------------------ actions
    def _launch_queued(self, stats) -> None:
        for inst in self.im.with_status(InstanceStatus.QUEUED):
            self.im.transition(inst.instance_id, InstanceStatus.REQUESTED)
            try:
                cid = self.provider.create_node(
                    inst.node_type, self.node_types[inst.node_type])
            except Exception:
                self.im.transition(inst.instance_id,
                                   InstanceStatus.ALLOCATION_FAILED)
                continue
            self.im.transition(inst.instance_id, InstanceStatus.ALLOCATED,
                               cloud_instance_id=cid)
            stats["launched"] += 1
