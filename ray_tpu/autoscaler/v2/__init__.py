"""Autoscaler v2 — declarative, crash-resilient instance management.

Reference: `python/ray/autoscaler/v2/` (`instance_manager/
instance_manager.py`, `instance_manager/reconciler.py`,
`instance_storage.py`): instances are rows in a versioned table with an
explicit lifecycle state machine; one Reconciler pass diffs desired
vs. observed (cloud + ray) state and issues the transitions.  The table
persists in the GCS KV, so an autoscaler crash/restart resumes exactly
where it left off — the property v1's in-memory loop lacks.
"""

from ray_tpu.autoscaler.v2.instance_manager import (Instance,
                                                    InstanceManager,
                                                    InstanceStatus)
from ray_tpu.autoscaler.v2.reconciler import Reconciler

__all__ = ["Instance", "InstanceManager", "InstanceStatus", "Reconciler"]
