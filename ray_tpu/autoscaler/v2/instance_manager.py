"""Instance table + lifecycle state machine.

Reference: `autoscaler/v2/instance_manager/instance_manager.py` (the
versioned instance table with expected-version CAS updates) and
`common.py` InstanceStatus.  Statuses and legal transitions mirror the
reference's machine, trimmed to the states this runtime has observable
signals for:

    QUEUED -> REQUESTED -> ALLOCATED -> RAY_RUNNING
                                     -> RAY_STOPPING -> TERMINATING
    any    -> ALLOCATION_FAILED / TERMINATED

The table lives in the GCS KV under one key, written atomically with a
version counter: a crashed autoscaler process reloads the exact table
(including in-flight REQUESTED instances) on restart.
"""

from __future__ import annotations

import dataclasses
import json
import time
import uuid
from typing import Any, Dict, List, Optional

KV_KEY = "autoscaler_v2/instances"


class InstanceStatus:
    QUEUED = "QUEUED"                  # decided, not yet requested
    REQUESTED = "REQUESTED"            # create_node issued
    ALLOCATED = "ALLOCATED"            # cloud says it exists
    RAY_RUNNING = "RAY_RUNNING"        # node registered with the GCS
    RAY_STOPPING = "RAY_STOPPING"      # drain requested
    TERMINATING = "TERMINATING"        # terminate_node issued
    TERMINATED = "TERMINATED"          # gone (terminal)
    ALLOCATION_FAILED = "ALLOCATION_FAILED"  # create failed (terminal)


_LEGAL = {
    InstanceStatus.QUEUED: {InstanceStatus.REQUESTED,
                            InstanceStatus.TERMINATED},
    InstanceStatus.REQUESTED: {InstanceStatus.ALLOCATED,
                               InstanceStatus.ALLOCATION_FAILED,
                               InstanceStatus.TERMINATED},
    InstanceStatus.ALLOCATED: {InstanceStatus.RAY_RUNNING,
                               InstanceStatus.RAY_STOPPING,
                               InstanceStatus.TERMINATING,
                               InstanceStatus.TERMINATED},
    InstanceStatus.RAY_RUNNING: {InstanceStatus.RAY_STOPPING,
                                 InstanceStatus.TERMINATING,
                                 InstanceStatus.TERMINATED},
    InstanceStatus.RAY_STOPPING: {InstanceStatus.TERMINATING,
                                  InstanceStatus.TERMINATED},
    InstanceStatus.TERMINATING: {InstanceStatus.TERMINATED},
    InstanceStatus.TERMINATED: set(),
    InstanceStatus.ALLOCATION_FAILED: set(),
}


@dataclasses.dataclass
class Instance:
    instance_id: str
    node_type: str
    status: str = InstanceStatus.QUEUED
    # provider id once REQUESTED succeeds; cluster NodeID hex once joined
    cloud_instance_id: Optional[str] = None
    node_id: Optional[str] = None
    status_since: float = dataclasses.field(default_factory=time.time)
    history: List[str] = dataclasses.field(default_factory=list)

    @staticmethod
    def new(node_type: str) -> "Instance":
        return Instance(instance_id=uuid.uuid4().hex[:12],
                        node_type=node_type)

    def to_row(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @staticmethod
    def from_row(row: Dict[str, Any]) -> "Instance":
        return Instance(**row)


class InvalidTransition(Exception):
    pass


class InstanceManager:
    """The versioned instance table, persisted in the GCS KV.

    All mutations go through `transition` / `add`, which write the whole
    table back with `version+1` — a concurrent writer (e.g. a split-
    brain autoscaler) loses by version check on the next reload, which
    is the reference's expected-version CAS semantics flattened to the
    single-writer deployment this runtime uses."""

    def __init__(self, kv_get, kv_put):
        self._kv_get = kv_get
        self._kv_put = kv_put
        self.version = 0
        self.instances: Dict[str, Instance] = {}
        self._load()

    # ----------------------------------------------------------- storage
    def _load(self) -> None:
        raw = self._kv_get(KV_KEY)
        if not raw:
            return
        doc = json.loads(raw if isinstance(raw, str) else raw.decode())
        self.version = doc["version"]
        self.instances = {r["instance_id"]: Instance.from_row(r)
                          for r in doc["instances"]}

    def _flush(self) -> None:
        self.version += 1
        doc = {"version": self.version,
               "instances": [i.to_row() for i in self.instances.values()]}
        self._kv_put(KV_KEY, json.dumps(doc))

    # --------------------------------------------------------- mutations
    def add(self, node_type: str) -> Instance:
        inst = Instance.new(node_type)
        inst.history.append(f"{InstanceStatus.QUEUED}@{inst.status_since:.0f}")
        self.instances[inst.instance_id] = inst
        self._flush()
        return inst

    def transition(self, instance_id: str, new_status: str,
                   **fields) -> Instance:
        inst = self.instances[instance_id]
        if new_status not in _LEGAL[inst.status]:
            raise InvalidTransition(
                f"{inst.instance_id}: {inst.status} -> {new_status}")
        inst.status = new_status
        inst.status_since = time.time()
        inst.history.append(f"{new_status}@{inst.status_since:.0f}")
        for k, v in fields.items():
            setattr(inst, k, v)
        self._flush()
        return inst

    # ------------------------------------------------------------ views
    def with_status(self, *statuses: str) -> List[Instance]:
        return [i for i in self.instances.values() if i.status in statuses]

    def active(self) -> List[Instance]:
        """Instances that exist or will exist (count against limits)."""
        return self.with_status(
            InstanceStatus.QUEUED, InstanceStatus.REQUESTED,
            InstanceStatus.ALLOCATED, InstanceStatus.RAY_RUNNING)

    def by_cloud_id(self, cloud_instance_id: str) -> Optional[Instance]:
        for i in self.instances.values():
            if i.cloud_instance_id == cloud_instance_id:
                return i
        return None
