"""StandardAutoscaler — demand-driven scale up, idle-driven scale down.

Reference: `autoscaler/_private/autoscaler.py` (StandardAutoscaler.update
loop) + `resource_demand_scheduler.py` (bin-packs pending demand against
`available_node_types`) + the v2 rewrite's GCS-driven load source
(`gcs_autoscaler_state_manager.h`). Load comes from the GCS
`get_cluster_load` RPC: per-node availability plus lease demands queued
with nowhere to run.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ray_tpu._private.resources import ResourceSet
from ray_tpu._private.rpc import RpcClient


class StandardAutoscaler:
    """One `update()` = one reconcile pass (call it from a monitor loop)."""

    def __init__(self, gcs_addr, provider,
                 available_node_types: Dict[str, Dict[str, Any]],
                 max_workers: int = 8, idle_timeout_s: float = 60.0):
        self._gcs = RpcClient(*tuple(gcs_addr))
        self.provider = provider
        self.node_types = available_node_types
        self.max_workers = max_workers
        self.idle_timeout_s = idle_timeout_s
        self._idle_since: Dict[str, float] = {}
        self._launched_at: Dict[str, float] = {}

    # ----------------------------------------------------------------- update
    def update(self) -> Dict[str, int]:
        """Returns {"launched": n, "terminated": n} for observability."""
        load = self._gcs.call("get_cluster_load", timeout=30)
        launched = self._scale_up(load)
        terminated = self._scale_down(load)
        self._enforce_min_workers()
        return {"launched": launched, "terminated": terminated}

    def _pending_demands(self, load) -> List[ResourceSet]:
        out = []
        for node in load:
            for demand in node.get("pending_demands", []):
                out.append(ResourceSet(demand))
        return out

    def _scale_up(self, load) -> int:
        demands = self._pending_demands(load)
        if not demands:
            return 0
        # Demands a pending launch will satisfy don't need another node.
        pending_types = [self.provider.node_type_of(pid)
                         for pid in self.provider.non_terminated_nodes()
                         if self._is_pending(pid, load)]
        launched = 0
        for demand in demands:
            if self._fits_somewhere(demand, load):
                continue  # schedulable once current queues drain
            covered = False
            for t in pending_types:
                if t and self._type_fits(t, demand):
                    pending_types.remove(t)
                    covered = True
                    break
            if covered:
                continue
            node_type = self._pick_type(demand)
            if node_type is None:
                continue  # infeasible on any configured type
            if len(self.provider.non_terminated_nodes()) >= self.max_workers:
                break
            pid = self.provider.create_node(
                node_type, self.node_types[node_type])
            self._launched_at[pid] = time.monotonic()
            pending_types.append(node_type)
            launched += 1
        return launched

    def _is_pending(self, pid: str, load) -> bool:
        internal = self.provider.internal_node_id(pid)
        if internal is None:
            return True
        return not any(n["node_id"] == internal for n in load)

    def _fits_somewhere(self, demand: ResourceSet, load) -> bool:
        return any(ResourceSet(n["available"]).is_superset_of(demand)
                   for n in load)

    def _type_fits(self, node_type: str, demand: ResourceSet) -> bool:
        caps = ResourceSet(self.node_types[node_type].get("resources", {}))
        return caps.is_superset_of(demand)

    def _pick_type(self, demand: ResourceSet) -> Optional[str]:
        for name in sorted(self.node_types):
            if self._type_fits(name, demand):
                return name
        return None

    # ------------------------------------------------------------- scale down
    def _scale_down(self, load) -> int:
        by_internal = {n["node_id"]: n for n in load}
        now = time.monotonic()
        terminated = 0
        for pid in self.provider.non_terminated_nodes():
            internal = self.provider.internal_node_id(pid)
            node = by_internal.get(internal)
            if node is None:
                continue  # still joining
            # Warm pooled workers are not load — full resource availability
            # with nothing queued is idle.
            fully_idle = (node["available"] == node["total"]
                          and not node.get("pending_demands"))
            if not fully_idle:
                self._idle_since.pop(pid, None)
                continue
            since = self._idle_since.setdefault(pid, now)
            min_of_type = self.node_types.get(
                self.provider.node_type_of(pid) or "", {}).get(
                "min_workers", 0)
            same_type = [p for p in self.provider.non_terminated_nodes()
                         if self.provider.node_type_of(p)
                         == self.provider.node_type_of(pid)]
            if (now - since >= self.idle_timeout_s
                    and len(same_type) > min_of_type):
                self.provider.terminate_node(pid)
                self._idle_since.pop(pid, None)
                terminated += 1
        return terminated

    def _enforce_min_workers(self) -> None:
        counts: Dict[str, int] = {}
        for pid in self.provider.non_terminated_nodes():
            t = self.provider.node_type_of(pid)
            counts[t] = counts.get(t, 0) + 1
        for name, cfg in self.node_types.items():
            for _ in range(cfg.get("min_workers", 0) - counts.get(name, 0)):
                if (len(self.provider.non_terminated_nodes())
                        >= self.max_workers):
                    return
                pid = self.provider.create_node(name, cfg)
                self._launched_at[pid] = time.monotonic()
