"""Gang (pod-slice) node provider: all hosts of a TPU slice, or none.

Reference: `python/ray/autoscaler/node_provider.py` is per-node; TPU pod
slices break that model — a v5e-16 slice is 4 hosts that exist together
(the TPU runtime on each host only initializes when the whole slice is
up). So the provider's unit here is the *node group*: `create_node_group`
launches every host of a slice and rolls back on partial failure;
`terminate_node_group` tears the slice down as one.

`SubprocessPodProvider` implements the interface with local raylet
processes (the test/e2e backend, the analogue of
`fake_multi_node/node_provider.py`); a cloud implementation maps a group
to one TPU VM pod-slice creation call (which is atomic server-side).
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider


class PodGroupProvider(NodeProvider):
    """NodeProvider extended with atomic node-group (pod slice) ops.

    Single-node types degrade to groups of size 1, so the autoscaler can
    treat everything as groups.
    """

    def create_node_group(self, node_type: str,
                          node_config: Dict[str, Any],
                          gang_size: int) -> str:
        """Launch `gang_size` hosts atomically; returns a group id.
        Partial failures must roll back (terminate already-started hosts)
        and raise."""
        raise NotImplementedError

    def terminate_node_group(self, group_id: str) -> None:
        raise NotImplementedError

    def node_groups(self) -> List[str]:
        raise NotImplementedError

    def group_nodes(self, group_id: str) -> List[str]:
        """Provider node ids of the group's hosts."""
        raise NotImplementedError

    def group_type_of(self, group_id: str) -> Optional[str]:
        raise NotImplementedError


class SubprocessPodProvider(PodGroupProvider):
    """Pod slices as gangs of local raylet processes.

    Host 0 of each group additionally exposes the promoted
    ``TPU-{type}-head`` resource (when the node type declares one), so a
    single head-resource task gang-schedules against the slice.
    """

    def __init__(self, gcs_addr, session_dir: str):
        self._gcs_addr = tuple(gcs_addr)
        self._session_dir = session_dir
        self._lock = threading.Lock()
        self._groups: Dict[str, List[str]] = {}
        self._group_types: Dict[str, str] = {}
        self._nodes: Dict[str, Any] = {}      # provider node id -> Node
        self._node_types: Dict[str, str] = {}

    # ---- group ops --------------------------------------------------------
    def create_node_group(self, node_type: str,
                          node_config: Dict[str, Any],
                          gang_size: int) -> str:
        from ray_tpu._private.node import Node

        group_id = f"group-{node_type}-{uuid.uuid4().hex[:6]}"
        started: List[str] = []
        try:
            for host_index in range(gang_size):
                resources = dict(node_config.get("resources", {}))
                if host_index == 0:
                    resources.update(node_config.get("head_resources", {}))
                num_cpus = resources.pop("CPU", 1)
                num_tpus = resources.pop("TPU", 0)
                labels = {"autoscaler-node-type": node_type,
                          "pod-group": group_id,
                          "pod-host-index": str(host_index)}
                node = Node(head=False, gcs_addr=self._gcs_addr,
                            num_cpus=num_cpus, num_tpus=num_tpus,
                            resources=resources,
                            session_dir=self._session_dir, labels=labels)
                pid = f"{group_id}-host{host_index}"
                with self._lock:
                    self._nodes[pid] = node
                    self._node_types[pid] = node_type
                started.append(pid)
        except Exception:
            # All-or-nothing: a partially-up slice is useless (the TPU
            # runtime needs every host); roll back what started.
            for pid in started:
                self._terminate_node_internal(pid)
            raise
        with self._lock:
            self._groups[group_id] = started
            self._group_types[group_id] = node_type
        return group_id

    def terminate_node_group(self, group_id: str) -> None:
        with self._lock:
            pids = self._groups.pop(group_id, [])
            self._group_types.pop(group_id, None)
        for pid in pids:
            self._terminate_node_internal(pid)

    def node_groups(self) -> List[str]:
        with self._lock:
            return list(self._groups)

    def group_nodes(self, group_id: str) -> List[str]:
        with self._lock:
            return list(self._groups.get(group_id, []))

    def group_type_of(self, group_id: str) -> Optional[str]:
        return self._group_types.get(group_id)

    # ---- per-node view (NodeProvider interface) ---------------------------
    def create_node(self, node_type: str,
                    node_config: Dict[str, Any]) -> str:
        return self.create_node_group(node_type, node_config, 1)

    def terminate_node(self, provider_node_id: str) -> None:
        # A single host of a gang cannot be terminated alone; terminate
        # the containing group (or the id may itself be a group id).
        if provider_node_id in self._groups:
            self.terminate_node_group(provider_node_id)
            return
        with self._lock:
            owner = next((g for g, pids in self._groups.items()
                          if provider_node_id in pids), None)
        if owner is not None:
            self.terminate_node_group(owner)

    def _terminate_node_internal(self, pid: str) -> None:
        with self._lock:
            node = self._nodes.pop(pid, None)
            self._node_types.pop(pid, None)
        if node is not None:
            node.shutdown(cleanup_session=False)

    def non_terminated_nodes(self) -> List[str]:
        with self._lock:
            return list(self._nodes)

    def node_type_of(self, provider_node_id: str) -> Optional[str]:
        return self._node_types.get(provider_node_id)

    def internal_node_id(self, provider_node_id: str) -> Optional[bytes]:
        node = self._nodes.get(provider_node_id)
        return node.node_id.binary() if node is not None else None

    def shutdown(self) -> None:
        for gid in list(self._groups):
            self.terminate_node_group(gid)
        for pid in list(self._nodes):
            self._terminate_node_internal(pid)
