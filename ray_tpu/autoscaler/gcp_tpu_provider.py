"""GCE TPU-VM pod-slice provider + node bootstrap command runner.

Reference shape: `python/ray/autoscaler/_private/gcp/node_provider.py`
(+ `command_runner.py` for SSH bootstrap), re-designed around the TPU VM
API's own atomicity: one `projects.locations.nodes.create` call brings
up EVERY host of a slice (or none), so `create_node_group` maps to a
single API call instead of N instance inserts with client-side gang
logic. Rollback on partial failure = one delete.

The REST transport and the per-host command runner are injectable:
production uses urllib against ``tpu.googleapis.com`` with a metadata-
server access token and `ssh`; tests drive the full provider logic with
a fake API state machine and a capturing runner (reference:
`autoscaler/_private/fake_multi_node`), no cloud required.
"""

from __future__ import annotations

import json
import re as _re
import subprocess
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.autoscaler.tpu_pod_provider import PodGroupProvider
from ray_tpu.autoscaler.node_provider import GcsNodeTableMixin

TPU_API = "https://tpu.googleapis.com/v2"


# ------------------------------------------------------------- transports

def rest_transport(method: str, url: str,
                   body: Optional[dict] = None, *,
                   timeout: float = 60.0) -> dict:
    """GCE-authenticated REST transport: urllib + metadata-server access
    token. Shared by every Google-API surface (TPU provider, BigQuery
    source/sink) — auth/timeout fixes land once."""
    import urllib.request

    tok_req = urllib.request.Request(
        "http://metadata.google.internal/computeMetadata/v1/instance/"
        "service-accounts/default/token",
        headers={"Metadata-Flavor": "Google"})
    with urllib.request.urlopen(tok_req, timeout=5) as resp:
        token = json.loads(resp.read())["access_token"]
    req = urllib.request.Request(
        url, method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Authorization": f"Bearer {token}",
                 "Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        payload = resp.read()
    return json.loads(payload) if payload else {}


class CommandRunner:
    """Runs a bootstrap command on one host (reference:
    `command_runner.py` SSHCommandRunner)."""

    def run(self, host_ip: str, command: str) -> None:
        raise NotImplementedError


class SSHCommandRunner(CommandRunner):
    def __init__(self, ssh_user: str = "ray", ssh_key: Optional[str] = None,
                 connect_timeout_s: int = 30):
        self._user = ssh_user
        self._key = ssh_key
        self._timeout = connect_timeout_s

    def run(self, host_ip: str, command: str) -> None:
        args = ["ssh", "-o", "StrictHostKeyChecking=no",
                "-o", f"ConnectTimeout={self._timeout}"]
        if self._key:
            args += ["-i", self._key]
        args += [f"{self._user}@{host_ip}", command]
        proc = subprocess.run(args, capture_output=True, text=True,
                              timeout=600)
        if proc.returncode != 0:
            raise RuntimeError(
                f"bootstrap failed on {host_ip}: {proc.stderr[-500:]}")


# --------------------------------------------------------------- provider

class GceTpuPodProvider(GcsNodeTableMixin, PodGroupProvider):
    """TPU VM pod slices as atomic node groups.

    ``provider_config``: {"project", "zone", "cluster_name",
    "runtime_version" (default v2-alpha-tpuv5-lite)}; each node type's
    ``node_config`` carries {"accelerator_type": "v5litepod-16", ...}.
    """

    def __init__(self, provider_config: Dict[str, Any], gcs_addr,
                 transport: Callable[..., dict] = rest_transport,
                 command_runner: Optional[CommandRunner] = None,
                 ready_timeout_s: float = 900.0,
                 poll_interval_s: float = 5.0):
        self._cfg = provider_config
        self._gcs_addr = tuple(gcs_addr)
        self._t = transport
        self._runner = command_runner or SSHCommandRunner(
            ssh_user=provider_config.get("ssh_user", "ray"),
            ssh_key=provider_config.get("ssh_private_key"))
        self._ready_timeout = ready_timeout_s
        self._poll = poll_interval_s
        # group id (tpu node name) -> {"type", "hosts": [ip...]}
        self._groups: Dict[str, Dict[str, Any]] = {}
        # provider node id ("<group>#<i>") -> cluster NodeID (bytes)
        self._internal_ids: Dict[str, Optional[bytes]] = {}

    # ------------------------------------------------------------- helpers
    def _parent(self) -> str:
        return (f"projects/{self._cfg['project']}/locations/"
                f"{self._cfg['zone']}")

    def _node_url(self, name: str) -> str:
        return f"{TPU_API}/{self._parent()}/nodes/{name}"

    def _bootstrap_command(self, group_id: str, worker_index: int,
                           node_config: Dict[str, Any]) -> str:
        host, port = self._gcs_addr
        resources = dict(node_config.get("resources", {}))
        if worker_index == 0:
            # Host 0 carries the promoted pod-head resource so one task
            # can gang-claim the slice (same contract as
            # SubprocessPodProvider).
            resources.update(node_config.get("head_resources", {}))
        return (f"python -m ray_tpu start --address {host}:{port} "
                f"--resources '{json.dumps(resources)}' "
                f"--labels '{{\"provider_group\": \"{group_id}\", "
                f"\"worker_index\": \"{worker_index}\"}}'")

    @staticmethod
    def _accel_type(node_config: Dict[str, Any]) -> str:
        """GCE accelerator type from either a bare node_config or a full
        node-type spec (the autoscaler passes the whole spec). A
        shorthand `tpu: v5e-16` translates to the GCE catalog name."""
        nc = node_config.get("node_config", node_config)
        at = nc.get("accelerator_type") or node_config.get(
            "accelerator_type")
        if at:
            return at
        tpu = node_config.get("tpu_type") or nc.get("tpu")
        if not tpu:
            raise ValueError(
                "node_config needs 'accelerator_type' (GCE name) or "
                "'tpu' (e.g. 'v5e-16')")
        gen, _, suffix = tpu.partition("-")
        gen = {"v5e": "v5litepod", "v5p": "v5p"}.get(gen, gen)
        return f"{gen}-{suffix}" if suffix else gen

    # --------------------------------------------------------- group ops
    def create_node_group(self, node_type: str,
                          node_config: Dict[str, Any],
                          gang_size: int) -> str:
        # TPU node ids must be RFC1035 ([a-z]([-a-z0-9]*[a-z0-9])?):
        # sanitize cluster/type names (dots, underscores, caps are all
        # legal in OUR config but rejected by the API).
        raw = (f"ray-{self._cfg.get('cluster_name', 'cluster')}-"
               f"{node_type}-{uuid.uuid4().hex[:8]}")
        name = _re.sub(r"-+", "-",
                       _re.sub(r"[^a-z0-9-]", "-", raw.lower())).strip("-")
        body = {
            "acceleratorType": self._accel_type(node_config),
            "runtimeVersion": self._cfg.get(
                "runtime_version", "v2-alpha-tpuv5-lite"),
            "networkConfig": {"enableExternalIps": False},
            "metadata": {"ray-cluster":
                         self._cfg.get("cluster_name", "cluster")},
        }
        self._t("POST",
                f"{TPU_API}/{self._parent()}/nodes?nodeId={name}", body)
        try:
            hosts = self._wait_ready(name, gang_size)
            for i, ip in enumerate(hosts):
                self._runner.run(
                    ip, self._bootstrap_command(name, i, node_config))
        except Exception:
            # Atomicity contract: partial slice (API stuck, a host that
            # failed bootstrap) never leaks — tear the whole slice down.
            try:
                self._t("DELETE", self._node_url(name))
            except Exception:
                pass
            raise
        self._groups[name] = {"type": node_type, "hosts": hosts}
        for i in range(len(hosts)):
            self._internal_ids.setdefault(f"{name}#{i}", None)
        return name

    def _wait_ready(self, name: str, gang_size: int) -> List[str]:
        deadline = time.monotonic() + self._ready_timeout
        while time.monotonic() < deadline:
            try:
                node = self._t("GET", self._node_url(name))
            except Exception:
                # Transient transport blip (or the async create not yet
                # visible — a GET right after POST can 404): retry within
                # the deadline instead of tearing the slice down.
                time.sleep(self._poll)
                continue
            state = node.get("state")
            if state == "READY":
                endpoints = node.get("networkEndpoints", [])
                ips = [e.get("ipAddress") for e in endpoints]
                if len(ips) < gang_size:
                    raise RuntimeError(
                        f"slice {name} READY with {len(ips)} hosts, "
                        f"expected {gang_size} (wrong accelerator_type "
                        "for this node type?)")
                return ips[:gang_size]
            if state in ("PREEMPTED", "TERMINATED", "FAILED"):
                raise RuntimeError(f"slice {name} entered {state} "
                                   "during creation")
            time.sleep(self._poll)
        raise TimeoutError(
            f"slice {name} not READY within {self._ready_timeout}s")

    def terminate_node_group(self, group_id: str) -> None:
        try:
            self._t("DELETE", self._node_url(group_id))
        finally:
            info = self._groups.pop(group_id, None)
            if info:
                for i in range(len(info["hosts"])):
                    self._internal_ids.pop(f"{group_id}#{i}", None)

    def node_groups(self) -> List[str]:
        return list(self._groups)

    def group_nodes(self, group_id: str) -> List[str]:
        info = self._groups.get(group_id)
        if not info:
            return []
        return [f"{group_id}#{i}" for i in range(len(info["hosts"]))]

    def group_type_of(self, group_id: str) -> Optional[str]:
        info = self._groups.get(group_id)
        return info["type"] if info else None

    # ---------------------------------------------------- per-node facade
    def create_node(self, node_type: str,
                    node_config: Dict[str, Any]) -> str:
        gid = self.create_node_group(node_type, node_config, 1)
        return f"{gid}#0"

    def terminate_node(self, provider_node_id: str) -> None:
        self.terminate_node_group(provider_node_id.split("#", 1)[0])

    def non_terminated_nodes(self) -> List[str]:
        return [n for g in self._groups for n in self.group_nodes(g)]

    def node_type_of(self, provider_node_id: str) -> Optional[str]:
        return self.group_type_of(provider_node_id.split("#", 1)[0])

    def internal_node_id(self, provider_node_id: str) -> Optional[bytes]:
        """Resolve via the GCS: bootstrapped raylets carry a
        provider_group/worker_index label."""
        cached = self._internal_ids.get(provider_node_id)
        if cached is not None:
            return cached
        group_id, _, idx = provider_node_id.partition("#")
        nodes = self._node_table()
        if nodes is None:
            return None
        for n in nodes or []:
            labels = n.get("labels") or {}
            if (labels.get("provider_group") == group_id
                    and labels.get("worker_index") == idx
                    and n.get("state") == "ALIVE"):
                self._internal_ids[provider_node_id] = n["node_id"]
                return n["node_id"]
        return None


    def refresh_groups(self) -> int:
        """Rediscover slices this cluster owns (reference: the gcp
        provider's nodes.list reconciliation): a restarted monitor must
        not orphan running slices (idle-terminate stops working, billing
        runs forever) nor double-launch min_workers. Returns the number
        of groups adopted."""
        try:
            listing = self._t("GET", f"{TPU_API}/{self._parent()}/nodes")
        except Exception:
            return 0
        mine = self._cfg.get("cluster_name", "cluster")
        adopted = 0
        for node in listing.get("nodes", []):
            meta = node.get("metadata") or {}
            if meta.get("ray-cluster") != mine:
                continue
            name = node.get("name", "").rsplit("/", 1)[-1]
            if not name or name in self._groups:
                continue
            ips = [e.get("ipAddress")
                   for e in node.get("networkEndpoints", [])]
            # Node type is recoverable from the name we minted:
            # ray-<cluster>-<type>-<hex>.
            prefix = f"ray-{mine}-".lower()
            node_type = name[len(prefix):].rsplit("-", 1)[0] \
                if name.startswith(prefix) else "unknown"
            self._groups[name] = {"type": node_type, "hosts": ips}
            adopted += 1
        return adopted

    def shutdown(self) -> None:
        for gid in list(self._groups):
            try:
                self.terminate_node_group(gid)
            except Exception:
                pass
