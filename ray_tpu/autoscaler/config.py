"""Cluster-launcher YAML config: schema validation + TPU pod expansion.

Reference: `python/ray/autoscaler/ray-schema.json` (the cluster YAML
schema), `autoscaler/_private/util.py:prepare_config/validate_config`.
TPU-first addition: a node type may declare ``node_config.tpu`` (an
accelerator type like ``v5e-16``); it expands into per-host resources,
a gang size (hosts per pod slice), and the promoted ``TPU-{type}-head``
resource on host 0 of every slice — the scheduling handle SURVEY M10
promotes for gang-launching pod slices atomically.

Example::

    cluster_name: tpu-demo
    max_workers: 16
    provider:
      type: fake            # fake | subprocess | external (module path)
    available_node_types:
      cpu.worker:
        resources: {CPU: 8}
        min_workers: 0
        max_workers: 4
      tpu.v5e-16:
        node_config: {tpu: v5e-16}
        min_workers: 0
        max_workers: 2      # pod slices, not hosts
    head_node_type: cpu.worker
    idle_timeout_minutes: 1
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

# Known slice topologies: accelerator type -> (hosts, chips_per_host).
# (reference analogue: `ray._private.accelerators.tpu` TPU_*_HOST maps;
# v5e: 8 chips/host max but 4/host for 16-chip slices, etc. Kept to the
# common configurations; unknown types fall back to user-declared values.)
TPU_SLICE_TOPOLOGY: Dict[str, tuple] = {
    "v4-8": (1, 4), "v4-16": (2, 4), "v4-32": (4, 4), "v4-64": (8, 4),
    "v5e-1": (1, 1), "v5e-4": (1, 4), "v5e-8": (1, 8),
    "v5e-16": (4, 4), "v5e-32": (8, 4), "v5e-64": (16, 4),
    "v5p-8": (1, 4), "v5p-16": (2, 4), "v5p-32": (4, 4),
    "v6e-4": (1, 4), "v6e-8": (1, 8), "v6e-16": (4, 4),
}

_TOP_KEYS = {"cluster_name", "max_workers", "provider",
             "available_node_types", "head_node_type",
             "idle_timeout_minutes", "setup_commands",
             "head_setup_commands", "worker_setup_commands",
             "initialization_commands", "file_mounts", "auth"}

_TYPE_KEYS = {"resources", "min_workers", "max_workers", "node_config",
              "worker_setup_commands", "labels"}


class ClusterConfigError(ValueError):
    pass


def load_cluster_config(path: str) -> Dict[str, Any]:
    import yaml

    if not os.path.isfile(path):
        raise ClusterConfigError(f"cluster config {path!r} not found")
    with open(path) as f:
        raw = yaml.safe_load(f)
    return validate_cluster_config(raw)


def validate_cluster_config(cfg: Any) -> Dict[str, Any]:
    if not isinstance(cfg, dict):
        raise ClusterConfigError("cluster config must be a mapping")
    unknown = set(cfg) - _TOP_KEYS
    if unknown:
        raise ClusterConfigError(
            f"unknown top-level config key(s): {sorted(unknown)}; "
            f"known: {sorted(_TOP_KEYS)}")
    if "cluster_name" not in cfg or not isinstance(cfg["cluster_name"], str):
        raise ClusterConfigError("cluster_name (str) is required")
    provider = cfg.get("provider") or {}
    if not isinstance(provider, dict) or "type" not in provider:
        raise ClusterConfigError("provider.type is required")
    types = cfg.get("available_node_types")
    if not isinstance(types, dict) or not types:
        raise ClusterConfigError("available_node_types must be a non-empty "
                                 "mapping of node type name -> spec")
    out = dict(cfg)
    out.setdefault("max_workers", 8)
    out.setdefault("idle_timeout_minutes", 5)
    out["available_node_types"] = {
        name: _expand_node_type(name, spec)
        for name, spec in types.items()
    }
    head = cfg.get("head_node_type")
    if head is not None and head not in types:
        raise ClusterConfigError(
            f"head_node_type {head!r} is not in available_node_types")
    if not isinstance(out["max_workers"], int) or out["max_workers"] < 0:
        raise ClusterConfigError("max_workers must be a non-negative int")
    return out


def _expand_node_type(name: str, spec: Any) -> Dict[str, Any]:
    if not isinstance(spec, dict):
        raise ClusterConfigError(f"node type {name!r} must be a mapping")
    unknown = set(spec) - _TYPE_KEYS
    if unknown:
        raise ClusterConfigError(
            f"node type {name!r}: unknown key(s) {sorted(unknown)}")
    out = dict(spec)
    out.setdefault("min_workers", 0)
    out.setdefault("max_workers", 1)
    out.setdefault("resources", {})
    out.setdefault("node_config", {})
    if not isinstance(out["resources"], dict):
        raise ClusterConfigError(f"node type {name!r}: resources must be "
                                 "a mapping")
    tpu = out["node_config"].get("tpu")
    if tpu:
        hosts, chips = tpu_slice_shape(
            tpu,
            hosts=out["node_config"].get("tpu_hosts"),
            chips_per_host=out["node_config"].get("tpu_chips_per_host"))
        res = dict(out["resources"])
        res.setdefault("CPU", out["node_config"].get("cpus_per_host", 8))
        res["TPU"] = chips
        res[f"TPU-{tpu.split('-')[0]}"] = 0.001 * chips  # accelerator tag
        out["resources"] = res
        out["gang_size"] = hosts
        # Host 0 of each slice carries the promoted pod-head resource:
        # a single task demanding {"TPU-v5e-16-head": 1} gang-schedules
        # the slice (each host then joins the same jax.distributed world).
        out["head_resources"] = {f"TPU-{tpu}-head": 1}
        out["tpu_type"] = tpu
    else:
        out["gang_size"] = int(out["node_config"].get("gang_size", 1))
    if out["gang_size"] < 1:
        raise ClusterConfigError(f"node type {name!r}: gang_size >= 1")
    return out


def tpu_slice_shape(tpu_type: str, hosts: Optional[int] = None,
                    chips_per_host: Optional[int] = None) -> tuple:
    """(hosts_per_slice, chips_per_host) for an accelerator type."""
    if hosts and chips_per_host:
        return int(hosts), int(chips_per_host)
    if tpu_type in TPU_SLICE_TOPOLOGY:
        return TPU_SLICE_TOPOLOGY[tpu_type]
    # "<gen>-<chips>" fallback: assume 4-chip hosts above 8 chips.
    try:
        chips_total = int(tpu_type.rsplit("-", 1)[1])
    except (IndexError, ValueError):
        raise ClusterConfigError(
            f"unknown TPU type {tpu_type!r}; declare tpu_hosts and "
            "tpu_chips_per_host explicitly") from None
    if chips_total <= 8:
        return 1, chips_total
    return chips_total // 4, 4


def make_provider(cfg: Dict[str, Any], gcs_addr, session_dir: str):
    """Instantiate the provider named by provider.type."""
    ptype = cfg["provider"]["type"]
    if ptype in ("fake", "subprocess"):
        from ray_tpu.autoscaler.tpu_pod_provider import SubprocessPodProvider

        return SubprocessPodProvider(gcs_addr, session_dir)
    if ptype in ("gcp", "gcp_tpu"):
        from ray_tpu.autoscaler.gcp_tpu_provider import GceTpuPodProvider

        return GceTpuPodProvider(cfg["provider"], gcs_addr)
    if ptype in ("kuberay", "kubernetes", "gke"):
        from ray_tpu.autoscaler.kuberay_provider import KubeRayProvider

        return KubeRayProvider(cfg["provider"], gcs_addr)
    if "." in ptype:  # external: "my.module.MyProvider"
        import importlib

        mod, _, cls = ptype.rpartition(".")
        provider_cls = getattr(importlib.import_module(mod), cls)
        return provider_cls(cfg["provider"], gcs_addr, session_dir)
    raise ClusterConfigError(
        f"unknown provider type {ptype!r}: use 'fake'/'subprocess', "
        "'gcp_tpu', 'kuberay', or a "
        "'module.Class' path")
