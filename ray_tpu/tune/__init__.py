"""ray_tpu.tune — hyperparameter search & trial execution.

Reference: `python/ray/tune/` (Tuner, TuneConfig, tune.report, search
spaces, schedulers). The execution engine (TuneController over trial
actors) also backs every trainer's `fit()`.
"""

from ray_tpu.tune._session import get_checkpoint, get_session, report
from ray_tpu.tune.schedulers import (
    AsyncHyperBandScheduler, FIFOScheduler, HyperBandScheduler,
    MedianStoppingRule, PopulationBasedTraining,
)
from ray_tpu.tune.search import (
    choice, grid_search, loguniform, randint, sample_from, uniform,
)
from ray_tpu.tune.suggest import (
    BOHBSearcher, ConcurrencyLimiter, GPEISearcher, OptunaSearch,
    TPESearcher,
)
from ray_tpu.tune.tuner import ResultGrid, TuneConfig, Tuner

__all__ = [
    "AsyncHyperBandScheduler", "BOHBSearcher", "ConcurrencyLimiter",
    "FIFOScheduler",
    "GPEISearcher", "HyperBandScheduler", "MedianStoppingRule",
    "OptunaSearch", "PopulationBasedTraining", "ResultGrid", "TPESearcher",
    "TuneConfig", "Tuner", "choice", "get_checkpoint", "get_session",
    "grid_search", "loguniform", "randint", "report", "sample_from",
    "uniform",
]

from ray_tpu._private.usage_stats import record_library_usage as _rlu

_rlu("tune")
del _rlu
