"""Tuner — the user-facing experiment API.

Reference: `python/ray/tune/tuner.py` (Tuner.fit -> tune.run ->
TuneController) and `tune/result_grid.py` (ResultGrid). Every trainer's
`fit()` routes through this engine as a single-trial experiment, exactly as
the reference's `BaseTrainer.fit` wraps itself in a Tuner
(`train/base_trainer.py:567`).
"""

from __future__ import annotations

import os
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train.config import Result, RunConfig
from ray_tpu.tune.execution.tune_controller import (
    ERRORED, Trial, TuneController,
)
from ray_tpu.tune.search import BasicVariantGenerator


@dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    max_concurrent_trials: int = 4
    scheduler: Any = None
    search_seed: Optional[int] = None
    trial_resources: Optional[Dict[str, float]] = None
    # Adaptive searcher (ray_tpu.tune.suggest.Searcher): when set, trials
    # are suggested incrementally instead of expanded up front, and
    # completed results feed back into the search (reference:
    # TuneConfig.search_alg).
    search_alg: Any = None


class ResultGrid:
    """Indexable view over per-trial Results (reference
    `tune/result_grid.py`)."""

    def __init__(self, results: List[Result], metric: Optional[str],
                 mode: str):
        self._results = results
        self._metric = metric
        self._mode = mode

    def __len__(self) -> int:
        return len(self._results)

    def __getitem__(self, i: int) -> Result:
        return self._results[i]

    def __iter__(self):
        return iter(self._results)

    @property
    def errors(self) -> List[str]:
        return [r.error for r in self._results if r.error]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("get_best_result requires a metric")
        scored = [r for r in self._results
                  if r.error is None and metric in (r.metrics or {})]
        if not scored:
            raise RuntimeError(f"no completed trial reported '{metric}'")
        return (max if mode == "max" else min)(
            scored, key=lambda r: r.metrics[metric])


class Tuner:
    def __init__(self, trainable: Callable = None, *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 _restore_path: Optional[str] = None):
        if trainable is not None and hasattr(trainable, "as_trainable"):
            trainable = trainable.as_trainable()
        self._trainable = trainable
        self._param_space = param_space or {}
        self._tune_config = tune_config or TuneConfig()
        self._run_config = run_config or RunConfig()
        self._restore_path = _restore_path

    # ------------------------------------------------------------------ fit
    def fit(self) -> ResultGrid:
        tc = self._tune_config
        if tc.search_alg is not None:
            # Both branches need a configured searcher: a restored
            # experiment keeps suggesting its remaining trials.
            tc.search_alg.set_search_properties(
                tc.metric, tc.mode, self._param_space)
        if self._restore_path:
            experiment_dir = self._restore_path
            trials = TuneController.load_experiment_state(experiment_dir)
        else:
            name = self._run_config.name or f"tune_{uuid.uuid4().hex[:8]}"
            experiment_dir = os.path.join(
                self._run_config.resolved_storage_path(), name)
            if tc.search_alg is not None:
                trials = []  # the controller pulls suggestions as slots free
            else:
                configs = BasicVariantGenerator(tc.search_seed).generate(
                    self._param_space, tc.num_samples)
                trials = [Trial(trial_id=f"trial_{i:05d}", config=cfg)
                          for i, cfg in enumerate(configs)]

        scheduler = tc.scheduler
        if scheduler is not None and getattr(scheduler, "metric",
                                             None) is None:
            # Reference Tune copies TuneConfig metric/mode into a scheduler
            # that wasn't explicitly configured (metric unset). A scheduler
            # constructed with its own metric/mode is left alone — blindly
            # overwriting mode would flip a min-mode ASHA to max and prune
            # the best trials.
            scheduler.metric = tc.metric
            if tc.mode:
                scheduler.mode = tc.mode
        controller = TuneController(
            self._trainable, trials, experiment_dir,
            metric=tc.metric, mode=tc.mode, scheduler=scheduler,
            max_concurrent=tc.max_concurrent_trials,
            trial_resources=tc.trial_resources,
            searcher=tc.search_alg, num_samples=tc.num_samples)
        self._last_trials = controller.run()  # post-run Trial introspection
        return ResultGrid(controller.results(), tc.metric, tc.mode)

    # -------------------------------------------------------------- restore
    @classmethod
    def restore(cls, path: str, trainable: Callable,
                tune_config: Optional[TuneConfig] = None,
                run_config: Optional[RunConfig] = None) -> "Tuner":
        """Resume an interrupted experiment from its directory: finished
        trials keep their results; interrupted/errored ones restart from
        their latest checkpoint (reference `tune/tuner.py` Tuner.restore +
        `tune/execution/experiment_state.py`)."""
        if not os.path.exists(os.path.join(path, "experiment_state.json")):
            raise FileNotFoundError(f"no experiment state under {path}")
        return cls(trainable, tune_config=tune_config,
                   run_config=run_config, _restore_path=path)
