"""TuneController — the trial execution engine.

Reference: `python/ray/tune/execution/tune_controller.py:72` — owns the
trial list, launches trial actors up to the concurrency cap, consumes
results, applies scheduler decisions, snapshots experiment state for
restore, and surfaces each trial's Result.
"""

from __future__ import annotations

import json
import os
import pickle
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import Result
from ray_tpu.tune import _session as tsession
from ray_tpu.tune.schedulers import (CONTINUE, EXPLOIT, FIFOScheduler,
                                     PAUSE, STOP)

PENDING = "PENDING"
PAUSED = "PAUSED"
RUNNING = "RUNNING"
TERMINATED = "TERMINATED"
ERRORED = "ERRORED"


@dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    status: str = PENDING
    history: List[Dict[str, Any]] = field(default_factory=list)
    checkpoint_path: Optional[str] = None
    error: Optional[str] = None
    stopped_early: bool = False
    exploits: int = 0  # PBT: times this trial cloned a donor checkpoint

    @property
    def last_result(self) -> Dict[str, Any]:
        return self.history[-1] if self.history else {}


@ray_tpu.remote(num_cpus=1)
class _TrialActor:
    """Runs one function-trainable trial."""

    def run(self, fn: Callable, config: Dict[str, Any], trial_dir: str,
            checkpoint_path: Optional[str]) -> bool:
        os.makedirs(trial_dir, exist_ok=True)
        ckpt = Checkpoint(checkpoint_path) if checkpoint_path else None
        self._session = tsession._TuneSession(fn, config, trial_dir, ckpt)
        self._session.start()
        return True

    def next_result(self):
        return self._session.next_result(timeout=600.0)

    def request_stop(self) -> bool:
        self._session.request_stop()
        return True


class TuneController:
    def __init__(self, trainable: Callable, trials: List[Trial],
                 experiment_dir: str, metric: Optional[str] = None,
                 mode: str = "max", scheduler=None,
                 max_concurrent: int = 4,
                 trial_resources: Optional[Dict[str, float]] = None,
                 searcher=None, num_samples: int = 0):
        self._trainable = trainable
        self.trials = trials
        self._dir = experiment_dir
        self._metric = metric
        self._mode = mode
        self._scheduler = scheduler or FIFOScheduler()
        self._max_concurrent = max(1, max_concurrent)
        self._resources = trial_resources or {"CPU": 1}
        # Adaptive search (reference: SearchGenerator over a Searcher):
        # trials are requested one at a time as slots free, so completed
        # results steer later suggestions.
        self._searcher = searcher
        self._num_samples = num_samples
        os.makedirs(experiment_dir, exist_ok=True)

    def _maybe_suggest(self, pending: List["Trial"], n_running: int) -> None:
        if self._searcher is None:
            return
        while (len(self.trials) < self._num_samples
               and len(pending) + n_running < self._max_concurrent):
            trial_id = f"trial_{len(self.trials):05d}"
            config = self._searcher.suggest(trial_id)
            if config is None:  # limiter saturated / space exhausted
                return
            trial = Trial(trial_id=trial_id, config=config)
            self.trials.append(trial)
            pending.append(trial)

    def _notify_searcher(self, trial: "Trial") -> None:
        if self._searcher is None:
            return
        try:
            self._searcher.on_trial_complete(
                trial.trial_id, result=trial.last_result or None,
                error=trial.status == ERRORED)
        except Exception as e:  # noqa: BLE001
            # Surfaced, not swallowed: a searcher that drops every
            # observation silently degrades to random search with no
            # sign anything is wrong.
            import sys

            print(f"[tune] searcher.on_trial_complete failed for "
                  f"{trial.trial_id}: {e!r}", file=sys.stderr)

    # ----------------------------------------------------------------- run
    def run(self) -> List[Trial]:
        pending = [t for t in self.trials if t.status == PENDING]
        running: Dict[str, Any] = {}   # trial_id -> (actor, in-flight ref)
        trial_by_id = {t.trial_id: t for t in self.trials}
        self._save_experiment_state()

        while True:
            # Suggest BEFORE the emptiness check: when the last running
            # trial completes, the searcher must get a chance to refill
            # or fit() exits after one trial at max_concurrent=1. A
            # suggest() of None with nothing pending/running means the
            # space (or limiter) is exhausted — stop rather than spin.
            self._maybe_suggest(pending, len(running))
            trial_by_id.update({t.trial_id: t for t in self.trials})
            # Synchronous schedulers (HyperBand) park trials at rung
            # barriers and release them in batches once the rung is
            # decided.
            if hasattr(self._scheduler, "pop_decisions"):
                resume, stop = self._scheduler.pop_decisions()
                for tid in resume:
                    t = trial_by_id.get(tid)
                    if t is not None and t.status == PAUSED:
                        t.status = PENDING
                        pending.append(t)
                for tid in stop:
                    t = trial_by_id.get(tid)
                    if t is not None and t.status in (PAUSED, PENDING):
                        t.status = TERMINATED
                        t.stopped_early = True
                        if t in pending:
                            pending.remove(t)
                        self._notify_searcher(t)
            paused_left = any(t.status == PAUSED for t in self.trials)
            if not pending and not running and not paused_left:
                break
            if not pending and not running and paused_left:
                # Only barrier-parked trials remain. Normally the last
                # pause already flushed its bracket; force a flush to
                # cover restore-from-snapshot and scheduler bugs, and
                # fail the stragglers rather than spin forever.
                flush = getattr(self._scheduler, "flush_barriers", None)
                if flush is not None and flush():
                    continue
                for t in self.trials:
                    if t.status == PAUSED:
                        t.status = ERRORED
                        t.error = "parked at a rung barrier that never flushed"
                        self._notify_searcher(t)
                break
            while pending and len(running) < self._max_concurrent:
                trial = pending.pop(0)
                trial_dir = os.path.join(self._dir, trial.trial_id)
                launched = False
                for attempt in range(2):
                    actor = _TrialActor.options(
                        num_cpus=self._resources.get("CPU", 1)).remote()
                    try:
                        ray_tpu.get(actor.run.remote(
                            self._trainable, trial.config, trial_dir,
                            trial.checkpoint_path), timeout=300)
                        launched = True
                        break
                    except Exception as e:  # actor/worker died at launch
                        launch_error = e
                        try:
                            ray_tpu.kill(actor)
                        except Exception:
                            pass
                if not launched:
                    trial.status = ERRORED
                    trial.error = f"trial launch failed: {launch_error}"
                    self._notify_searcher(trial)
                    self._save_experiment_state()
                    continue
                trial.status = RUNNING
                running[trial.trial_id] = (actor, actor.next_result.remote())

            if not running:
                continue
            refs = [ref for (_, ref) in running.values()]
            ready, _ = ray_tpu.wait(refs, num_returns=1, timeout=1.0)
            if not ready:
                continue
            ready_ref = ready[0]
            trial_id = next(tid for tid, (_, ref) in running.items()
                            if ref == ready_ref)
            actor, _ = running[trial_id]
            trial = trial_by_id[trial_id]
            try:
                item = ray_tpu.get(ready_ref, timeout=30)
            except Exception as e:  # actor died
                trial.status = ERRORED
                trial.error = f"trial actor died: {e}"
                getattr(self._scheduler, "on_trial_remove",
                        lambda _t: None)(trial_id)
                # The session persists checkpoints to the trial dir BEFORE
                # report() returns, so a crash can leave a newer checkpoint
                # on disk than the last result we received — recover it for
                # restore (reference: trial dirs are the durable record).
                # Never clobber a checkpoint_path pointing OUTSIDE the
                # trial dir (a freshly-assigned PBT donor checkpoint) and
                # never go backwards in index.
                latest = self._latest_disk_checkpoint(trial.trial_id)
                cur = trial.checkpoint_path
                trial_dir = os.path.join(self._dir, trial.trial_id)
                cur_in_dir = (cur is not None and
                              os.path.dirname(os.path.abspath(cur))
                              == os.path.abspath(trial_dir))
                if latest is not None and (
                        cur is None or (cur_in_dir and os.path.basename(
                            latest) > os.path.basename(cur))):
                    trial.checkpoint_path = latest
                running.pop(trial_id)
                self._notify_searcher(trial)
                self._save_experiment_state()
                continue

            if item is None:  # poll timeout inside actor; re-arm
                running[trial_id] = (actor, actor.next_result.remote())
                continue
            kind, payload, ckpt_path = item
            if kind == tsession.FINISHED:
                trial.status = TERMINATED
                running.pop(trial_id)
                ray_tpu.kill(actor)
                self._notify_searcher(trial)
            elif kind == tsession.ERRORED:
                trial.status = ERRORED
                trial.error = payload
                running.pop(trial_id)
                ray_tpu.kill(actor)
                self._notify_searcher(trial)
            else:
                metrics = dict(payload or {})
                metrics.setdefault("training_iteration",
                                   len(trial.history) + 1)
                trial.history.append(metrics)
                if ckpt_path:
                    trial.checkpoint_path = ckpt_path
                decision = CONTINUE
                if self._metric and self._metric in metrics:
                    decision = self._scheduler.on_result(
                        trial_id, metrics["training_iteration"],
                        float(metrics[self._metric]))
                if decision == PAUSE:
                    # Rung barrier: checkpoint stays on disk; release
                    # the slot and park until the scheduler decides.
                    if trial.checkpoint_path is None:
                        # Resume would silently restart from iteration 0
                        # while training_iteration keeps counting — rung
                        # comparisons would then rank restarted runs.
                        import sys

                        print(f"[tune] WARNING: pausing {trial_id} with "
                              "no checkpoint; the trainable never "
                              "reported one, so resume restarts from "
                              "scratch (report a Checkpoint to make "
                              "HyperBand pause/resume meaningful)",
                              file=sys.stderr)
                    try:
                        ray_tpu.get(actor.request_stop.remote(), timeout=10)
                    except Exception:
                        pass
                    running.pop(trial_id)
                    ray_tpu.kill(actor)
                    trial.status = PAUSED
                elif decision == STOP:
                    trial.stopped_early = True
                    trial.status = TERMINATED
                    try:
                        ray_tpu.get(actor.request_stop.remote(), timeout=10)
                    except Exception:
                        pass
                    running.pop(trial_id)
                    ray_tpu.kill(actor)
                    self._notify_searcher(trial)
                elif decision == EXPLOIT:
                    # PBT: clone a top-quantile donor's checkpoint into
                    # this trial with a perturbed config and relaunch
                    # (reference: pbt.py _exploit).
                    donor = trial_by_id.get(
                        self._scheduler.exploit_target(trial_id))
                    if (donor is None or donor.trial_id == trial_id
                            or donor.checkpoint_path is None):
                        # Nothing usable to exploit: keep training.
                        running[trial_id] = (actor,
                                             actor.next_result.remote())
                    else:
                        try:
                            ray_tpu.get(actor.request_stop.remote(),
                                        timeout=10)
                        except Exception:
                            pass
                        running.pop(trial_id)
                        ray_tpu.kill(actor)
                        trial.config = self._scheduler.mutate(donor.config)
                        trial.checkpoint_path = donor.checkpoint_path
                        trial.status = PENDING
                        trial.exploits += 1
                        pending.append(trial)
                else:
                    running[trial_id] = (actor, actor.next_result.remote())
            self._save_experiment_state()
        return self.trials

    def _latest_disk_checkpoint(self, trial_id: str) -> Optional[str]:
        trial_dir = os.path.join(self._dir, trial_id)
        try:
            cands = [os.path.join(trial_dir, d)
                     for d in os.listdir(trial_dir)
                     if d.startswith("checkpoint")]
        except OSError:
            return None
        # Highest checkpoint index, not mtime: session numbering is
        # monotonic across relaunches, while rewriting files inside an
        # existing dir does not bump the dir's mtime.
        cands = [c for c in cands if os.path.isdir(c)]
        return max(cands, key=os.path.basename) if cands else None

    # ---------------------------------------------------------- persistence
    def _save_experiment_state(self) -> None:
        state = [{
            "trial_id": t.trial_id, "status": t.status,
            "history": t.history, "checkpoint_path": t.checkpoint_path,
            "error": t.error, "stopped_early": t.stopped_early,
        } for t in self.trials]
        tmp = os.path.join(self._dir, ".experiment_state.tmp")
        with open(tmp, "w") as f:
            json.dump(state, f)
        os.replace(tmp, os.path.join(self._dir, "experiment_state.json"))
        # Rewritten every save: PBT exploits mutate trial configs
        # mid-experiment, and restore must see the post-mutation values.
        cfg_tmp = os.path.join(self._dir, ".trial_configs.tmp")
        with open(cfg_tmp, "wb") as f:
            pickle.dump({t.trial_id: t.config for t in self.trials}, f)
        os.replace(cfg_tmp, os.path.join(self._dir, "trial_configs.pkl"))

    @staticmethod
    def load_experiment_state(experiment_dir: str) -> List[Trial]:
        with open(os.path.join(experiment_dir,
                               "experiment_state.json")) as f:
            state = json.load(f)
        with open(os.path.join(experiment_dir, "trial_configs.pkl"),
                  "rb") as f:
            configs = pickle.load(f)
        trials = []
        for s in state:
            t = Trial(trial_id=s["trial_id"],
                      config=configs.get(s["trial_id"], {}),
                      status=s["status"], history=s["history"],
                      checkpoint_path=s["checkpoint_path"],
                      error=s["error"],
                      stopped_early=s.get("stopped_early", False))
            if t.status in (RUNNING, ERRORED):
                # Interrupted mid-flight: resume from latest checkpoint.
                # Clear the stale error and pre-crash history — the Result
                # of the resumed run reports only post-restore progress
                # (the checkpoint, not the metric log, is the state that
                # carries over).
                t.status = PENDING
                t.error = None
                t.history = []
            trials.append(t)
        return trials

    # ---------------------------------------------------------------- query
    def results(self) -> List[Result]:
        out = []
        for t in self.trials:
            out.append(Result(
                metrics=t.last_result,
                checkpoint=(Checkpoint(t.checkpoint_path)
                            if t.checkpoint_path else None),
                path=os.path.join(self._dir, t.trial_id),
                metrics_dataframe=t.history,
                error=t.error,
                config=t.config,
            ))
        return out
