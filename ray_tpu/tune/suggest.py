"""Adaptive search algorithms — suggest/observe searchers for Tune.

Reference: `python/ray/tune/search/` (Searcher ABC at `searcher.py:40`,
ConcurrencyLimiter, and the Optuna/HyperOpt adapters). The controller
asks a Searcher for the next config as slots free up and reports
completed trials back, so the search posterior actually steers later
trials — unlike BasicVariantGenerator's up-front expansion.

`TPESearcher` is a from-scratch Tree-structured Parzen Estimator over
the same Domain objects grid/random search use (numpy only — no
external HPO library in the image); `OptunaSearch` adapts an installed
optuna, and raises a clear error when the library is absent.
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional

import numpy as np

from ray_tpu.tune.search import (
    Categorical, Domain, LogUniform, Randint, SampleFrom, Uniform,
    _GridSearch,
)


class Searcher:
    """suggest()/on_trial_complete() protocol (reference:
    `search/searcher.py:40`)."""

    metric: Optional[str] = None
    mode: str = "max"

    def set_search_properties(self, metric: Optional[str], mode: str,
                              param_space: Dict[str, Any]) -> None:
        self.metric = metric
        self.mode = mode
        self.param_space = self._normalize_space(param_space)

    @staticmethod
    def _normalize_space(param_space: Dict[str, Any]) -> Dict[str, Any]:
        """Adaptive searchers model distributions: grid_search entries
        become Categorical; sample_from (arbitrary code over the partial
        config) cannot be modeled — reject it clearly instead of
        crashing mid-experiment."""
        out = {}
        for key, dom in param_space.items():
            if isinstance(dom, _GridSearch):
                out[key] = Categorical(list(dom.values))
            elif isinstance(dom, SampleFrom):
                raise ValueError(
                    f"param {key!r}: sample_from is not supported by "
                    "adaptive searchers (use a Domain, or "
                    "BasicVariantGenerator via search_alg=None)")
            else:
                out[key] = dom
        return out

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        pass


class ConcurrencyLimiter(Searcher):
    """Cap outstanding (suggested but unfinished) trials (reference:
    `search/concurrency_limiter.py`)."""

    def __init__(self, searcher: Searcher, max_concurrent: int = 4):
        self.searcher = searcher
        self.max_concurrent = max(1, max_concurrent)
        self._live: set = set()

    def set_search_properties(self, metric, mode, param_space):
        super().set_search_properties(metric, mode, param_space)
        self.searcher.set_search_properties(metric, mode, param_space)

    def suggest(self, trial_id):
        if len(self._live) >= self.max_concurrent:
            return None
        out = self.searcher.suggest(trial_id)
        if out is not None:
            self._live.add(trial_id)
        return out

    def on_trial_complete(self, trial_id, result=None, error=False):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result, error)


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator over Domain spaces.

    After ``n_startup`` random trials, observations split at the γ
    quantile into good/bad sets; numeric dims model both with Gaussian
    KDEs (in log space for LogUniform) and categorical dims with
    smoothed counts. Candidates sample from the good model and the one
    maximizing l(x)/g(x) wins — the standard TPE acquisition.
    """

    def __init__(self, n_startup: int = 8, gamma: float = 0.25,
                 n_candidates: int = 32, seed: Optional[int] = None):
        self._n_startup = n_startup
        self._gamma = gamma
        self._n_cand = n_candidates
        self._rng = np.random.default_rng(seed)
        self._pyrng = random.Random(seed)
        self._suggested: Dict[str, Dict[str, Any]] = {}
        self._obs: List[tuple] = []  # (config, score) score higher=better

    # ------------------------------------------------------------ helpers
    def _dims(self):
        return {k: v for k, v in self.param_space.items()
                if isinstance(v, Domain)}

    def _random_config(self) -> Dict[str, Any]:
        out = {}
        for key, dom in self.param_space.items():
            out[key] = dom.sample(self._pyrng) if isinstance(dom, Domain) \
                else dom
        return out

    @staticmethod
    def _to_num(dom, v):
        return math.log(v) if isinstance(dom, LogUniform) else float(v)

    @staticmethod
    def _from_num(dom, x):
        if isinstance(dom, LogUniform):
            return float(np.clip(math.exp(x), dom.lower, dom.upper))
        if isinstance(dom, Randint):
            return int(np.clip(round(x), dom.lower, dom.upper - 1))
        return float(np.clip(x, dom.lower, dom.upper))

    def _kde_logpdf(self, xs: np.ndarray, pts: np.ndarray, lo, hi) -> np.ndarray:
        if len(pts) == 0:
            return np.zeros_like(xs)
        bw = max((hi - lo) / max(len(pts), 1) * 1.06, (hi - lo) * 0.02, 1e-12)
        diff = (xs[:, None] - pts[None, :]) / bw
        return np.log(np.exp(-0.5 * diff * diff).mean(axis=1) / bw + 1e-12)

    # ------------------------------------------------------------- protocol
    def suggest(self, trial_id):
        if len(self._obs) < self._n_startup:
            cfg = self._random_config()
            self._suggested[trial_id] = cfg
            return dict(cfg)
        ranked = sorted(self._obs, key=lambda cs: -cs[1])
        n_good = max(1, int(len(ranked) * self._gamma))
        good = [c for c, _ in ranked[:n_good]]
        bad = [c for c, _ in ranked[n_good:]] or good
        cfg = {}
        for key, dom in self.param_space.items():
            if not isinstance(dom, Domain):
                cfg[key] = dom
                continue
            if isinstance(dom, Categorical):
                cats = dom.categories
                gc = np.array([sum(1.0 for c in good if c[key] == v) + 1.0
                               for v in cats])
                bc = np.array([sum(1.0 for c in bad if c[key] == v) + 1.0
                               for v in cats])
                score = (gc / gc.sum()) / (bc / bc.sum())
                cfg[key] = cats[int(np.argmax(
                    score * self._rng.dirichlet(np.ones(len(cats))) ** 0.1))]
                continue
            lo = self._to_num(dom, dom.lower)
            hi = self._to_num(dom, getattr(dom, "upper"))
            gpts = np.array([self._to_num(dom, c[key]) for c in good])
            bpts = np.array([self._to_num(dom, c[key]) for c in bad])
            # Candidates from the good KDE (plus uniform exploration).
            idx = self._rng.integers(0, len(gpts), self._n_cand)
            bw = max((hi - lo) * 0.1, 1e-12)
            cand = gpts[idx] + self._rng.normal(0, bw, self._n_cand)
            cand = np.clip(cand, lo, hi)
            cand[0] = self._rng.uniform(lo, hi)  # never fully greedy
            ei = (self._kde_logpdf(cand, gpts, lo, hi)
                  - self._kde_logpdf(cand, bpts, lo, hi))
            cfg[key] = self._from_num(dom, float(cand[int(np.argmax(ei))]))
        self._suggested[trial_id] = cfg
        return dict(cfg)

    def on_trial_complete(self, trial_id, result=None, error=False):
        cfg = self._suggested.pop(trial_id, None)
        if cfg is None or error or not result or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        self._obs.append((cfg, score))


class BOHBSearcher(TPESearcher):
    """BOHB: multi-fidelity TPE (reference:
    `tune/search/bohb/bohb_search.py`, which wraps hpbandster's KDE;
    this is a from-scratch equivalent over our TPE).

    Observations are pooled per budget (the `training_iteration` a trial
    reached when it reported — under HyperBand rungs, its rung budget).
    suggest() models on the LARGEST budget whose pool has at least
    `min_points_per_budget` observations (default: #dims + 2, BOHB's
    rule) so the KDE is fit on the highest-fidelity evidence available,
    falling back to the all-budgets pool (plain TPE) before any rung has
    enough points. Pair with `HyperBandScheduler` for BOHB proper.
    """

    def __init__(self, n_startup: int = 8, gamma: float = 0.25,
                 n_candidates: int = 32, seed: Optional[int] = None,
                 min_points_per_budget: Optional[int] = None):
        super().__init__(n_startup=n_startup, gamma=gamma,
                         n_candidates=n_candidates, seed=seed)
        self._min_points = min_points_per_budget
        self._by_budget: Dict[int, List[tuple]] = {}

    def _model_pool(self) -> Optional[List[tuple]]:
        need = (self._min_points if self._min_points is not None
                else len(self._dims()) + 2)
        for b in sorted(self._by_budget, reverse=True):
            if len(self._by_budget[b]) >= need:
                return self._by_budget[b]
        return None

    def suggest(self, trial_id):
        pool = self._model_pool()
        if pool is None or len(self._obs) < self._n_startup:
            return super().suggest(trial_id)
        all_obs, startup = self._obs, self._n_startup
        # The qualifying pool may be smaller than n_startup (rungs are
        # narrow); BOHB's rule says model as soon as the pool qualifies,
        # so drop the startup gate for the swapped-in pool — otherwise
        # the high-fidelity regime would silently fall back to random.
        self._obs, self._n_startup = pool, 0
        try:
            return super().suggest(trial_id)
        finally:
            self._obs, self._n_startup = all_obs, startup

    def on_trial_complete(self, trial_id, result=None, error=False):
        cfg = self._suggested.get(trial_id)
        super().on_trial_complete(trial_id, result, error)
        if cfg is None or error or not result or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        budget = int(result.get("training_iteration", 0) or 0)
        self._by_budget.setdefault(budget, []).append((cfg, score))


class GPEISearcher(Searcher):
    """Native Gaussian-process searcher with Expected Improvement
    (reference role: `tune/search/bayesopt/bayesopt_search.py`, which
    adapts the external bayes_opt GP — rebuilt here on numpy only).

    Params encode to [0,1]^d (log-scaled for LogUniform, index-scaled
    for Categorical/Randint). After ``n_startup`` random trials, fit an
    RBF-kernel GP posterior over observations and suggest the candidate
    (from ``n_candidates`` random draws) maximizing EI over the best
    observed value.
    """

    def __init__(self, n_startup: int = 6, n_candidates: int = 256,
                 length_scale: float = 0.2, noise: float = 1e-4,
                 xi: float = 0.01, seed: Optional[int] = None):
        self._n_startup = n_startup
        self._n_cand = n_candidates
        self._ls = length_scale
        self._noise = noise
        self._xi = xi
        self._rng = np.random.default_rng(seed)
        self._pyrng = random.Random(seed)
        self._suggested: Dict[str, Dict[str, Any]] = {}
        self._X: List[np.ndarray] = []
        self._y: List[float] = []

    # ---------------------------------------------------------- encoding
    def _domains(self):
        return [(k, v) for k, v in sorted(self.param_space.items())
                if isinstance(v, Domain)]

    def _encode(self, cfg: Dict[str, Any]) -> np.ndarray:
        xs = []
        for key, dom in self._domains():
            v = cfg[key]
            if isinstance(dom, Categorical):
                cats = list(dom.categories)
                xs.append(cats.index(v) / max(1, len(cats) - 1)
                          if v in cats else 0.5)
            elif isinstance(dom, LogUniform):
                lo, hi = math.log(dom.lower), math.log(dom.upper)
                xs.append((math.log(v) - lo) / max(hi - lo, 1e-12))
            else:
                lo = float(dom.lower)
                hi = float(getattr(dom, "upper"))
                xs.append((float(v) - lo) / max(hi - lo, 1e-12))
        return np.asarray(xs)

    def _random_config(self) -> Dict[str, Any]:
        return {k: (v.sample(self._pyrng) if isinstance(v, Domain) else v)
                for k, v in self.param_space.items()}

    def _kernel(self, A: np.ndarray, B: np.ndarray) -> np.ndarray:
        d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / (self._ls ** 2))

    # ---------------------------------------------------------- protocol
    def suggest(self, trial_id):
        if len(self._y) < self._n_startup:
            cfg = self._random_config()
            self._suggested[trial_id] = cfg
            return dict(cfg)
        X = np.stack(self._X)
        y = np.asarray(self._y)
        mu0, sd0 = y.mean(), y.std() or 1.0
        yn = (y - mu0) / sd0
        K = self._kernel(X, X) + self._noise * np.eye(len(X))
        try:
            L = np.linalg.cholesky(K)
            alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))
        except np.linalg.LinAlgError:
            cfg = self._random_config()
            self._suggested[trial_id] = cfg
            return dict(cfg)
        cands = [self._random_config() for _ in range(self._n_cand)]
        C = np.stack([self._encode(c) for c in cands])
        Ks = self._kernel(C, X)
        mu = Ks @ alpha
        v = np.linalg.solve(L, Ks.T)
        var = np.clip(1.0 - (v * v).sum(0), 1e-12, None)
        sigma = np.sqrt(var)
        best = yn.max()
        z = (mu - best - self._xi) / sigma
        # EI = sigma * (z * Phi(z) + phi(z))
        phi = np.exp(-0.5 * z * z) / math.sqrt(2 * math.pi)
        Phi = 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2)))
        ei = sigma * (z * Phi + phi)
        cfg = cands[int(np.argmax(ei))]
        self._suggested[trial_id] = cfg
        return dict(cfg)

    def on_trial_complete(self, trial_id, result=None, error=False):
        cfg = self._suggested.pop(trial_id, None)
        if cfg is None or error or not result or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        self._X.append(self._encode(cfg))
        self._y.append(score)


class OptunaSearch(Searcher):
    """Adapter over an installed optuna (reference:
    `search/optuna/optuna_search.py`); raises ImportError with guidance
    when the library isn't present."""

    def __init__(self, seed: Optional[int] = None):
        try:
            import optuna
        except ImportError as e:
            raise ImportError(
                "OptunaSearch requires the 'optuna' package; it is not "
                "bundled — use TPESearcher for a built-in adaptive "
                "searcher") from e
        self._optuna = optuna
        self._seed = seed
        self._study = None
        self._live: Dict[str, Any] = {}

    def set_search_properties(self, metric, mode, param_space):
        super().set_search_properties(metric, mode, param_space)
        sampler = self._optuna.samplers.TPESampler(seed=self._seed)
        self._study = self._optuna.create_study(
            direction="maximize" if mode == "max" else "minimize",
            sampler=sampler)

    def suggest(self, trial_id):
        t = self._study.ask()
        cfg = {}
        for key, dom in self.param_space.items():
            if isinstance(dom, Categorical):
                cfg[key] = t.suggest_categorical(key, dom.categories)
            elif isinstance(dom, LogUniform):
                cfg[key] = t.suggest_float(key, dom.lower, dom.upper,
                                           log=True)
            elif isinstance(dom, Uniform):
                cfg[key] = t.suggest_float(key, dom.lower, dom.upper)
            elif isinstance(dom, Randint):
                cfg[key] = t.suggest_int(key, dom.lower, dom.upper - 1)
            else:
                cfg[key] = dom
        self._live[trial_id] = t
        return cfg

    def on_trial_complete(self, trial_id, result=None, error=False):
        t = self._live.pop(trial_id, None)
        if t is None:
            return
        if error or not result or self.metric not in result:
            self._study.tell(t, state=self._optuna.trial.TrialState.FAIL)
        else:
            self._study.tell(t, float(result[self.metric]))
