"""Search spaces + basic variant generation.

Reference: `python/ray/tune/search/sample.py` (Domain objects) and
`search/basic_variant.py` (BasicVariantGenerator): grid_search entries are
expanded cross-product; stochastic domains are sampled once per trial;
`num_samples` repeats the whole expansion.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Any, Callable, Dict, List, Optional


@dataclasses.dataclass
class _GridSearch:
    values: List[Any]


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


@dataclasses.dataclass
class Categorical(Domain):
    categories: List[Any]

    def sample(self, rng):
        return rng.choice(self.categories)


@dataclasses.dataclass
class Uniform(Domain):
    lower: float
    upper: float

    def sample(self, rng):
        return rng.uniform(self.lower, self.upper)


@dataclasses.dataclass
class LogUniform(Domain):
    lower: float
    upper: float

    def sample(self, rng):
        import math

        return math.exp(rng.uniform(math.log(self.lower),
                                    math.log(self.upper)))


@dataclasses.dataclass
class Randint(Domain):
    lower: int
    upper: int

    def sample(self, rng):
        return rng.randrange(self.lower, self.upper)


@dataclasses.dataclass
class SampleFrom(Domain):
    fn: Callable[[Dict[str, Any]], Any]

    def sample(self, rng):  # resolved against the partial config later
        raise NotImplementedError


def grid_search(values: List[Any]) -> _GridSearch:
    return _GridSearch(list(values))


def choice(categories: List[Any]) -> Categorical:
    return Categorical(list(categories))


def uniform(lower: float, upper: float) -> Uniform:
    return Uniform(lower, upper)


def loguniform(lower: float, upper: float) -> LogUniform:
    return LogUniform(lower, upper)


def randint(lower: int, upper: int) -> Randint:
    return Randint(lower, upper)


def sample_from(fn: Callable[[Dict[str, Any]], Any]) -> SampleFrom:
    return SampleFrom(fn)


class BasicVariantGenerator:
    """Expands a param_space into concrete trial configs."""

    def __init__(self, seed: Optional[int] = None):
        self._rng = random.Random(seed)

    def generate(self, param_space: Dict[str, Any], num_samples: int = 1
                 ) -> List[Dict[str, Any]]:
        grids: List[tuple] = []
        for key, value in (param_space or {}).items():
            if isinstance(value, _GridSearch):
                grids.append((key, value.values))
        combos: List[Dict[str, Any]] = [{}]
        for key, values in grids:
            combos = [dict(c, **{key: v}) for c in combos for v in values]

        out: List[Dict[str, Any]] = []
        for _ in range(max(num_samples, 1)):
            for combo in combos:
                cfg: Dict[str, Any] = {}
                for key, value in (param_space or {}).items():
                    if isinstance(value, _GridSearch):
                        cfg[key] = combo[key]
                    elif isinstance(value, SampleFrom):
                        pass  # resolved after the rest
                    elif isinstance(value, Domain):
                        cfg[key] = value.sample(self._rng)
                    else:
                        cfg[key] = value
                for key, value in (param_space or {}).items():
                    if isinstance(value, SampleFrom):
                        cfg[key] = value.fn(dict(cfg))
                out.append(cfg)
        return out
