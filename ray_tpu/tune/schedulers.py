"""Trial schedulers.

Reference: `python/ray/tune/schedulers/async_hyperband.py` — ASHA: rungs at
grace_period * reduction_factor^k; at each rung a trial continues only if its
result is in the top 1/reduction_factor of results recorded at that rung.
Also `tune/schedulers/pbt.py` (PopulationBasedTraining: bottom-quantile
trials clone a top-quantile trial's checkpoint with perturbed hyperparams)
and `tune/schedulers/median_stopping_rule.py`.
"""

from __future__ import annotations

import math
import random
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"
# Scheduler asks the controller to clone a donor trial's checkpoint into
# this trial with a perturbed config (PBT). The controller calls
# `exploit_target(trial_id)` and `mutate(donor_config)` to act on it.
EXPLOIT = "EXPLOIT"


class FIFOScheduler:
    def on_result(self, trial_id: str, iteration: int, value: float) -> str:
        return CONTINUE


class AsyncHyperBandScheduler:
    def __init__(self, metric: str = None, mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 3):
        self.metric = metric
        self.mode = mode
        self._max_t = max_t
        self._grace = grace_period
        self._rf = reduction_factor
        rungs: List[int] = []
        t = grace_period
        while t < max_t:
            rungs.append(int(t))
            t *= reduction_factor
        self._rungs = rungs                       # ascending milestones
        self._recorded: Dict[int, List[float]] = defaultdict(list)
        self._reached: Dict[str, set] = defaultdict(set)  # trial -> rungs

    def on_result(self, trial_id: str, iteration: int, value: float) -> str:
        if self.mode == "min":
            value = -value
        if iteration >= self._max_t:
            return STOP
        # A trial reporting a coarser iteration cadence may skip past a
        # milestone; evaluate at the highest rung reached but not yet
        # scored for this trial (reference ASHA: `>= milestone`).
        for rung in reversed(self._rungs):
            if iteration >= rung and rung not in self._reached[trial_id]:
                self._reached[trial_id].add(rung)
                recorded = self._recorded[rung]
                recorded.append(value)
                k = max(1, int(math.ceil(len(recorded) / self._rf)))
                cutoff = sorted(recorded, reverse=True)[k - 1]
                if value < cutoff:
                    return STOP
                break
        return CONTINUE


class MedianStoppingRule:
    """Stop a trial whose best value so far is worse than the median of
    other trials' running averages at a comparable step (reference:
    `tune/schedulers/median_stopping_rule.py`; the Vizier rule)."""

    def __init__(self, metric: str = None, mode: str = "max",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.metric = metric
        self.mode = mode
        self._grace = grace_period
        self._min_samples = min_samples_required
        self._sums: Dict[str, float] = defaultdict(float)
        self._counts: Dict[str, int] = defaultdict(int)
        self._best: Dict[str, float] = {}

    def on_result(self, trial_id: str, iteration: int, value: float) -> str:
        if self.mode == "min":
            value = -value
        self._sums[trial_id] += value
        self._counts[trial_id] += 1
        self._best[trial_id] = max(
            self._best.get(trial_id, -math.inf), value)
        if iteration < self._grace:
            return CONTINUE
        others = [self._sums[t] / self._counts[t]
                  for t in self._sums if t != trial_id]
        if len(others) < self._min_samples:
            return CONTINUE
        median = sorted(others)[len(others) // 2]
        return STOP if self._best[trial_id] < median else CONTINUE


class PopulationBasedTraining:
    """PBT (reference `tune/schedulers/pbt.py`): every
    `perturbation_interval` iterations, a trial in the bottom quantile
    exploits — the controller clones a random top-quantile trial's latest
    checkpoint into it and re-launches with a perturbed config.

    `hyperparam_mutations` maps config key -> list of choices | callable
    () -> value | (low, high) numeric range. On perturb: with
    `resample_probability` draw fresh from the spec, otherwise multiply
    numeric values by 0.8/1.2 (or step to a list neighbor).
    """

    def __init__(self, metric: str = None, mode: str = "max",
                 perturbation_interval: int = 2,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        if not 0 < quantile_fraction <= 0.5:
            raise ValueError("quantile_fraction must be in (0, 0.5]")
        self.metric = metric
        self.mode = mode
        self._interval = perturbation_interval
        self._mutations = hyperparam_mutations or {}
        self._quantile = quantile_fraction
        self._resample_prob = resample_probability
        self._rng = random.Random(seed)
        self._scores: Dict[str, float] = {}          # signed latest value
        self._last_perturb: Dict[str, int] = defaultdict(int)
        self._donor_for: Dict[str, str] = {}

    # ------------------------------------------------------------ protocol
    def on_result(self, trial_id: str, iteration: int, value: float) -> str:
        if self.mode == "min":
            value = -value
        self._scores[trial_id] = value
        if iteration - self._last_perturb[trial_id] < self._interval:
            return CONTINUE
        self._last_perturb[trial_id] = iteration
        ranked = sorted(self._scores, key=self._scores.get)
        k = max(1, int(len(ranked) * self._quantile))
        if len(ranked) < 2 * k:
            return CONTINUE            # population too small to split
        bottom, top = ranked[:k], ranked[-k:]
        if trial_id in bottom and trial_id not in top:
            self._donor_for[trial_id] = self._rng.choice(top)
            return EXPLOIT
        return CONTINUE

    def exploit_target(self, trial_id: str) -> Optional[str]:
        return self._donor_for.get(trial_id)

    def mutate(self, config: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(config)
        for key, spec in self._mutations.items():
            resample = self._rng.random() < self._resample_prob
            cur = out.get(key)
            if callable(spec):
                out[key] = spec()
            elif isinstance(spec, list):
                if resample or cur not in spec:
                    out[key] = self._rng.choice(spec)
                else:  # step to a neighbor (reference behavior)
                    i = spec.index(cur)
                    j = min(len(spec) - 1, max(0, i + self._rng.choice(
                        (-1, 1))))
                    out[key] = spec[j]
            elif (isinstance(spec, tuple) and len(spec) == 2
                  and all(isinstance(b, (int, float)) for b in spec)):
                low, high = spec
                if resample or not isinstance(cur, (int, float)):
                    out[key] = self._rng.uniform(low, high)
                else:
                    out[key] = min(high, max(
                        low, cur * self._rng.choice((0.8, 1.2))))
                if isinstance(low, int) and isinstance(high, int):
                    out[key] = int(round(out[key]))
            else:
                raise ValueError(
                    f"unsupported mutation spec for {key!r}: {spec!r}")
        return out
