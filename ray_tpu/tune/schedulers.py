"""Trial schedulers.

Reference: `python/ray/tune/schedulers/async_hyperband.py` — ASHA: rungs at
grace_period * reduction_factor^k; at each rung a trial continues only if its
result is in the top 1/reduction_factor of results recorded at that rung.
Also `tune/schedulers/pbt.py` (PopulationBasedTraining: bottom-quantile
trials clone a top-quantile trial's checkpoint with perturbed hyperparams)
and `tune/schedulers/median_stopping_rule.py`.
"""

from __future__ import annotations

import math
import random
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional

CONTINUE = "CONTINUE"
STOP = "STOP"
# Scheduler asks the controller to clone a donor trial's checkpoint into
# this trial with a perturbed config (PBT). The controller calls
# `exploit_target(trial_id)` and `mutate(donor_config)` to act on it.
EXPLOIT = "EXPLOIT"
# Scheduler asks the controller to checkpoint + park the trial (release
# its resources) until the scheduler later resumes or stops it via
# `pop_decisions()` — synchronous HyperBand's rung barrier.
PAUSE = "PAUSE"


class FIFOScheduler:
    def on_result(self, trial_id: str, iteration: int, value: float) -> str:
        return CONTINUE


class AsyncHyperBandScheduler:
    def __init__(self, metric: str = None, mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 3):
        self.metric = metric
        self.mode = mode
        self._max_t = max_t
        self._grace = grace_period
        self._rf = reduction_factor
        rungs: List[int] = []
        t = grace_period
        while t < max_t:
            rungs.append(int(t))
            t *= reduction_factor
        self._rungs = rungs                       # ascending milestones
        self._recorded: Dict[int, List[float]] = defaultdict(list)
        self._reached: Dict[str, set] = defaultdict(set)  # trial -> rungs

    def on_result(self, trial_id: str, iteration: int, value: float) -> str:
        if self.mode == "min":
            value = -value
        if iteration >= self._max_t:
            return STOP
        # A trial reporting a coarser iteration cadence may skip past a
        # milestone; evaluate at the highest rung reached but not yet
        # scored for this trial (reference ASHA: `>= milestone`).
        for rung in reversed(self._rungs):
            if iteration >= rung and rung not in self._reached[trial_id]:
                self._reached[trial_id].add(rung)
                recorded = self._recorded[rung]
                recorded.append(value)
                k = max(1, int(math.ceil(len(recorded) / self._rf)))
                cutoff = sorted(recorded, reverse=True)[k - 1]
                if value < cutoff:
                    return STOP
                break
        return CONTINUE


class HyperBandScheduler:
    """Synchronous HyperBand proper (reference:
    `tune/schedulers/hyperband.py:1` HyperBandScheduler), distinct from
    ASHA: trials are grouped into brackets; each bracket runs successive
    halving ROUNDS with a barrier — every live trial in the bracket runs
    to the round's budget, PAUSES, and only when the whole round has
    reported does the bracket promote its top 1/eta and stop the rest.
    The barrier trades ASHA's asynchrony for exact top-k promotion.

    Bracket s (s = s_max..0) admits n_s = ceil((s_max+1)/(s+1) * eta^s)
    trials with initial per-round budget r_s = max_t * eta^(-s); new
    trials fill the highest-s bracket with a free slot.
    """

    def __init__(self, metric: str = None, mode: str = "max",
                 max_t: int = 81, reduction_factor: float = 3):
        self.metric = metric
        self.mode = mode
        self._max_t = max_t
        self._eta = reduction_factor
        # +1e-9: math.log(243, 3) is 4.999...97 — bare int() would drop
        # the most-exploratory bracket for exact-power inputs.
        self._s_max = int(math.log(max_t, reduction_factor) + 1e-9)
        self._brackets: List[_HBBracket] = [
            _HBBracket(s, self._s_max, max_t, reduction_factor)
            for s in range(self._s_max, -1, -1)]
        self._bracket_of: Dict[str, _HBBracket] = {}
        # (resume_ids, stop_ids) accumulated by rung promotions, drained
        # by the controller via pop_decisions().
        self._resume: List[str] = []
        self._stop: List[str] = []

    def _assign(self, trial_id: str) -> "_HBBracket":
        b = self._bracket_of.get(trial_id)
        if b is None:
            b = next((bk for bk in self._brackets if bk.has_room()),
                     self._brackets[-1])
            b.admit(trial_id)
            self._bracket_of[trial_id] = b
        return b

    def on_result(self, trial_id: str, iteration: int, value: float) -> str:
        if self.mode == "min":
            value = -value
        b = self._assign(trial_id)
        decision = b.on_result(trial_id, iteration, value)
        if decision == STOP and b.live and b.round_complete():
            # This trial finishing its full budget may have been the last
            # straggler its bracket's barrier was waiting on.
            keep, drop = b.promote()
            self._resume.extend(keep)
            self._stop.extend(drop)
            return STOP
        if decision == PAUSE and b.round_complete():
            keep, drop = b.promote()
            self._resume.extend(keep)
            self._stop.extend(drop)
            if trial_id in drop:
                self._stop.remove(trial_id)
                return STOP
            if trial_id in keep:
                # This trial survived its own barrier flush; let it keep
                # running instead of a pause/resume round-trip.
                self._resume.remove(trial_id)
                return CONTINUE
        return decision

    def on_trial_remove(self, trial_id: str) -> None:
        """Trial errored/left: drop it so a rung barrier can't wait on a
        trial that will never report."""
        b = self._bracket_of.get(trial_id)
        if b is not None:
            b.remove(trial_id)
            if b.round_complete() and b.live:
                keep, drop = b.promote()
                self._resume.extend(keep)
                self._stop.extend(drop)

    def pop_decisions(self):
        """-> (resume_ids, stop_ids); called by the controller loop."""
        resume, self._resume = self._resume, []
        stop, self._stop = self._stop, []
        return resume, stop

    def flush_barriers(self) -> bool:
        """Force-promote every bracket whose round is complete; True if
        any decision was produced (controller's anti-spin backstop)."""
        produced = False
        for b in self._brackets:
            # Bypass the round-0 fill requirement: nothing is pending or
            # running, so the bracket will never fill further.
            all_paused = bool(b.live) and all(
                t in b.paused for t in b.live)
            if all_paused:
                keep, drop = b.promote()
                self._resume.extend(keep)
                self._stop.extend(drop)
                produced = produced or bool(keep or drop)
        return produced


class _HBBracket:
    def __init__(self, s: int, s_max: int, max_t: int, eta: float):
        self.capacity = int(math.ceil(
            (s_max + 1) / (s + 1) * eta ** s))
        self.r0 = max(1, int(max_t * eta ** (-s)))
        self.max_t = max_t
        self.eta = eta
        self.round = 0
        self.live: List[str] = []
        self.admitted = 0
        self.scores: Dict[str, float] = {}   # this round's reports
        self.paused: set = set()

    def has_room(self) -> bool:
        return self.admitted < self.capacity

    def admit(self, trial_id: str) -> None:
        self.admitted += 1
        self.live.append(trial_id)

    def milestone(self) -> int:
        return min(self.max_t, int(self.r0 * self.eta ** self.round))

    def on_result(self, trial_id: str, iteration: int, value: float) -> str:
        self.scores[trial_id] = value
        if iteration >= self.max_t:
            # Done with its full budget: drop it from the bracket so the
            # rung barrier can't wait on it (it will never pause).
            self.remove(trial_id)
            return STOP
        if iteration >= self.milestone():
            self.paused.add(trial_id)
            return PAUSE
        return CONTINUE

    def round_complete(self) -> bool:
        # Round 0 additionally waits for the bracket to FILL: trials are
        # admitted lazily, so without this the first trial to pause
        # would "win" a one-trial rung. Partial brackets (experiment
        # smaller than capacity) are flushed by flush_barriers() once
        # nothing else can arrive.
        if self.round == 0 and self.admitted < self.capacity:
            return False
        return bool(self.live) and all(
            t in self.paused for t in self.live)

    def remove(self, trial_id: str) -> None:
        if trial_id in self.live:
            self.live.remove(trial_id)
        self.paused.discard(trial_id)
        self.scores.pop(trial_id, None)

    def promote(self):
        """Keep the top 1/eta of this round's reporters, stop the rest;
        advance to the next round."""
        ranked = sorted(self.live, key=lambda t: self.scores.get(
            t, -math.inf), reverse=True)
        k = max(1, int(len(ranked) / self.eta))
        keep, drop = ranked[:k], ranked[k:]
        self.live = list(keep)
        self.paused.clear()
        self.round += 1
        return keep, drop


class MedianStoppingRule:
    """Stop a trial whose best value so far is worse than the median of
    other trials' running averages at a comparable step (reference:
    `tune/schedulers/median_stopping_rule.py`; the Vizier rule)."""

    def __init__(self, metric: str = None, mode: str = "max",
                 grace_period: int = 1, min_samples_required: int = 3):
        self.metric = metric
        self.mode = mode
        self._grace = grace_period
        self._min_samples = min_samples_required
        self._sums: Dict[str, float] = defaultdict(float)
        self._counts: Dict[str, int] = defaultdict(int)
        self._best: Dict[str, float] = {}

    def on_result(self, trial_id: str, iteration: int, value: float) -> str:
        if self.mode == "min":
            value = -value
        self._sums[trial_id] += value
        self._counts[trial_id] += 1
        self._best[trial_id] = max(
            self._best.get(trial_id, -math.inf), value)
        if iteration < self._grace:
            return CONTINUE
        others = [self._sums[t] / self._counts[t]
                  for t in self._sums if t != trial_id]
        if len(others) < self._min_samples:
            return CONTINUE
        median = sorted(others)[len(others) // 2]
        return STOP if self._best[trial_id] < median else CONTINUE


class PopulationBasedTraining:
    """PBT (reference `tune/schedulers/pbt.py`): every
    `perturbation_interval` iterations, a trial in the bottom quantile
    exploits — the controller clones a random top-quantile trial's latest
    checkpoint into it and re-launches with a perturbed config.

    `hyperparam_mutations` maps config key -> list of choices | callable
    () -> value | (low, high) numeric range. On perturb: with
    `resample_probability` draw fresh from the spec, otherwise multiply
    numeric values by 0.8/1.2 (or step to a list neighbor).
    """

    def __init__(self, metric: str = None, mode: str = "max",
                 perturbation_interval: int = 2,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        if not 0 < quantile_fraction <= 0.5:
            raise ValueError("quantile_fraction must be in (0, 0.5]")
        self.metric = metric
        self.mode = mode
        self._interval = perturbation_interval
        self._mutations = hyperparam_mutations or {}
        self._quantile = quantile_fraction
        self._resample_prob = resample_probability
        self._rng = random.Random(seed)
        self._scores: Dict[str, float] = {}          # signed latest value
        self._last_perturb: Dict[str, int] = defaultdict(int)
        self._donor_for: Dict[str, str] = {}

    # ------------------------------------------------------------ protocol
    def on_result(self, trial_id: str, iteration: int, value: float) -> str:
        if self.mode == "min":
            value = -value
        self._scores[trial_id] = value
        if iteration - self._last_perturb[trial_id] < self._interval:
            return CONTINUE
        self._last_perturb[trial_id] = iteration
        ranked = sorted(self._scores, key=self._scores.get)
        k = max(1, int(len(ranked) * self._quantile))
        if len(ranked) < 2 * k:
            return CONTINUE            # population too small to split
        bottom, top = ranked[:k], ranked[-k:]
        if trial_id in bottom and trial_id not in top:
            self._donor_for[trial_id] = self._rng.choice(top)
            return EXPLOIT
        return CONTINUE

    def exploit_target(self, trial_id: str) -> Optional[str]:
        return self._donor_for.get(trial_id)

    def mutate(self, config: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(config)
        for key, spec in self._mutations.items():
            resample = self._rng.random() < self._resample_prob
            cur = out.get(key)
            if callable(spec):
                out[key] = spec()
            elif isinstance(spec, list):
                if resample or cur not in spec:
                    out[key] = self._rng.choice(spec)
                else:  # step to a neighbor (reference behavior)
                    i = spec.index(cur)
                    j = min(len(spec) - 1, max(0, i + self._rng.choice(
                        (-1, 1))))
                    out[key] = spec[j]
            elif (isinstance(spec, tuple) and len(spec) == 2
                  and all(isinstance(b, (int, float)) for b in spec)):
                low, high = spec
                if resample or not isinstance(cur, (int, float)):
                    out[key] = self._rng.uniform(low, high)
                else:
                    out[key] = min(high, max(
                        low, cur * self._rng.choice((0.8, 1.2))))
                if isinstance(low, int) and isinstance(high, int):
                    out[key] = int(round(out[key]))
            else:
                raise ValueError(
                    f"unsupported mutation spec for {key!r}: {spec!r}")
        return out
