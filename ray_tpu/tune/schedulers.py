"""Trial schedulers.

Reference: `python/ray/tune/schedulers/async_hyperband.py` — ASHA: rungs at
grace_period * reduction_factor^k; at each rung a trial continues only if its
result is in the top 1/reduction_factor of results recorded at that rung.
"""

from __future__ import annotations

import math
from collections import defaultdict
from typing import Dict, List

CONTINUE = "CONTINUE"
STOP = "STOP"


class FIFOScheduler:
    def on_result(self, trial_id: str, iteration: int, value: float) -> str:
        return CONTINUE


class AsyncHyperBandScheduler:
    def __init__(self, metric: str = None, mode: str = "max",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 3):
        self.metric = metric
        self.mode = mode
        self._max_t = max_t
        self._grace = grace_period
        self._rf = reduction_factor
        rungs: List[int] = []
        t = grace_period
        while t < max_t:
            rungs.append(int(t))
            t *= reduction_factor
        self._rungs = rungs                       # ascending milestones
        self._recorded: Dict[int, List[float]] = defaultdict(list)
        self._reached: Dict[str, set] = defaultdict(set)  # trial -> rungs

    def on_result(self, trial_id: str, iteration: int, value: float) -> str:
        if self.mode == "min":
            value = -value
        if iteration >= self._max_t:
            return STOP
        # A trial reporting a coarser iteration cadence may skip past a
        # milestone; evaluate at the highest rung reached but not yet
        # scored for this trial (reference ASHA: `>= milestone`).
        for rung in reversed(self._rungs):
            if iteration >= rung and rung not in self._reached[trial_id]:
                self._reached[trial_id].add(rung)
                recorded = self._recorded[rung]
                recorded.append(value)
                k = max(1, int(math.ceil(len(recorded) / self._rf)))
                cutoff = sorted(recorded, reverse=True)[k - 1]
                if value < cutoff:
                    return STOP
                break
        return CONTINUE
