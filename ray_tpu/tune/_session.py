"""Tune trial session: tune.report plumbing inside the trial actor.

Mirrors the train session's queue model (reference
`tune/trainable/function_trainable.py`: the user function runs in a thread;
reports flow through a bounded queue back to the controller's poll loop).
"""

from __future__ import annotations

import os
import queue
import threading
import traceback
from typing import Any, Callable, Dict, Optional

FINISHED = "__finished__"
ERRORED = "__errored__"
REPORT = "__report__"

_session: Optional["_TuneSession"] = None


class _TuneSession:
    def __init__(self, fn: Callable, config: Dict[str, Any],
                 trial_dir: str, checkpoint=None):
        self._fn = fn
        self._config = config
        self.trial_dir = trial_dir
        self.latest_checkpoint = checkpoint
        # maxsize=1 + join(): report() blocks until the controller has
        # consumed the result (reference `_TrainSession`'s bounded queue) —
        # otherwise a fast trial sprints ahead of the driver and its last
        # reported checkpoints are lost if it crashes.
        self._queue: "queue.Queue" = queue.Queue(maxsize=1)
        # Continue numbering past any checkpoints already in the trial
        # dir: a relaunched trial (PBT exploit, restore) must never
        # overwrite checkpoint_000000 — recovery picks the highest index.
        self._counter = 0
        try:
            for d in os.listdir(trial_dir):
                if d.startswith("checkpoint_"):
                    try:
                        idx = int(d.split("_")[1]) + 1
                    except ValueError:
                        continue  # foreign checkpoint naming
                    self._counter = max(self._counter, idx)
        except OSError:
            pass
        self._stop = threading.Event()

    def start(self):
        def _run():
            global _session
            _session = self
            try:
                self._fn(self._config)
                self._queue.put((FINISHED, None, None))
            except _StopTrial:
                self._queue.put((FINISHED, None, None))
            except BaseException as e:  # noqa: BLE001
                self._queue.put((ERRORED,
                                 f"{type(e).__name__}: {e}\n"
                                 f"{traceback.format_exc()}", None))

        threading.Thread(target=_run, daemon=True, name="tune-trial").start()

    def report(self, metrics: Dict[str, Any], checkpoint=None):
        if self._stop.is_set():
            raise _StopTrial()
        ckpt_path = None
        if checkpoint is not None:
            if checkpoint.path.startswith(
                    os.path.abspath(self.trial_dir) + os.sep):
                # Already persisted under this trial (e.g. by a nested
                # trainer's worker session) — no second copy.
                persisted = checkpoint
            else:
                persisted = checkpoint.persist(
                    self.trial_dir, name=f"checkpoint_{self._counter:06d}")
            self.latest_checkpoint = persisted
            ckpt_path = persisted.path
        self._counter += 1
        self._queue.put((REPORT, metrics, ckpt_path))
        self._queue.join()   # returns once next_result() handed it over

    def next_result(self, timeout: Optional[float] = None):
        try:
            item = self._queue.get(timeout=timeout)
        except queue.Empty:
            return None
        self._queue.task_done()
        return item

    def request_stop(self):
        self._stop.set()


class _StopTrial(BaseException):
    """Raised inside the user fn at the next report() after a STOP."""


def get_session() -> Optional[_TuneSession]:
    return _session


def report(metrics: Dict[str, Any], checkpoint=None) -> None:
    s = get_session()
    if s is None:
        raise RuntimeError("tune.report() called outside a trial")
    s.report(metrics, checkpoint)


def get_checkpoint():
    s = get_session()
    return s.latest_checkpoint if s else None
