"""ResNet family — the vision flagship (BASELINE.md ladder step 2:
data-parallel ResNet-50 ImageNet).

TPU-first notes: NHWC layout (TPU conv native), bf16 compute with fp32
batch-norm statistics, `flax.linen` modules (convs have per-layer shapes,
so the stacked-scan trick used for the Llama decoder does not apply).
Reference analog: the reference trains ResNet via torchvision through its
generic worker group (`release/air_tests/air_benchmarks/`); the model
itself is net-new here.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Dict, Sequence, Tuple

import jax
import jax.numpy as jnp

import flax.linen as nn


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Tuple[int, ...] = (3, 4, 6, 3)
    num_classes: int = 1000
    width: int = 64
    bottleneck: bool = True
    dtype: Any = jnp.bfloat16

    @staticmethod
    def resnet18(**kw) -> "ResNetConfig":
        return ResNetConfig(**{**dict(stage_sizes=(2, 2, 2, 2),
                                      bottleneck=False), **kw})

    @staticmethod
    def resnet50(**kw) -> "ResNetConfig":
        return ResNetConfig(**{**dict(stage_sizes=(3, 4, 6, 3),
                                      bottleneck=True), **kw})

    @staticmethod
    def tiny(**kw) -> "ResNetConfig":
        """CPU-test size: 8x8 inputs train in milliseconds."""
        return ResNetConfig(**{**dict(stage_sizes=(1, 1), num_classes=10,
                                      width=8, bottleneck=False), **kw})

    def num_params(self, params) -> int:
        return sum(x.size for x in jax.tree.leaves(params))


class _Block(nn.Module):
    filters: int
    strides: Tuple[int, int]
    bottleneck: bool
    dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        conv = partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = partial(nn.BatchNorm, use_running_average=not train,
                       momentum=0.9, epsilon=1e-5, dtype=jnp.float32)
        residual = x
        if self.bottleneck:
            y = conv(self.filters, (1, 1))(x)
            y = nn.relu(norm()(y))
            y = conv(self.filters, (3, 3), self.strides)(y)
            y = nn.relu(norm()(y))
            y = conv(self.filters * 4, (1, 1))(y)
            y = norm(scale_init=nn.initializers.zeros)(y)
            out_filters = self.filters * 4
        else:
            y = conv(self.filters, (3, 3), self.strides)(x)
            y = nn.relu(norm()(y))
            y = conv(self.filters, (3, 3))(y)
            y = norm(scale_init=nn.initializers.zeros)(y)
            out_filters = self.filters
        if residual.shape != y.shape:
            residual = conv(out_filters, (1, 1), self.strides,
                            name="shortcut")(residual)
            residual = norm(name="shortcut_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    config: ResNetConfig

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = self.config
        x = x.astype(c.dtype)
        x = nn.Conv(c.width, (7, 7), (2, 2), use_bias=False,
                    dtype=c.dtype, name="stem")(x)
        x = nn.relu(nn.BatchNorm(use_running_average=not train,
                                 momentum=0.9, dtype=jnp.float32)(x))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, stage_size in enumerate(c.stage_sizes):
            for j in range(stage_size):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = _Block(c.width * (2 ** i), strides, c.bottleneck,
                           c.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))                      # global avg pool
        x = nn.Dense(c.num_classes, dtype=jnp.float32)(x)
        return x


def init_params(config: ResNetConfig, key: jax.Array,
                image_size: int = 224) -> Dict[str, Any]:
    """Returns {"params", "batch_stats"} variables."""
    model = ResNet(config)
    dummy = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    return model.init(key, dummy, train=True)


def forward(variables: Dict[str, Any], images: jax.Array,
            config: ResNetConfig, train: bool = False):
    """images [B, H, W, 3] -> logits [B, num_classes]. In train mode also
    returns updated batch_stats."""
    model = ResNet(config)
    if train:
        return model.apply(variables, images, train=True,
                           mutable=["batch_stats"])
    return model.apply(variables, images, train=False)


def loss_fn(variables: Dict[str, Any], batch: Dict[str, jax.Array],
            config: ResNetConfig):
    """Softmax cross-entropy; returns (loss, new_batch_stats)."""
    logits, updates = forward(variables, batch["image"], config, train=True)
    labels = batch["label"]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    return nll.mean(), updates["batch_stats"]


def make_train_step(config: ResNetConfig, optimizer) -> Callable:
    """Data-parallel jitted step over (variables, opt_state, batch):
    params replicated, batch sharded over the data axis (GSPMD inserts the
    gradient psum)."""

    def step(variables, opt_state, batch):
        def wrapped(params):
            return loss_fn({"params": params,
                            "batch_stats": variables["batch_stats"]},
                           batch, config)

        (loss, new_stats), grads = jax.value_and_grad(
            wrapped, has_aux=True)(variables["params"])
        updates, new_opt = optimizer.update(grads, opt_state,
                                            variables["params"])
        import optax

        new_params = optax.apply_updates(variables["params"], updates)
        return ({"params": new_params, "batch_stats": new_stats},
                new_opt, loss)

    return jax.jit(step, donate_argnums=(0, 1))
