"""Llama-2-family decoder — the flagship model, TPU-first.

Design (not a port — the reference has no in-repo model zoo; its Llama runs
arrive via HF/DeepSpeed through the generic worker group, e.g.
`train/examples/deepspeed/deepspeed_torch_trainer.py`):

- Pure-functional: params are a pytree of arrays; no module framework in the
  hot path, so pjit sharding rules are plain pytrees too (parallel/sharding.py).
- Layers are STACKED along a leading [n_layers, ...] axis and iterated with
  `lax.scan` — one compiled layer body instead of n_layers inlined copies:
  small XLA programs, fast compiles, and the idiomatic substrate for
  pipeline parallelism (a stage = a slice of the stacked tree).
- bfloat16 activations/matmuls (MXU-native), fp32 params + softmax/norm
  accumulators.
- GQA (n_kv_heads <= n_heads), RoPE, RMSNorm, SwiGLU — Llama-2/3 shapes.
- Attention is pluggable: "xla" einsum (fused by XLA), "flash"
  (ray_tpu.ops pallas kernel on TPU), or "ring" (context parallel over a
  mesh axis) — selected by config or overridden per call.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    hidden_dim: int = 11008
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16   # activation/matmul dtype
    param_dtype: Any = jnp.float32
    attn_impl: str = "xla"      # "xla" | "flash" | "ring"
    # False | True (full per-layer jax.checkpoint) | "dots" (checkpoint
    # with dots-saveable policy: keep matmul outputs, recompute the rest)
    remat: Any = False
    tie_embeddings: bool = False
    # Mixture-of-Experts FFN (0 = dense). Experts shard over the mesh
    # "expert" axis (SURVEY §2.7 EP; see models/moe.py).
    n_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @staticmethod
    def llama2_7b(**overrides) -> "LlamaConfig":
        return LlamaConfig(**{**dict(
            vocab_size=32000, dim=4096, n_layers=32, n_heads=32,
            n_kv_heads=32, hidden_dim=11008, max_seq_len=4096), **overrides})

    @staticmethod
    def llama3_8b(**overrides) -> "LlamaConfig":
        return LlamaConfig(**{**dict(
            vocab_size=128256, dim=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, hidden_dim=14336, max_seq_len=8192,
            rope_theta=500000.0), **overrides})

    @staticmethod
    def tiny(**overrides) -> "LlamaConfig":
        """Test-size config: runs on CPU in milliseconds."""
        return LlamaConfig(**{**dict(
            vocab_size=256, dim=64, n_layers=2, n_heads=4, n_kv_heads=2,
            hidden_dim=128, max_seq_len=128), **overrides})

    def num_params(self) -> int:
        d, h, v = self.dim, self.hidden_dim, self.vocab_size
        ffn = (self.n_experts * 3 * d * h + d * self.n_experts
               if self.n_experts else 3 * d * h)
        per_layer = (self.dim * self.head_dim * self.n_heads      # wq
                     + 2 * self.dim * self.head_dim * self.n_kv_heads  # wk,wv
                     + self.dim * self.dim                         # wo
                     + ffn                                         # ffn/moe
                     + 2 * d)                                      # norms
        out_head = 0 if self.tie_embeddings else d * v
        return v * d + self.n_layers * per_layer + d + out_head


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------

def init_params(config: LlamaConfig, key: jax.Array) -> Dict[str, Any]:
    """Stacked-layer parameter pytree."""
    c = config
    k_embed, k_layers, k_out = jax.random.split(key, 3)
    initializer = jax.nn.initializers.normal(0.02)

    def dense(key, shape):
        return initializer(key, shape, c.param_dtype)

    kd = c.head_dim
    lk = jax.random.split(k_layers, 8)

    def stacked(key, shape):
        return dense(key, (c.n_layers, *shape))

    params = {
        "embed": dense(k_embed, (c.vocab_size, c.dim)),
        "layers": {
            "attn_norm": jnp.ones((c.n_layers, c.dim), c.param_dtype),
            "wq": stacked(lk[0], (c.dim, c.n_heads * kd)),
            "wk": stacked(lk[1], (c.dim, c.n_kv_heads * kd)),
            "wv": stacked(lk[2], (c.dim, c.n_kv_heads * kd)),
            "wo": stacked(lk[3], (c.n_heads * kd, c.dim)),
            "ffn_norm": jnp.ones((c.n_layers, c.dim), c.param_dtype),
            **(
                {
                    "router": stacked(lk[7], (c.dim, c.n_experts)),
                    "w_gate": stacked(lk[4], (c.n_experts, c.dim,
                                              c.hidden_dim)),
                    "w_up": stacked(lk[5], (c.n_experts, c.dim,
                                            c.hidden_dim)),
                    "w_down": stacked(lk[6], (c.n_experts, c.hidden_dim,
                                              c.dim)),
                } if c.n_experts else {
                    "w_gate": stacked(lk[4], (c.dim, c.hidden_dim)),
                    "w_up": stacked(lk[5], (c.dim, c.hidden_dim)),
                    "w_down": stacked(lk[6], (c.hidden_dim, c.dim)),
                }
            ),
        },
        "norm_f": jnp.ones((c.dim,), c.param_dtype),
    }
    if not c.tie_embeddings:
        params["lm_head"] = dense(k_out, (c.dim, c.vocab_size))
    return params


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------

def quantize_weights_int8(params: Dict[str, Any]) -> Dict[str, Any]:
    """Weight-only int8 quantization for the decode path (serving):
    per-output-channel symmetric scales on every large matmul weight
    (attention/FFN projections + lm_head). Decode is HBM-bandwidth-bound
    — each generated token reads every weight once — so halving weight
    bytes converts ~directly into decode throughput; dequant happens
    per-layer inside the scan (int8 travels HBM→VMEM, bf16 never
    materializes). Norms and the embedding gather stay in bf16.

    Returns a params-shaped pytree where each quantized weight `w`
    becomes the pair `w_q` (int8) + `w_s` (f32 scales); consumed by
    decode_step/prefill via `_weight`.
    """
    def quant(w):
        w32 = w.astype(jnp.float32)
        scale = jnp.max(jnp.abs(w32), axis=-2, keepdims=True) / 127.0
        scale = jnp.maximum(scale, 1e-8)
        q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
        return q, scale

    out: Dict[str, Any] = {"embed": params["embed"],
                           "norm_f": params["norm_f"]}
    layers = dict(params["layers"])
    qlayers: Dict[str, Any] = {
        "attn_norm": layers["attn_norm"], "ffn_norm": layers["ffn_norm"]}
    for name in ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down"):
        q, s = quant(layers[name])
        qlayers[name + "_q"] = q
        qlayers[name + "_s"] = s
    if "router" in layers:
        qlayers["router"] = layers["router"]
    out["layers"] = qlayers
    if "lm_head" in params:
        q, s = quant(params["lm_head"])
        out["lm_head_q"] = q
        out["lm_head_s"] = s
    return out


def _weight(p: Dict[str, Any], name: str, dtype) -> jax.Array:
    """Fetch a matmul weight in compute dtype, dequantizing int8+scale
    pairs in place (fused by XLA into the consuming dot's operand)."""
    q = p.get(name + "_q")
    if q is not None:
        return (q.astype(dtype) * p[name + "_s"].astype(dtype))
    return p[name].astype(dtype)


def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    orig_dtype = x.dtype
    x32 = x.astype(jnp.float32)
    rrms = lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return ((x32 * rrms).astype(orig_dtype)
            * weight.astype(orig_dtype))


def rope_freqs(head_dim: int, max_len: int, theta: float) -> Tuple[jax.Array, jax.Array]:
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                           / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)                       # [S, D/2]
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: [B, S, H, D]; cos/sin: [S, D/2]."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    cos = cos[None, :, None, :]
    sin = sin[None, :, None, :]
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def xla_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                  causal: bool = True,
                  positions: Optional[jax.Array] = None) -> jax.Array:
    """Reference attention, [B, S, H, D] layout; fp32 softmax accumulator.
    XLA fuses this well on TPU for short/medium sequences; flash/ring
    kernels take over for long context."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        s_q, s_k = q.shape[1], k.shape[1]
        if positions is None:
            q_pos = jnp.arange(s_q)[:, None]
        else:
            q_pos = positions[:, None]
        mask = q_pos >= jnp.arange(s_k)[None, :]
        scores = jnp.where(mask[None, None, :, :], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _repeat_kv(x: jax.Array, n_rep: int) -> jax.Array:
    if n_rep == 1:
        return x
    return jnp.repeat(x, n_rep, axis=2)


def _get_attention_fn(impl):
    if callable(impl):
        # e.g. parallel.context_parallel_attention(mesh): ring attention
        # with the mesh/axis already bound.
        return impl
    if impl == "flash":
        from ray_tpu.ops.attention import flash_attention

        return flash_attention
    if impl == "ring":
        from ray_tpu.ops.ring_attention import ring_attention

        return ring_attention
    return xla_attention


def _make_embed_lookup(vocab: int, dtype_name: str):
    """Embedding gather whose BACKWARD is a one-hot matmul, not a scatter.

    XLA lowers the gather's transpose to a serialized scatter-add on TPU —
    hundreds of ms at [V, D] scale; the MXU does the same reduction as a
    [V, B*S] x [B*S, D] matmul in milliseconds. Static (vocab, dtype) live
    in this closure: custom_vjp residuals must be JAX arrays only.
    """

    @jax.custom_vjp
    def lookup(embed, tokens):
        return embed[tokens]

    def fwd(embed, tokens):
        return embed[tokens], tokens

    def bwd(tokens, g):
        flat_tok = tokens.reshape(-1)
        flat_g = g.reshape(flat_tok.shape[0], -1)
        onehot = jax.nn.one_hot(flat_tok, vocab, dtype=flat_g.dtype, axis=0)
        d_embed = jax.lax.dot_general(
            onehot, flat_g, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return d_embed.astype(dtype_name), None

    lookup.defvjp(fwd, bwd)
    return lookup


_EMBED_LOOKUP_CACHE: Dict[Tuple[int, str], Any] = {}


def embed_lookup(embed: jax.Array, tokens: jax.Array) -> jax.Array:
    key = (embed.shape[0], jnp.dtype(embed.dtype).name)
    fn = _EMBED_LOOKUP_CACHE.get(key)
    if fn is None:
        fn = _EMBED_LOOKUP_CACHE[key] = _make_embed_lookup(*key)
    return fn(embed, tokens)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def _layer(config: LlamaConfig, cos, sin, attn_fn, x, layer_params):
    c = config
    p = layer_params
    B, S, _ = x.shape
    kd = c.head_dim

    h = rms_norm(x, p["attn_norm"], c.norm_eps)
    q = (h @ p["wq"].astype(c.dtype)).reshape(B, S, c.n_heads, kd)
    k = (h @ p["wk"].astype(c.dtype)).reshape(B, S, c.n_kv_heads, kd)
    v = (h @ p["wv"].astype(c.dtype)).reshape(B, S, c.n_kv_heads, kd)
    q = apply_rope(q, cos[:S], sin[:S])
    k = apply_rope(k, cos[:S], sin[:S])
    k = _repeat_kv(k, c.n_heads // c.n_kv_heads)
    v = _repeat_kv(v, c.n_heads // c.n_kv_heads)
    attn = attn_fn(q, k, v, causal=True)
    x = x + attn.reshape(B, S, -1) @ p["wo"].astype(c.dtype)

    h = rms_norm(x, p["ffn_norm"], c.norm_eps)
    if c.n_experts:
        from ray_tpu.models.moe import MoEConfig, moe_layer

        mcfg = MoEConfig(
            dim=c.dim, hidden_dim=c.hidden_dim, n_experts=c.n_experts,
            top_k=c.moe_top_k, capacity_factor=c.moe_capacity_factor,
            dtype=c.dtype)
        delta, aux = moe_layer(h, {
            "router": p["router"], "w_gate": p["w_gate"],
            "w_up": p["w_up"], "w_down": p["w_down"]}, mcfg)
        return x + delta, aux
    gate = jax.nn.silu(h @ p["w_gate"].astype(c.dtype))
    up = h @ p["w_up"].astype(c.dtype)
    x = x + (gate * up) @ p["w_down"].astype(c.dtype)
    return x, jnp.zeros((), jnp.float32)


def forward_hidden(params: Dict[str, Any], tokens: jax.Array,
                   config: LlamaConfig,
                   attn_impl: Optional[str] = None):
    """Trunk only: tokens [B, S] -> (hidden [B, S, D], aux). The fused
    training loss consumes hidden states directly so the [B, S, V]
    logits tensor never materializes (ops/fused_loss.py); `forward`
    adds the lm_head matmul on top."""
    c = config
    impl = attn_impl or c.attn_impl
    attn_fn = _get_attention_fn(impl)
    cos, sin = rope_freqs(c.head_dim, c.max_seq_len, c.rope_theta)

    x = embed_lookup(params["embed"].astype(c.dtype), tokens)

    layer_fn = partial(_layer, c, cos, sin, attn_fn)
    if isinstance(c.remat, str) and c.remat != "dots":
        raise ValueError(
            f"remat={c.remat!r}: expected False, True, or 'dots'")
    if c.remat == "dots":
        # Keep matmul outputs, recompute only cheap elementwise ops on
        # the backward — ~5x less recompute than full remat at a modest
        # HBM premium (policy: dots_with_no_batch_dims_saveable).
        layer_fn = jax.checkpoint(
            layer_fn,
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    elif c.remat:
        layer_fn = jax.checkpoint(layer_fn)

    def scan_body(x, layer_params):
        return layer_fn(x, layer_params)

    x, aux = lax.scan(scan_body, x, params["layers"])
    x = rms_norm(x, params["norm_f"], c.norm_eps)
    return x, jnp.sum(aux)


def forward(params: Dict[str, Any], tokens: jax.Array,
            config: LlamaConfig,
            attn_impl: Optional[str] = None,
            return_aux: bool = False):
    """tokens [B, S] int32 -> logits [B, S, V] (or (logits, aux_loss)
    with return_aux — the MoE router load-balance term)."""
    c = config
    x, aux = forward_hidden(params, tokens, config, attn_impl)
    head = (params["embed"].T if c.tie_embeddings else params["lm_head"])
    # bf16 matmul on the MXU (fp32 here costs ~4x), fp32 accumulation for
    # the softmax/loss that follows.
    logits = jax.lax.dot_general(
        x, head.astype(c.dtype), (((2,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    if return_aux:
        return logits, aux
    return logits


def loss_fn(params: Dict[str, Any], batch: Dict[str, jax.Array],
            config: LlamaConfig,
            attn_impl: Optional[str] = None,
            fused: Optional[bool] = None) -> jax.Array:
    """Next-token cross-entropy. batch: tokens [B, S] (+ optional mask).

    ``fused`` (default: env RAY_TPU_FUSED_LOSS, on unless =0) streams
    the lm_head matmul + logsumexp over vocab blocks so the [B, S, V]
    logits tensor never round-trips to HBM (ops/fused_loss.py) —
    identical numerics, fraction of the loss-stage memory traffic."""
    import os

    tokens = batch["tokens"]
    targets = tokens[:, 1:]
    if fused is None:
        fused = os.environ.get("RAY_TPU_FUSED_LOSS", "1") != "0"
    if fused:
        from ray_tpu.ops.fused_loss import blockwise_xent

        hidden, aux = forward_hidden(params, tokens[:, :-1], config,
                                     attn_impl)
        c = config
        head = (params["embed"].T if c.tie_embeddings
                else params["lm_head"]).astype(c.dtype)
        b, s, d = hidden.shape
        nll = blockwise_xent(hidden.reshape(b * s, d), head,
                             targets.reshape(-1)).reshape(b, s)
    else:
        logits, aux = forward(params, tokens[:, :-1], config, attn_impl,
                              return_aux=True)
        # NLL via logsumexp - target_logit: one [B,S,V] reduction instead
        # of a materialized log_softmax plus gather.
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, targets[..., None],
                                  axis=-1)[..., 0]
        nll = lse - tgt
    mask = batch.get("mask")
    if mask is not None:
        m = mask[:, 1:].astype(jnp.float32)
        return (nll * m).sum() / jnp.maximum(m.sum(), 1.0) + aux
    return nll.mean() + aux


def flops_per_token(config: LlamaConfig, seq_len: int) -> float:
    """Approximate training FLOPs/token (fwd+bwd ~ 6*N + attention)."""
    n = config.num_params()
    attn = 12 * config.n_layers * config.dim * seq_len  # score+value matmuls
    return 6.0 * n + attn


# ---------------------------------------------------------------------------
# Inference: KV-cache decode + generation (the Serve-on-TPU path)
# ---------------------------------------------------------------------------

def init_kv_cache(config: LlamaConfig, batch_size: int,
                  max_len: Optional[int] = None) -> Dict[str, jax.Array]:
    """Stacked per-layer cache [L, B, S, n_kv, head_dim] (bf16)."""
    c = config
    S = max_len or c.max_seq_len
    shape = (c.n_layers, batch_size, S, c.n_kv_heads, c.head_dim)
    return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype)}


def _decode_attention(q, k_cache, v_cache, pos):
    """q [B,1,H,D]; caches [B,S,kvH,D]; attends to positions <= pos."""
    B, S, KVH, D = k_cache.shape
    H = q.shape[2]
    rep = H // KVH
    k = jnp.repeat(k_cache, rep, axis=2)
    v = jnp.repeat(v_cache, rep, axis=2)
    scale = 1.0 / math.sqrt(D)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    mask = jnp.arange(S)[None, None, None, :] <= pos[:, None, None, None]
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def decode_step(params: Dict[str, Any], cache: Dict[str, jax.Array],
                tokens: jax.Array, positions: jax.Array,
                config: LlamaConfig,
                active: Optional[jax.Array] = None):
    """One incremental token: tokens [B] int32 at `positions` [B].
    Returns (logits [B, V], updated cache). Jittable; scan over layers.

    ``active`` [B] bool (optional) slot-masks the KV write: inactive
    rows keep their cache untouched (the write index is pushed out of
    bounds, where scatter drops it) so a continuous-batching engine can
    run dead slots through the same fixed-shape program without
    corrupting rows a later prefill has already claimed. Logits for
    inactive rows are garbage by construction — callers ignore them.
    """
    if config.n_experts:
        raise NotImplementedError(
            "KV-cache decode for MoE configs is not implemented yet; "
            "use forward() for scoring")
    c = config
    cos, sin = rope_freqs(c.head_dim, cache["k"].shape[2], c.rope_theta)
    x = embed_lookup(params["embed"].astype(c.dtype), tokens[:, None])
    B = tokens.shape[0]
    kd = c.head_dim
    pos_cos = cos[positions][:, None, :]       # [B, 1, D/2]
    pos_sin = sin[positions][:, None, :]

    def rope1(t):  # [B, 1, H, D]
        t1, t2 = jnp.split(t.astype(jnp.float32), 2, axis=-1)
        pc = pos_cos[:, :, None, :]
        ps = pos_sin[:, :, None, :]
        return jnp.concatenate(
            [t1 * pc - t2 * ps, t2 * pc + t1 * ps], axis=-1).astype(t.dtype)

    def layer(carry, inputs):
        x = carry
        p, k_cache, v_cache = inputs
        h = rms_norm(x, p["attn_norm"], c.norm_eps)
        q = (h @ _weight(p, "wq", c.dtype)).reshape(B, 1, c.n_heads, kd)
        k = (h @ _weight(p, "wk", c.dtype)).reshape(B, 1, c.n_kv_heads, kd)
        v = (h @ _weight(p, "wv", c.dtype)).reshape(B, 1, c.n_kv_heads, kd)
        q, k = rope1(q), rope1(k)
        # Write this token's k/v at its position. Inactive slots write at
        # S (out of bounds -> dropped), leaving their rows untouched.
        bidx = jnp.arange(B)
        if active is None:
            write_pos = positions
        else:
            write_pos = jnp.where(active, positions, k_cache.shape[1])
        k_cache = k_cache.at[bidx, write_pos].set(k[:, 0])
        v_cache = v_cache.at[bidx, write_pos].set(v[:, 0])
        attn = _decode_attention(q, k_cache, v_cache, positions)
        x = x + attn.reshape(B, 1, -1) @ _weight(p, "wo", c.dtype)
        h = rms_norm(x, p["ffn_norm"], c.norm_eps)
        gate = jax.nn.silu(h @ _weight(p, "w_gate", c.dtype))
        up = h @ _weight(p, "w_up", c.dtype)
        x = x + (gate * up) @ _weight(p, "w_down", c.dtype)
        return x, (k_cache, v_cache)

    x, (new_k, new_v) = lax.scan(
        layer, x, (params["layers"], cache["k"], cache["v"]))
    x = rms_norm(x, params["norm_f"], c.norm_eps)
    head = lm_head_weight(params, c)
    logits = jax.lax.dot_general(
        x[:, 0], head, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return logits, {"k": new_k, "v": new_v}


def init_paged_kv_cache(config: LlamaConfig, num_blocks: int,
                        block_size: int) -> Dict[str, jax.Array]:
    """Paged cache: a fixed POOL of KV blocks shared by all sequences,
    [L, num_blocks, block_size, n_kv, head_dim] (bf16). A sequence owns
    a *block table* — the list of physical block ids covering its
    logical positions — instead of a dense [S] stripe, so short and long
    requests share HBM instead of each reserving max_seq rows
    (PagedAttention, arXiv:2309.06180)."""
    c = config
    shape = (c.n_layers, num_blocks, block_size, c.n_kv_heads, c.head_dim)
    return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype)}


def decode_step_paged(params: Dict[str, Any], pools: Dict[str, jax.Array],
                      block_tables: jax.Array, tokens: jax.Array,
                      positions: jax.Array, config: LlamaConfig,
                      active: Optional[jax.Array] = None):
    """One incremental token against the paged pool: tokens [B] at
    `positions` [B], block_tables [B, max_blocks] int32 mapping each
    sequence's logical block index -> physical pool block. Returns
    (logits [B, V], updated pools).

    Token-exact with `decode_step` on a dense cache holding the same
    logical contents: the gather assembles each sequence's dense
    [S_pad] view (S_pad = max_blocks * block_size), the write lands at
    (table[pos // bs], pos % bs), and the same masked softmax drops
    padding/stale rows to exact zeros. ``active`` masks the pool write
    by pushing the physical block index out of bounds (scatter drops
    it), mirroring the dense path's out-of-bounds position trick.
    """
    if config.n_experts:
        raise NotImplementedError(
            "paged KV-cache decode for MoE configs is not implemented")
    c = config
    NB, bs = pools["k"].shape[1], pools["k"].shape[2]
    max_blocks = block_tables.shape[1]
    S_pad = max_blocks * bs
    cos, sin = rope_freqs(c.head_dim, S_pad, c.rope_theta)
    x = embed_lookup(params["embed"].astype(c.dtype), tokens[:, None])
    B = tokens.shape[0]
    kd = c.head_dim
    pos_cos = cos[positions][:, None, :]
    pos_sin = sin[positions][:, None, :]

    def rope1(t):  # [B, 1, H, D]
        t1, t2 = jnp.split(t.astype(jnp.float32), 2, axis=-1)
        pc = pos_cos[:, :, None, :]
        ps = pos_sin[:, :, None, :]
        return jnp.concatenate(
            [t1 * pc - t2 * ps, t2 * pc + t1 * ps], axis=-1).astype(t.dtype)

    bidx = jnp.arange(B)
    phys = block_tables[bidx, positions // bs]
    if active is not None:
        phys = jnp.where(active, phys, NB)     # OOB scatter -> dropped
    off = positions % bs

    def layer(carry, inputs):
        x = carry
        p, k_pool, v_pool = inputs
        h = rms_norm(x, p["attn_norm"], c.norm_eps)
        q = (h @ _weight(p, "wq", c.dtype)).reshape(B, 1, c.n_heads, kd)
        k = (h @ _weight(p, "wk", c.dtype)).reshape(B, 1, c.n_kv_heads, kd)
        v = (h @ _weight(p, "wv", c.dtype)).reshape(B, 1, c.n_kv_heads, kd)
        q, k = rope1(q), rope1(k)
        k_pool = k_pool.at[phys, off].set(k[:, 0].astype(k_pool.dtype))
        v_pool = v_pool.at[phys, off].set(v[:, 0].astype(v_pool.dtype))
        # Per-sequence dense view via the block table (gather AFTER the
        # write so this token's own row is attendable at `positions`).
        k_dense = k_pool[block_tables].reshape(B, S_pad, c.n_kv_heads, kd)
        v_dense = v_pool[block_tables].reshape(B, S_pad, c.n_kv_heads, kd)
        attn = _decode_attention(q, k_dense, v_dense, positions)
        x = x + attn.reshape(B, 1, -1) @ _weight(p, "wo", c.dtype)
        h = rms_norm(x, p["ffn_norm"], c.norm_eps)
        gate = jax.nn.silu(h @ _weight(p, "w_gate", c.dtype))
        up = h @ _weight(p, "w_up", c.dtype)
        x = x + (gate * up) @ _weight(p, "w_down", c.dtype)
        return x, (k_pool, v_pool)

    x, (new_k, new_v) = lax.scan(
        layer, x, (params["layers"], pools["k"], pools["v"]))
    x = rms_norm(x, params["norm_f"], c.norm_eps)
    head = lm_head_weight(params, c)
    logits = jax.lax.dot_general(
        x[:, 0], head, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return logits, {"k": new_k, "v": new_v}


def verify_kv_paged(params: Dict[str, Any], pools: Dict[str, jax.Array],
                    block_tables: jax.Array, tokens: jax.Array,
                    positions: jax.Array, config: LlamaConfig,
                    active: Optional[jax.Array] = None):
    """K-token verify step for speculative decoding: tokens [B, K] are
    consumed in parallel, token j of row b at absolute position
    ``positions[b] + j``. Returns (logits [B, K, V], updated pools).

    Row j's logits are the target model's distribution for the token
    FOLLOWING input j — exactly what ``decode_step_paged`` would produce
    after consuming inputs 0..j one at a time, because every op here is
    row-independent (per-position matmuls, per-query masked softmax):
    running K queries through one program instead of K programs changes
    batching, not values. The engine exploits this for draft
    verification: accept the longest prefix where the target's argmax
    agrees with the draft, and greedy parity holds by construction.

    All K KV writes scatter before the dense gather, so input j attends
    to inputs i < j (their positions pass the ``key_pos <= pos + j``
    mask) and never to inputs i > j. Rejected inputs leave stale rows
    past the accepted position — the same stale-rows-overwritten-
    before-attended invariant every other path in this file relies on.
    ``active`` masks writes by pushing the physical block id out of
    bounds, mirroring ``decode_step_paged``.
    """
    if config.n_experts:
        raise NotImplementedError(
            "paged KV-cache verify for MoE configs is not implemented")
    c = config
    NB, bs = pools["k"].shape[1], pools["k"].shape[2]
    max_blocks = block_tables.shape[1]
    S_pad = max_blocks * bs
    cos, sin = rope_freqs(c.head_dim, S_pad, c.rope_theta)
    B, K = tokens.shape
    kd = c.head_dim
    # Absolute position of every query; clamped so inactive rows with
    # garbage positions still index rope/scatter safely (their writes
    # are dropped and their logits ignored).
    qpos = jnp.minimum(positions[:, None] + jnp.arange(K)[None, :],
                       S_pad - 1)                            # [B, K]
    pos_cos = cos[qpos]                                      # [B, K, D/2]
    pos_sin = sin[qpos]

    x = embed_lookup(params["embed"].astype(c.dtype), tokens)

    def ropek(t):  # [B, K, H, D] rotated by per-(row, query) position
        t1, t2 = jnp.split(t.astype(jnp.float32), 2, axis=-1)
        pc = pos_cos[:, :, None, :]
        ps = pos_sin[:, :, None, :]
        return jnp.concatenate(
            [t1 * pc - t2 * ps, t2 * pc + t1 * ps], axis=-1).astype(t.dtype)

    phys = block_tables[jnp.arange(B)[:, None], qpos // bs]  # [B, K]
    if active is not None:
        phys = jnp.where(active[:, None], phys, NB)  # OOB scatter drop
    off = qpos % bs
    scale = 1.0 / math.sqrt(kd)

    def layer(carry, inputs):
        x = carry
        p, k_pool, v_pool = inputs
        h = rms_norm(x, p["attn_norm"], c.norm_eps)
        q = (h @ _weight(p, "wq", c.dtype)).reshape(B, K, c.n_heads, kd)
        k = (h @ _weight(p, "wk", c.dtype)).reshape(B, K, c.n_kv_heads, kd)
        v = (h @ _weight(p, "wv", c.dtype)).reshape(B, K, c.n_kv_heads, kd)
        q, k = ropek(q), ropek(k)
        k_pool = k_pool.at[phys, off].set(k.astype(k_pool.dtype))
        v_pool = v_pool.at[phys, off].set(v.astype(v_pool.dtype))
        # Dense per-sequence view gathered AFTER all K writes: query j
        # sees queries i < j through the position mask below.
        k_dense = k_pool[block_tables].reshape(B, S_pad, c.n_kv_heads, kd)
        v_dense = v_pool[block_tables].reshape(B, S_pad, c.n_kv_heads, kd)
        rep = c.n_heads // c.n_kv_heads
        kr = _repeat_kv(k_dense.astype(c.dtype), rep)
        vr = _repeat_kv(v_dense.astype(c.dtype), rep)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, kr).astype(
            jnp.float32) * scale
        mask = (qpos[:, None, :, None]
                >= jnp.arange(S_pad)[None, None, None, :])
        scores = jnp.where(mask, scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(c.dtype)
        attn = jnp.einsum("bhqk,bkhd->bqhd", probs, vr)
        x = x + attn.reshape(B, K, -1) @ _weight(p, "wo", c.dtype)
        h = rms_norm(x, p["ffn_norm"], c.norm_eps)
        gate = jax.nn.silu(h @ _weight(p, "w_gate", c.dtype))
        up = h @ _weight(p, "w_up", c.dtype)
        x = x + (gate * up) @ _weight(p, "w_down", c.dtype)
        return x, (k_pool, v_pool)

    x, (new_k, new_v) = lax.scan(
        layer, x, (params["layers"], pools["k"], pools["v"]))
    x = rms_norm(x, params["norm_f"], c.norm_eps)
    head = lm_head_weight(params, c)
    logits = jax.lax.dot_general(
        x, head, (((2,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                  # [B, K, V]
    return logits, {"k": new_k, "v": new_v}


def prefill_kv_paged(params: Dict[str, Any], tokens: jax.Array,
                     start: jax.Array, hist_k: jax.Array,
                     hist_v: jax.Array, config: LlamaConfig):
    """Suffix prefill with history: the prefix-cache hit path. tokens
    [1, Pb] sit at absolute positions start..start+Pb-1; hist_k/hist_v
    [L, S_pad, n_kv, head_dim] hold the cached prefix KV (rows >= start
    are don't-care — masked, then overwritten by the suffix). Returns
    (normed hidden [1, Pb, D], suffix ks/vs [L, 1, Pb, n_kv, head_dim]).

    With start=0 and zero history this reduces exactly to `prefill_kv`
    over a padded bucket: real queries attend only real keys (mask
    key_pos <= start + i), so bit-identical KV and logits — the engine
    uses ONE program family for both fresh and prefix-hit admission.
    """
    c = config
    B, Pb = tokens.shape
    S_pad = hist_k.shape[1]
    cos, sin = rope_freqs(c.head_dim, S_pad, c.rope_theta)
    qpos = start + jnp.arange(Pb)
    kd = c.head_dim

    x = embed_lookup(params["embed"].astype(c.dtype), tokens)

    def scan_body(x, inputs):
        p, hk, hv = inputs
        h = rms_norm(x, p["attn_norm"], c.norm_eps)
        q = (h @ _weight(p, "wq", c.dtype)).reshape(B, Pb, c.n_heads, kd)
        k = (h @ _weight(p, "wk", c.dtype)).reshape(B, Pb, c.n_kv_heads, kd)
        v = (h @ _weight(p, "wv", c.dtype)).reshape(B, Pb, c.n_kv_heads, kd)
        q = apply_rope(q, cos[qpos], sin[qpos])
        k = apply_rope(k, cos[qpos], sin[qpos])
        keys = lax.dynamic_update_slice(hk, k[0].astype(hk.dtype),
                                        (start, 0, 0))
        vals = lax.dynamic_update_slice(hv, v[0].astype(hv.dtype),
                                        (start, 0, 0))
        rep = c.n_heads // c.n_kv_heads
        attn = xla_attention(
            q, _repeat_kv(keys[None].astype(c.dtype), rep),
            _repeat_kv(vals[None].astype(c.dtype), rep),
            causal=True, positions=qpos)
        x = x + attn.reshape(B, Pb, -1) @ _weight(p, "wo", c.dtype)
        h = rms_norm(x, p["ffn_norm"], c.norm_eps)
        gate = jax.nn.silu(h @ _weight(p, "w_gate", c.dtype))
        up = h @ _weight(p, "w_up", c.dtype)
        x = x + (gate * up) @ _weight(p, "w_down", c.dtype)
        return x, (k, v)

    x, (ks, vs) = lax.scan(scan_body, x, (params["layers"],
                                          hist_k, hist_v))
    x = rms_norm(x, params["norm_f"], c.norm_eps)
    return x, ks, vs


def lm_head_weight(params: Dict[str, Any], config: LlamaConfig) -> jax.Array:
    """Output-projection matrix [D, V] in compute dtype (tied or not)."""
    if config.tie_embeddings:
        return params["embed"].T.astype(config.dtype)
    return _weight(params, "lm_head", config.dtype)


def prefill_kv(params: Dict[str, Any], tokens: jax.Array,
               config: LlamaConfig):
    """Prefill trunk: prompt [B, P] -> (normed hidden [B, P, D],
    per-layer pre-repeat ks/vs [L, B, P, n_kv, head_dim]).

    Shared by `prefill` (whole-cache fill) and the continuous-batching
    engine's insert-at-slot path (serve/llm/engine.py) so both produce
    bit-identical KV for the same prompt."""
    c = config
    B, P = tokens.shape
    cos, sin = rope_freqs(c.head_dim, P, c.rope_theta)
    attn_fn = _get_attention_fn(c.attn_impl)
    kd = c.head_dim

    x = embed_lookup(params["embed"].astype(c.dtype), tokens)

    def scan_body(x, p):
        h = rms_norm(x, p["attn_norm"], c.norm_eps)
        q = (h @ _weight(p, "wq", c.dtype)).reshape(B, P, c.n_heads, kd)
        k = (h @ _weight(p, "wk", c.dtype)).reshape(B, P, c.n_kv_heads, kd)
        v = (h @ _weight(p, "wv", c.dtype)).reshape(B, P, c.n_kv_heads, kd)
        q = apply_rope(q, cos[:P], sin[:P])
        k = apply_rope(k, cos[:P], sin[:P])
        rep = c.n_heads // c.n_kv_heads
        attn = attn_fn(q, _repeat_kv(k, rep), _repeat_kv(v, rep),
                       causal=True)
        x = x + attn.reshape(B, P, -1) @ _weight(p, "wo", c.dtype)
        h = rms_norm(x, p["ffn_norm"], c.norm_eps)
        gate = jax.nn.silu(h @ _weight(p, "w_gate", c.dtype))
        up = h @ _weight(p, "w_up", c.dtype)
        x = x + (gate * up) @ _weight(p, "w_down", c.dtype)
        return x, (k, v)

    x, (ks, vs) = lax.scan(scan_body, x, params["layers"])
    x = rms_norm(x, params["norm_f"], c.norm_eps)
    return x, ks, vs


def prefill(params: Dict[str, Any], tokens: jax.Array,
            config: LlamaConfig, max_len: Optional[int] = None):
    """Fill the cache from a prompt [B, P] in ONE batched forward pass
    (all prompt positions hit the MXU together; the per-layer pre-repeat
    k/v come out of the layer scan and land in the cache with a single
    dynamic_update_slice). Returns (last-token logits [B, V], cache)."""
    c = config
    B, P = tokens.shape
    S = max_len or c.max_seq_len

    x, ks, vs = prefill_kv(params, tokens, config)
    head = lm_head_weight(params, c)
    logits = jax.lax.dot_general(
        x[:, -1], head, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    cache = init_kv_cache(c, B, S)
    cache = {
        "k": lax.dynamic_update_slice(
            cache["k"], ks.astype(c.dtype), (0, 0, 0, 0, 0)),
        "v": lax.dynamic_update_slice(
            cache["v"], vs.astype(c.dtype), (0, 0, 0, 0, 0)),
    }
    return logits, cache


def generate(params: Dict[str, Any], prompt: jax.Array,
             config: LlamaConfig, max_new_tokens: int,
             temperature: float = 0.0,
             rng: Optional[jax.Array] = None) -> jax.Array:
    """Greedy (or temperature) generation, fully jit-compatible:
    prompt [B, P] -> [B, max_new_tokens]."""
    B, P = prompt.shape
    logits, cache = prefill(params, prompt, config,
                            max_len=P + max_new_tokens)
    rng = rng if rng is not None else jax.random.key(0)

    def sample(logits, key):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return jax.random.categorical(
            key, logits / temperature).astype(jnp.int32)

    def body(carry, i):
        cache, logits, key = carry
        key, sub = jax.random.split(key)
        tok = sample(logits, sub)
        pos = jnp.full((B,), P, jnp.int32) + i
        logits, cache = decode_step(params, cache, tok, pos, config)
        return (cache, logits, key), tok

    (_, _, _), toks = lax.scan(
        body, (cache, logits, rng), jnp.arange(max_new_tokens))
    return toks.T  # [B, max_new_tokens]
