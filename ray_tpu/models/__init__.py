from ray_tpu.models import llama
from ray_tpu.models.llama import LlamaConfig

__all__ = ["llama", "LlamaConfig"]
