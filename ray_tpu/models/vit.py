"""Vision Transformer — the attention-based vision family.

TPU-first notes: patchify is one strided conv (NHWC, maps to the MXU as
an unrolled matmul), the encoder is pre-LN transformer blocks in bf16
with fp32 layernorm statistics, and the sequence is short enough
(e.g. 197 for ViT-B/16 at 224^2) that plain XLA attention is optimal —
no flash kernel needed below the [S, S] memory wall. `flax.linen`
modules like the ResNet family (per-layer conv shapes preclude the
Llama stacked-scan trick only for the patch stem; encoder blocks share
shapes and could scan, but at ViT depths XLA's unrolled fusion wins).

Reference analog: the reference trains torchvision/timm ViTs through its
generic worker group; the model itself is net-new here (same stance as
`models/resnet.py`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

import flax.linen as nn


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    num_classes: int = 1000
    dim: int = 768
    depth: int = 12
    n_heads: int = 12
    mlp_dim: int = 3072
    dropout: float = 0.0
    dtype: Any = jnp.bfloat16

    @staticmethod
    def vit_b16(**kw) -> "ViTConfig":
        return ViTConfig(**kw)

    @staticmethod
    def vit_s16(**kw) -> "ViTConfig":
        return ViTConfig(**{**dict(dim=384, depth=12, n_heads=6,
                                   mlp_dim=1536), **kw})

    @staticmethod
    def tiny(**kw) -> "ViTConfig":
        """CPU-test size: 16x16 inputs train in milliseconds."""
        return ViTConfig(**{**dict(image_size=16, patch_size=4,
                                   num_classes=10, dim=32, depth=2,
                                   n_heads=4, mlp_dim=64), **kw})

    def __post_init__(self):
        if self.image_size % self.patch_size:
            raise ValueError(
                f"image_size={self.image_size} must be divisible by "
                f"patch_size={self.patch_size}")

    @property
    def seq_len(self) -> int:
        return (self.image_size // self.patch_size) ** 2 + 1  # + [CLS]


class _Encoder(nn.Module):
    config: ViTConfig

    @nn.compact
    def __call__(self, x, train: bool):
        c = self.config
        drop = not train or c.dropout == 0.0
        for _ in range(c.depth):
            # Pre-LN attention block, fp32 norm stats, bf16 matmuls.
            h = nn.LayerNorm(dtype=jnp.float32)(x).astype(c.dtype)
            h = nn.MultiHeadDotProductAttention(
                num_heads=c.n_heads, dtype=c.dtype,
                deterministic=drop, dropout_rate=c.dropout)(h, h)
            h = nn.Dropout(c.dropout, deterministic=drop)(h)
            x = x + h
            h = nn.LayerNorm(dtype=jnp.float32)(x).astype(c.dtype)
            h = nn.Dense(c.mlp_dim, dtype=c.dtype)(h)
            h = nn.gelu(h)
            h = nn.Dropout(c.dropout, deterministic=drop)(h)
            h = nn.Dense(c.dim, dtype=c.dtype)(h)
            h = nn.Dropout(c.dropout, deterministic=drop)(h)
            x = x + h
        return nn.LayerNorm(dtype=jnp.float32)(x)


class ViT(nn.Module):
    config: ViTConfig

    @nn.compact
    def __call__(self, images, train: bool = True):
        """images [B, H, W, C] (NHWC) -> logits [B, num_classes]."""
        c = self.config
        B = images.shape[0]
        x = nn.Conv(c.dim, (c.patch_size, c.patch_size),
                    strides=(c.patch_size, c.patch_size),
                    padding="VALID", dtype=c.dtype, name="patch_embed")(
            images.astype(c.dtype))
        x = x.reshape(B, -1, c.dim)                       # [B, S-1, dim]
        cls = self.param("cls", nn.initializers.zeros, (1, 1, c.dim))
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (B, 1, c.dim)).astype(c.dtype), x], 1)
        pos = self.param("pos_embed",
                         nn.initializers.normal(stddev=0.02),
                         (1, c.seq_len, c.dim))
        x = x + pos.astype(c.dtype)
        x = nn.Dropout(c.dropout,
                       deterministic=not train or c.dropout == 0.0)(x)
        x = _Encoder(c)(x, train)
        return nn.Dense(c.num_classes, dtype=jnp.float32,
                        name="head")(x[:, 0].astype(jnp.float32))


def init_params(config: ViTConfig, key: jax.Array):
    model = ViT(config)
    dummy = jnp.zeros(
        (1, config.image_size, config.image_size, 3), jnp.float32)
    return model.init({"params": key}, dummy, train=False)


def forward(params, images, config: ViTConfig, train: bool = False,
            rngs: Optional[Dict] = None):
    if train and config.dropout > 0.0 and (
            rngs is None or "dropout" not in rngs):
        raise ValueError(
            "training with dropout > 0 requires "
            "rngs={'dropout': jax.random.key(...)}")
    return ViT(config).apply(params, images, train=train,
                             rngs=rngs or {})


def loss_fn(params, batch: Dict[str, jax.Array], config: ViTConfig,
            rngs: Optional[Dict] = None) -> Tuple[jax.Array, jax.Array]:
    """Cross-entropy; batch: {"images" [B,H,W,C], "labels" [B]}.
    Returns (loss, accuracy)."""
    logits = forward(params, batch["images"], config, train=True,
                     rngs=rngs)
    labels = batch["labels"].astype(jnp.int32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    tgt = jnp.take_along_axis(logits, labels[:, None], -1)[:, 0]
    loss = (lse - tgt).mean()
    acc = (jnp.argmax(logits, -1) == labels).mean()
    return loss, acc


def num_params(params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))
