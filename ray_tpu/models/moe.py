"""Mixture-of-Experts layer — expert parallelism over a mesh axis.

SURVEY §2.7: the reference has NO in-repo expert parallelism (delegated
to user libraries); this is the net-new TPU-native implementation. The
design is the GShard/Switch dispatch pattern rather than a scatter loop:

  router logits -> top-k experts per token -> capacity-masked one-hot
  dispatch tensor -> three einsums (dispatch, expert FFN, combine).

Everything is dense, fixed-shape einsums, so XLA tiles them onto the MXU
and — when the expert dimension is sharded over a mesh "expert" axis
while tokens are data-sharded — inserts the all-to-alls over ICI
automatically. No hand-written collectives; the mesh does EP.

Sharding recipe (see `moe_param_specs`): experts [E, ...] sharded
P("expert", ...); token tensors data-sharded; jit with those out/in
shardings and GSPMD places dispatch/combine all-to-alls on the ICI ring.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    dim: int
    hidden_dim: int          # per-expert FFN width
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_weight: float = 1e-2
    dtype: Any = jnp.bfloat16

    def capacity(self, n_tokens: int) -> int:
        cap = int(self.capacity_factor * n_tokens * self.top_k
                  / self.n_experts)
        return max(cap, self.top_k)


def init_moe_params(cfg: MoEConfig, key: jax.Array,
                    param_dtype=jnp.float32) -> Dict[str, jax.Array]:
    kr, kg, ku, kd = jax.random.split(key, 4)
    d, f, e = cfg.dim, cfg.hidden_dim, cfg.n_experts
    scale = d ** -0.5
    return {
        "router": (jax.random.normal(kr, (d, e)) * scale).astype(param_dtype),
        "w_gate": (jax.random.normal(kg, (e, d, f)) * scale).astype(param_dtype),
        "w_up": (jax.random.normal(ku, (e, d, f)) * scale).astype(param_dtype),
        "w_down": (jax.random.normal(kd, (e, f, d)) * (f ** -0.5)).astype(param_dtype),
    }


def moe_param_specs() -> Dict[str, P]:
    """PartitionSpecs placing experts on the "expert" mesh axis (router
    stays replicated — it is tiny and every token needs it)."""
    return {
        "router": P(),
        "w_gate": P("expert", None, None),
        "w_up": P("expert", None, None),
        "w_down": P("expert", None, None),
    }


def _top_k_dispatch(probs: jax.Array, k: int, capacity: int,
                    out_dtype) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """probs [T, E] fp32 -> (dispatch [T, E, C], combine [T, E, C],
    raw_assign [k, T, E]).

    Capacity enforcement: tokens beyond an expert's C slots are dropped
    (their combine weight is 0 → they pass through the residual only),
    keeping every shape static for XLA. ALL position bookkeeping is
    int32 — counts beyond 256 would silently round in bf16 and collide
    capacity slots.
    """
    T, E = probs.shape
    topk_probs, topk_idx = jax.lax.top_k(probs, k)          # [T, k]
    # For each of the k choices: one-hot expert assignment [k, T, E].
    assign_raw = jax.nn.one_hot(topk_idx.T, E, dtype=jnp.int32)
    # Position of each token within its expert's queue, counted across
    # choice-major order so k=0 assignments fill first.
    flat = assign_raw.reshape(k * T, E)
    pos = (jnp.cumsum(flat, axis=0) - flat).reshape(k, T, E)
    assign = assign_raw * (pos < capacity)
    slot = jax.nn.one_hot(jnp.sum(pos * assign, axis=-1), capacity,
                          dtype=jnp.int32)                   # [k, T, C]
    # dispatch[t, e, c] = 1 iff token t occupies slot c of expert e.
    dispatch = jnp.einsum("kte,ktc->tec", assign, slot).astype(out_dtype)
    weight = jnp.sum(assign.astype(jnp.float32)
                     * topk_probs.T[..., None], axis=0)      # [T, E]
    combine = dispatch * weight[..., None].astype(out_dtype)
    return dispatch, combine, assign_raw


def moe_layer(x: jax.Array, params: Dict[str, jax.Array], cfg: MoEConfig
              ) -> Tuple[jax.Array, jax.Array]:
    """x [B, S, D] -> (out [B, S, D], aux_loss scalar).

    aux_loss is the standard load-balancing term (Switch eq. 4):
    E * sum_e f_e * p_e, minimized when routing is uniform.
    """
    B, S, D = x.shape
    T = B * S
    C = cfg.capacity(T)
    xt = x.reshape(T, D)

    logits = (xt @ params["router"].astype(cfg.dtype)).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)                  # [T, E]
    dispatch, combine, assign_raw = _top_k_dispatch(
        probs, cfg.top_k, C, cfg.dtype)

    # Load-balance aux loss (Switch eq. 4) from the PRE-capacity
    # assignment: computed post-drop it would saturate at C/T exactly
    # when an expert overloads — the regime the loss exists to fix.
    frac_tokens = jnp.mean(assign_raw.astype(jnp.float32),
                           axis=(0, 1)) * cfg.top_k          # [E]
    frac_probs = jnp.mean(probs, axis=0)                     # [E]
    aux = cfg.n_experts * jnp.sum(frac_tokens * frac_probs) \
        * cfg.router_aux_weight

    # Dispatch -> per-expert FFN -> combine: three MXU einsums; with
    # experts sharded over the mesh "expert" axis these become the EP
    # all-to-alls.
    expert_in = jnp.einsum("tec,td->ecd", dispatch, xt)      # [E, C, D]
    gate = jax.nn.silu(jnp.einsum(
        "ecd,edf->ecf", expert_in, params["w_gate"].astype(cfg.dtype)))
    up = jnp.einsum("ecd,edf->ecf", expert_in,
                    params["w_up"].astype(cfg.dtype))
    h = gate * up
    expert_out = jnp.einsum("ecf,efd->ecd", h,
                            params["w_down"].astype(cfg.dtype))
    out = jnp.einsum("tec,ecd->td", combine, expert_out)
    return out.reshape(B, S, D), aux
