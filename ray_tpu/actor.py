"""@ray_tpu.remote on classes — actors (reference: `python/ray/actor.py`)."""

from __future__ import annotations

import asyncio
import inspect
from typing import Any, Dict, List, Optional

import cloudpickle

_VALID_ACTOR_OPTIONS = {
    "num_cpus", "num_tpus", "resources", "memory", "accelerator_type",
    "max_restarts", "max_task_retries", "max_concurrency", "name",
    "namespace", "lifetime", "get_if_exists", "scheduling_strategy",
    "runtime_env", "concurrency_groups", "_labels",
}


def method(**options):
    """@ray_tpu.method decorator for per-method options
    (reference: `actor.py:53` `@ray.method(num_returns=...)`)."""

    def decorator(fn):
        fn.__ray_tpu_method_options__ = options
        return fn

    return decorator


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str,
                 options: Optional[Dict[str, Any]] = None):
        self._handle = handle
        self._name = name
        self._options = dict(options or {})

    def remote(self, *args, **kwargs):
        from ray_tpu._private.worker import global_worker

        w = global_worker()
        refs = w.submit_actor_task(
            self._handle._actor_id, self._name, args, kwargs, self._options,
            max_task_retries=self._handle._max_task_retries)
        nr = self._options.get("num_returns", 1)
        if nr == 0:
            return None
        if nr == 1 or isinstance(nr, str):
            # "dynamic"/"streaming" return the single generator ref.
            return refs[0]
        return refs

    def options(self, **options) -> "ActorMethod":
        return ActorMethod(self._handle, self._name,
                           {**self._options, **options})

    def bind(self, *args, **kwargs):
        """Lazy DAG node (reference `actor.py` bind); compose with
        ray_tpu.dag.InputNode and experimental_compile()."""
        from ray_tpu.dag import ClassMethodNode

        return ClassMethodNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor method {self._name} cannot be called directly; use "
            f".{self._name}.remote()")


class ActorHandle:
    def __init__(self, actor_id: bytes, class_name: str = "Actor",
                 max_task_retries: int = 0,
                 method_options: Optional[Dict[str, Dict]] = None):
        self._actor_id = actor_id
        self._class_name = class_name
        self._max_task_retries = max_task_retries
        self._method_options = method_options or {}
        self._gc_registered = False
        from ray_tpu._private import worker as worker_mod

        w = worker_mod.global_worker_or_none()
        if w is not None:
            w.actor_handles.add_ref(actor_id)
            self._gc_registered = True

    def __del__(self):
        if not self._gc_registered:
            return
        try:
            from ray_tpu._private import worker as worker_mod

            w = worker_mod.global_worker_or_none()
            if w is not None:
                w.actor_handles.remove_ref(self._actor_id)
        except BaseException:
            pass

    def __getattr__(self, name: str) -> ActorMethod:
        # Real attributes resolve via __dict__ first; only dunders must not
        # fall through to method synthesis (pickle/copy probe them).
        if name.startswith("__"):
            raise AttributeError(name)
        return ActorMethod(self, name, self._method_options.get(name))

    @property
    def _id(self) -> bytes:
        return self._actor_id

    def _actor_id_hex(self) -> str:
        return self._actor_id.hex()

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"

    def __hash__(self):
        return hash(self._actor_id)

    def __eq__(self, other):
        return isinstance(other, ActorHandle) and other._actor_id == self._actor_id


def reduce_actor_handle(handle: ActorHandle):
    from ray_tpu._private import worker as worker_mod

    w = worker_mod.global_worker_or_none()
    if w is not None:
        # Handle escapes this process: pin the actor (conservative stand-in
        # for the reference's distributed handle counting).
        w.actor_handles.mark_shared(handle._actor_id)
    return (_rehydrate_handle, (handle._actor_id, handle._class_name,
                                handle._max_task_retries,
                                handle._method_options))


def _rehydrate_handle(actor_id, class_name, max_task_retries, method_options):
    return ActorHandle(actor_id, class_name, max_task_retries, method_options)


class ActorClass:
    def __init__(self, cls: type, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = dict(options or {})
        for key in self._options:
            if key not in _VALID_ACTOR_OPTIONS:
                raise ValueError(
                    f"invalid option {key!r} for an actor; valid: "
                    f"{sorted(_VALID_ACTOR_OPTIONS)}")
        self._pickled: Optional[bytes] = None
        self.__name__ = cls.__name__

    def _collect_method_options(self) -> Dict[str, Dict]:
        out = {}
        for name, fn in inspect.getmembers(self._cls, callable):
            opts = getattr(fn, "__ray_tpu_method_options__", None)
            if opts:
                out[name] = opts
        return out

    def _is_async(self) -> bool:
        return any(
            asyncio.iscoroutinefunction(fn)
            for _, fn in inspect.getmembers(self._cls, callable))

    def remote(self, *args, **kwargs) -> ActorHandle:
        from ray_tpu._private.worker import global_worker

        w = global_worker()
        if self._pickled is None:
            self._pickled = cloudpickle.dumps(self._cls)
        options = dict(self._options)
        options["is_async"] = self._is_async()
        handle = w.create_actor(self._pickled, self.__name__, args, kwargs,
                                options)
        handle._max_task_retries = options.get("max_task_retries", 0)
        handle._method_options = self._collect_method_options()
        return handle

    def remote_many(self, count: int, *args, **kwargs) -> List[ActorHandle]:
        """Create ``count`` identical actors via ONE batched GCS
        registration round-trip — the fleet-bring-up path (a collective
        group's members, a serve deployment's replicas).  Named actors
        cannot be batched: names must be unique."""
        from ray_tpu._private.worker import global_worker

        if self._options.get("name"):
            raise ValueError(
                "remote_many cannot create named actors (names must be "
                "unique); use .options(name=...).remote() per actor")
        if count < 1:
            raise ValueError(f"count must be >= 1, got {count}")
        w = global_worker()
        if self._pickled is None:
            self._pickled = cloudpickle.dumps(self._cls)
        options = dict(self._options)
        options["is_async"] = self._is_async()
        handles = w.create_actors(self._pickled, self.__name__, count,
                                  args, kwargs, options)
        method_options = self._collect_method_options()
        for handle in handles:
            handle._max_task_retries = options.get("max_task_retries", 0)
            handle._method_options = method_options
        return handles

    def options(self, **options) -> "ActorClass":
        clone = ActorClass(self._cls, {**self._options, **options})
        clone._pickled = self._pickled
        return clone

    def bind(self, *args, **kwargs):
        raise NotImplementedError(
            "ActorClass.bind is not supported: create the actor with "
            ".remote() and bind its methods (actor.method.bind(...))")

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self.__name__} cannot be instantiated directly; "
            f"use {self.__name__}.remote()")
